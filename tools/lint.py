#!/usr/bin/env python3
"""Dependency-free linter: the style tier of `make check`.

The reference gates commits on jsl + jsstyle (Makefile:24-36); this is
the same idea for a stdlib-only environment: every file must parse,
carry no unused imports, no tabs, no trailing whitespace, and no lines
over 79 columns.  Exit status 1 on any finding.  The contract tier
above this one is tools/zkanalyze.py (`make analyze`).

`--fix` rewrites the mechanical findings in place (trailing
whitespace, tabs -> 4 spaces) with an AST-equality guard: a fix that
would change program behavior (whitespace inside a string literal)
is refused and reported instead of applied.

Usage-detection notes (kept in sync with tests/test_analyze.py's
lint drive-by units): names referenced only inside f-string
interpolations and format specs count as used; so do names inside
*quoted* annotations (parsed as expressions, so TYPE_CHECKING-only
imports need no noqa); so do names exported via ``__all__`` —
including ``__all__ += [...]`` augmented extensions.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

MAX_LINE = 79


def _imports(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                yield node.lineno, a.asname or a.name.split('.')[0]
        elif isinstance(node, ast.ImportFrom):
            if node.module == '__future__':
                continue
            for a in node.names:
                if a.name != '*':
                    yield node.lineno, a.asname or a.name


def _names_in_expr_string(value: str) -> set[str]:
    """Names inside a quoted annotation ('os.PathLike', 'list[Span]')
    — parsed as an expression, so string-only forward references
    count as usage and TYPE_CHECKING imports need no noqa."""
    try:
        tree = ast.parse(value, mode='eval')
    except SyntaxError:
        return set()
    return {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}


def _used_names(tree: ast.AST) -> set[str]:
    used: set[str] = set()
    annotations: list[ast.expr] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            # covers plain loads AND f-string interpolations/format
            # specs: FormattedValue bodies are real expressions, so
            # a name used only inside f'{mod.thing}' is a usage
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
        elif isinstance(node, ast.arg):
            if node.annotation is not None:
                annotations.append(node.annotation)
        elif isinstance(node, (ast.FunctionDef,
                               ast.AsyncFunctionDef)):
            if node.returns is not None:
                annotations.append(node.returns)
        elif isinstance(node, ast.AnnAssign):
            annotations.append(node.annotation)
    for annot in annotations:
        for node in ast.walk(annot):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                used |= _names_in_expr_string(node.value)
    return used


def _all_exports(tree: ast.AST) -> set[str]:
    """Strings exported via ``__all__`` — plain assignment, annotated
    assignment, and ``__all__ += [...]`` extensions all count, so an
    export-only import is never flagged as unused."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == '__all__'
                   for t in targets):
            continue
        if node.value is None:
            continue
        for const in ast.walk(node.value):
            if (isinstance(const, ast.Constant)
                    and isinstance(const.value, str)):
                out.add(const.value)
    return out


def _fix_text(text: str) -> str:
    lines = text.split('\n')
    return '\n'.join(line.rstrip().replace('\t', ' ' * 4)
                     if line != line.rstrip() or '\t' in line
                     else line for line in lines)


def fix_file(path: Path) -> str | None:
    """Rewrite trailing whitespace / tabs in place.  Returns a status
    message, or None when the file needed nothing.  Refuses (and
    reports) when the rewrite would change the AST — whitespace
    inside a multiline string is program data, not style."""
    try:
        text = path.read_text()
    except OSError as e:
        return '%s: cannot read: %s' % (path, e)
    fixed = _fix_text(text)
    if fixed == text:
        return None
    try:
        before = ast.dump(ast.parse(text))
        after = ast.dump(ast.parse(fixed))
    except SyntaxError:
        return ('%s: NOT fixed (does not parse; fix the syntax '
                'error first)' % (path,))
    if before != after:
        return ('%s: NOT fixed (whitespace/tab lives inside a '
                'string literal; change it by hand if intended)'
                % (path,))
    path.write_text(fixed)
    return '%s: fixed' % (path,)


def lint_file(path: Path) -> list[str]:
    problems: list[str] = []
    try:
        text = path.read_text()
    except OSError as e:
        return ['%s: cannot read: %s' % (path, e)]
    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as e:
        return ['%s:%s: syntax error: %s' % (path, e.lineno, e.msg)]

    if path.name != '__init__.py':  # __init__ imports are re-exports
        used = _used_names(tree)
        used |= _all_exports(tree)
        # Names referenced only in docstrings count as used; other
        # string literals (log messages, error text) do not get to
        # mask a dead import.
        for node in ast.walk(tree):
            if isinstance(node, (ast.Module, ast.ClassDef,
                                 ast.FunctionDef, ast.AsyncFunctionDef)):
                doc = ast.get_docstring(node, clean=False)
                if doc:
                    used.update(doc.split())
        src_lines = text.splitlines()
        for lineno, name in _imports(tree):
            if name not in used and not name.startswith('_'):
                # escape hatch shared with the line-length check
                if 'noqa' in src_lines[lineno - 1]:
                    continue
                problems.append('%s:%d: unused import %r'
                                % (path, lineno, name))

    for i, line in enumerate(text.splitlines(), 1):
        if '\t' in line:
            problems.append('%s:%d: tab character' % (path, i))
        if line != line.rstrip():
            problems.append('%s:%d: trailing whitespace' % (path, i))
        if len(line) > MAX_LINE and 'noqa' not in line:
            problems.append('%s:%d: line too long (%d > %d)'
                            % (path, i, len(line), MAX_LINE))
    return problems


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    fix = '--fix' in argv
    if fix:
        argv.remove('--fix')
    targets: list[Path] = []
    for arg in argv or ['.']:
        p = Path(arg)
        if p.is_dir():
            targets.extend(sorted(p.rglob('*.py')))
        else:
            targets.append(p)
    targets = [t for t in targets if '__pycache__' not in t.parts]
    if fix:
        nfixed = 0
        for t in targets:
            msg = fix_file(t)
            if msg is not None:
                print(msg)
                nfixed += msg.endswith(': fixed')
        print('%d file(s) rewritten' % (nfixed,))
    problems: list[str] = []
    for t in targets:
        problems.extend(lint_file(t))
    for p in problems:
        print(p)
    print('%d file(s) checked, %d problem(s)'
          % (len(targets), len(problems)))
    return 1 if problems else 0


if __name__ == '__main__':
    sys.exit(main())
