"""Pallas-vs-jnp crossover sweep for the wire pipeline step.

Times ``wire_pipeline_step_pallas`` (the fused Mosaic kernel) against
``wire_pipeline_step`` (pure jnp/lax) across fleet shapes on the
default JAX device (the real TPU under the driver), and prints one
JSON line per cell — the measured basis for the shape-based
auto-dispatch in ops/pipeline.py (VERDICT r2 item 3).

No readback happens until every cell is timed: on a tunneled remote
TPU the first readback permanently degrades dispatch, so correctness
gates run at the end.

Usage: python tools/sweep_pallas.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

FRAME = 104           # 4-byte prefix + 16-byte header + 84-byte body
REPEATS = 20


def fleet(B: int, frames: int, seed: int = 7):
    rng = np.random.RandomState(seed)
    L = frames * FRAME
    v = np.zeros((B, frames, FRAME), np.uint8)

    def be(field, width, out):
        shifts = np.arange(8 * (width - 1), -1, -8, dtype=np.int64)
        out[...] = ((field[..., None] >> shifts) & 0xFF).astype(np.uint8)

    be(np.full((B, frames), FRAME - 4, np.int64), 4, v[:, :, 0:4])
    be(rng.randint(1, 1 << 20, (B, frames)).astype(np.int64), 4,
       v[:, :, 4:8])
    be(rng.randint(1, 1 << 40, (B, frames)).astype(np.int64), 8,
       v[:, :, 8:16])
    v[:, :, 20:] = rng.randint(0, 256, (B, frames, FRAME - 20),
                               dtype=np.uint8)
    return v.reshape(B, L), np.full((B,), L, np.int32)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument('--quick', action='store_true')
    ap.add_argument('--block-rows', type=int, default=128)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from zkstream_tpu.ops.pipeline import (
        wire_pipeline_step,
        wire_pipeline_step_pallas,
    )

    shapes = [(256, 8), (256, 64), (2048, 8), (2048, 64),
              (8192, 64), (32768, 8), (32768, 64)]
    if args.quick:
        shapes = [(2048, 64), (32768, 64)]

    cells = []
    for B, F in shapes:
        buf, lens = fleet(B, F)
        jb, jl = jnp.asarray(buf), jnp.asarray(lens)
        total = int(lens.sum())
        row = {'B': B, 'frames': F, 'mib': round(total / 2**20, 1),
               'backend': jax.default_backend()}
        for name, fn in (
                ('pallas', lambda b, l, F=F: wire_pipeline_step_pallas(
                    b, l, max_frames=F, block_rows=args.block_rows)),
                ('jnp', lambda b, l, F=F: wire_pipeline_step(
                    b, l, max_frames=F))):
            try:
                step = jax.jit(fn)
                out = step(jb, jl)
                jax.block_until_ready(out)
            except Exception as e:
                row[name] = None
                row[name + '_err'] = repr(e)[:80]
                continue
            dts = []
            for _ in range(3):
                t0 = time.perf_counter()
                leaves = [step(jb, jl).n_frames
                          for _ in range(REPEATS)]
                jax.block_until_ready(leaves)
                dts.append((time.perf_counter() - t0) / REPEATS)
            row[name] = round(total / min(dts) / 2**20, 0)
            cells.append((row, name, out, B * F))
        if row.get('pallas') and row.get('jnp'):
            row['winner'] = ('pallas' if row['pallas'] > row['jnp']
                             else 'jnp')
            row['ratio'] = round(row['pallas'] / row['jnp'], 2)
        print(json.dumps(row), flush=True)
    # correctness gates last (readback poisons remote dispatch)
    for row, name, out, want in cells:
        got = int(np.asarray(out.n_frames).sum())
        assert got == want, (row, name, got, want)
    print('# all decode gates passed', file=sys.stderr)


if __name__ == '__main__':
    main()
