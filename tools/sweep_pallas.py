"""Pallas-vs-jnp crossover sweep for the wire pipeline step.

Times ``wire_pipeline_step_pallas`` (the fused Mosaic kernel) against
``wire_pipeline_step`` (pure jnp/lax) across fleet shapes on the
default JAX device (the real TPU under the driver), and prints one
JSON line per cell — the measured basis for the shape-based
auto-dispatch in ops/pipeline.py (VERDICT r2 item 3).

No readback happens until every cell is timed: on a tunneled remote
TPU the first readback permanently degrades dispatch, so correctness
gates run at the end.

Usage: python tools/sweep_pallas.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

FRAME = 104           # 4-byte prefix + 16-byte header + 84-byte body
REPEATS = 20


def fleet(B: int, frames: int, seed: int = 7):
    rng = np.random.RandomState(seed)
    L = frames * FRAME
    v = np.zeros((B, frames, FRAME), np.uint8)

    def be(field, width, out):
        shifts = np.arange(8 * (width - 1), -1, -8, dtype=np.int64)
        out[...] = ((field[..., None] >> shifts) & 0xFF).astype(np.uint8)

    be(np.full((B, frames), FRAME - 4, np.int64), 4, v[:, :, 0:4])
    be(rng.randint(1, 1 << 20, (B, frames)).astype(np.int64), 4,
       v[:, :, 4:8])
    be(rng.randint(1, 1 << 40, (B, frames)).astype(np.int64), 8,
       v[:, :, 8:16])
    v[:, :, 20:] = rng.randint(0, 256, (B, frames, FRAME - 20),
                               dtype=np.uint8)
    return v.reshape(B, L), np.full((B,), L, np.int32)


def _time_candidate(row, name, fn, jb, jl, total, leaf):
    """Shared timing protocol for every candidate (both sweeps):
    jit + warm (exceptions recorded, e.g. Mosaic unavailable), then
    min-of-3 rounds of REPEATS dispatches holding only a tiny leaf per
    repeat — NO full readback until the correctness gates at the end
    (the first readback poisons remote dispatch).  Returns the warm
    output or None."""
    import jax

    try:
        step = jax.jit(fn)
        out = step(jb, jl)
        jax.block_until_ready(out)
    except Exception as e:
        row[name] = None
        row[name + '_err'] = repr(e)[:80]
        return None
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        leaves = [leaf(step(jb, jl)) for _ in range(REPEATS)]
        jax.block_until_ready(leaves)
        dts.append((time.perf_counter() - t0) / REPEATS)
    row[name] = round(total / min(dts) / 2**20, 0)
    return out


def run_full(args) -> None:
    """Full-decode confirmation rows (VERDICT r3 next #3): the fused
    Mosaic scan+header+GET_DATA-body kernel vs (a) the equivalent jnp
    GET_DATA-only decode and (b) the full speculative
    parse_reply_bodies — at the header kernel's win pocket and its
    neighbors.  The number decides whether the kernel line lives."""
    import jax
    import jax.numpy as jnp

    from zkstream_tpu.ops import replies as R
    from zkstream_tpu.ops.pipeline import (
        getdata_bodies_jnp,
        wire_full_decode_pallas,
        wire_pipeline_step,
    )

    MD = 16

    def jnp_getdata(b, l, F):
        # the same work as the fused kernel, expressed as XLA ops
        st = wire_pipeline_step(b, l, max_frames=F)
        return st, getdata_bodies_jnp(b, st, MD)

    def jnp_full(b, l, F):
        st = wire_pipeline_step(b, l, max_frames=F)
        bd = R.parse_reply_bodies(b, st.starts, st.sizes,
                                  max_data=MD, max_path=8)
        return st, bd

    shapes = [(2048, 64), (8192, 64), (32768, 64)]
    if args.quick:
        shapes = [(8192, 64)]
    gates = []
    for B, F in shapes:
        buf, lens = fleet(B, F)
        jb, jl = jnp.asarray(buf), jnp.asarray(lens)
        total = int(lens.sum())
        row = {'B': B, 'frames': F, 'mib': round(total / 2**20, 1),
               'backend': jax.default_backend(), 'what': 'full'}
        outs = {}
        for name, fn in (
                ('pallas-full',
                 lambda b, l, F=F: wire_full_decode_pallas(
                     b, l, max_frames=F, max_data=MD,
                     block_rows=args.block_rows)),
                ('jnp-getdata',
                 lambda b, l, F=F: jnp_getdata(b, l, F)),
                ('jnp-fullspec',
                 lambda b, l, F=F: jnp_full(b, l, F))):
            out = _time_candidate(row, name, fn, jb, jl, total,
                                  lambda o: o[0].n_frames)
            if out is not None:
                outs[name] = out
        if row.get('pallas-full') and row.get('jnp-getdata'):
            row['ratio_vs_getdata'] = round(
                row['pallas-full'] / row['jnp-getdata'], 2)
        if row.get('pallas-full') and row.get('jnp-fullspec'):
            row['ratio_vs_fullspec'] = round(
                row['pallas-full'] / row['jnp-fullspec'], 2)
        print(json.dumps(row), flush=True)
        gates.append((row, outs, B * F))
    # correctness gates after all timing (readback poisons dispatch)
    for row, outs, want in gates:
        if 'pallas-full' in outs:
            stp, bdp = outs['pallas-full']
            assert int(np.asarray(stp.n_frames).sum()) == want, row
            if 'jnp-getdata' in outs:
                _stj, bdj = outs['jnp-getdata']
                np.testing.assert_array_equal(
                    np.asarray(bdp.data_len), np.asarray(bdj.data_len))
                np.testing.assert_array_equal(
                    np.asarray(bdp.data), np.asarray(bdj.data))
                np.testing.assert_array_equal(
                    np.asarray(bdp.stat_after_data.mzxid_lo),
                    np.asarray(bdj.stat_after_data.mzxid_lo))
    print('# all full-decode gates passed', file=sys.stderr)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument('--quick', action='store_true')
    ap.add_argument('--full', action='store_true',
                    help='run the fused full-decode confirmation rows')
    ap.add_argument('--block-rows', type=int, default=128)
    args = ap.parse_args()

    if args.full:
        run_full(args)
        return

    import jax
    import jax.numpy as jnp

    from zkstream_tpu.ops.pipeline import (
        wire_pipeline_step,
        wire_pipeline_step_pallas,
    )

    shapes = [(256, 8), (256, 64), (2048, 8), (2048, 64),
              (8192, 64), (32768, 8), (32768, 64)]
    if args.quick:
        shapes = [(2048, 64), (32768, 64)]

    cells = []
    for B, F in shapes:
        buf, lens = fleet(B, F)
        jb, jl = jnp.asarray(buf), jnp.asarray(lens)
        total = int(lens.sum())
        row = {'B': B, 'frames': F, 'mib': round(total / 2**20, 1),
               'backend': jax.default_backend()}
        for name, fn in (
                ('pallas', lambda b, l, F=F: wire_pipeline_step_pallas(
                    b, l, max_frames=F, block_rows=args.block_rows)),
                ('jnp', lambda b, l, F=F: wire_pipeline_step(
                    b, l, max_frames=F))):
            out = _time_candidate(row, name, fn, jb, jl, total,
                                  lambda o: o.n_frames)
            if out is not None:
                cells.append((row, name, out, B * F))
        if row.get('pallas') and row.get('jnp'):
            row['winner'] = ('pallas' if row['pallas'] > row['jnp']
                             else 'jnp')
            row['ratio'] = round(row['pallas'] / row['jnp'], 2)
        print(json.dumps(row), flush=True)
    # correctness gates last (readback poisons remote dispatch)
    for row, name, out, want in cells:
        got = int(np.asarray(out.n_frames).sum())
        assert got == want, (row, name, got, want)
    print('# all decode gates passed', file=sys.stderr)


if __name__ == '__main__':
    main()
