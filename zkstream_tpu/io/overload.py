"""The overload plane: admission control, rx/tx backpressure, and
slow-consumer defense.

PRs 13-17 built the throughput for wide fan-out (sharded ingress,
batched transport, observer read scale-out) but nothing bounded what a
member *accepts*: a handshake wave, a pipelining client, a corrupt
4-byte length prefix, or — worst — a stalled subscriber socket that
the watch table will happily buffer a 100k-watcher notification storm
into until the member OOMs.  Real ZooKeeper ships ``RequestThrottler``,
``maxClientCnxns`` and ``jute.maxbuffer`` for exactly this; this module
is that contract for this stack, threaded through every tier as
*watermarks* rather than queues:

- **Admission** — a global connection cap (``ZKSTREAM_MAX_CONNS``) and
  a derived per-ingress-shard cap; over-cap sockets are shed
  pre-adoption through :meth:`ZKServer.shed_client` (traced span +
  metric — never the silent abort the old accept path did).  A
  token-window **handshake pacer** (``ZKSTREAM_ACCEPT_PACE`` accepts
  per 50 ms window) converts a SYN/handshake wave into a deferred
  trickle instead of a thundering adoption storm.
- **Rx backpressure** — the inbound frame cap lives in
  protocol/framing.py (``ZKSTREAM_MAX_FRAME``, typed
  :class:`~..protocol.errors.ZKFrameTooLargeError` *before* buffering);
  this module adds the per-connection inflight throttle: when one
  drain decodes ``ZKSTREAM_MAX_INFLIGHT`` or more requests from a
  single connection, the plane *pauses that connection's rx* — the
  ingress plane removes its reader (stops marking it dirty), the
  validator loop parks on an event — and resumes a few ms later.  No
  queue is built: the kernel socket buffer fills and TCP flow control
  pushes back on the client, exactly the batched-drain shape the
  sharded ingress was built around.
- **Tx watermarks** — per-connection buffered-bytes accounting spans
  the send plane's cork, the transport tier's queued chunks and the
  asyncio transport's own buffer (``SendPlane.buffered_bytes``).  At
  the **soft** watermark (``ZKSTREAM_TX_SOFT``) watch notifications —
  the one legally lossy channel: the client re-syncs via SET_WATCHES —
  are dropped for that connection and counted.  At the **hard**
  watermark (``ZKSTREAM_TX_HARD``) the connection is evicted with a
  traced, typed close (the buffered bytes are *discarded*, not
  flushed: flushing into a wedged socket is how the bloat happened),
  so one stalled subscriber can never wedge a wide fan-out.
- **Global write throttle** — when the member-wide aggregate of
  tx-buffered bytes crosses ``ZKSTREAM_MEM_SOFT`` the member enters a
  degraded mode: new writes bounce with the typed wire code
  ``THROTTLED`` (definite failure — NOT applied; the client backs off
  and retries under its session retry policy) while reads keep
  flowing.  The aggregate is memoized per event-loop tick so the
  write hot path never does an O(conns) walk per op.

``ZKSTREAM_NO_OVERLOAD=1`` (or ``ZKServer(overload=False)``) is the
validator: with the plane off the byte-stream and chaos behavior are
bit-identical to the pre-overload stack (asserted in
tests/test_overload.py), which bisects whether a regression lives in
the plane or under it.

Everything observable: ``zk_throttled_ops_total``,
``zk_evicted_slow_consumers``, ``zk_notifications_dropped_total``, a
``zk_conn_tx_buffered_bytes`` histogram, OVERLOAD spans in the trace
ring, mntr census rows, and a blackbox ``overload`` frame on every
watermark crossing (the PR 17 flight recorder).
"""

from __future__ import annotations

import dataclasses
import os
import time

from ..protocol.consts import MAX_PACKET
from ..utils.aio import ambient_loop

#: Env knobs (all also constructor-settable on ZKServer).  Documented
#: in README.md — the zkanalyze drift checker gates that.
NO_OVERLOAD_ENV = 'ZKSTREAM_NO_OVERLOAD'
MAX_CONNS_ENV = 'ZKSTREAM_MAX_CONNS'
MAX_INFLIGHT_ENV = 'ZKSTREAM_MAX_INFLIGHT'
TX_SOFT_ENV = 'ZKSTREAM_TX_SOFT'
TX_HARD_ENV = 'ZKSTREAM_TX_HARD'
MEM_SOFT_ENV = 'ZKSTREAM_MEM_SOFT'
ACCEPT_PACE_ENV = 'ZKSTREAM_ACCEPT_PACE'

#: Metric names (registered on the server's collector when present).
METRIC_THROTTLED = 'zk_throttled_ops_total'
METRIC_EVICTED = 'zk_evicted_slow_consumers'
METRIC_NOTIF_DROPPED = 'zk_notifications_dropped_total'
METRIC_TX_BUFFERED = 'zk_conn_tx_buffered_bytes'

#: Histogram buckets for per-connection tx-buffered bytes: spans the
#: cork flush cap (~64 KiB) up past the default hard watermark.
TX_BUCKETS = (1024, 8192, 65536, 262144, 1048576,
              4 * 1024 * 1024, 16 * 1024 * 1024)

#: How long a paused connection's reader stays removed before the
#: drain resumes.  Long enough for the replies of the oversized batch
#: to flush and for the kernel buffer to exert TCP backpressure;
#: short enough to be invisible to a well-behaved client.
RX_PAUSE_S = 0.005

#: Aggregate tx-buffered bytes memo lifetime.  One event-loop tick of
#: writes shares a single O(conns) walk.
AGG_MEMO_S = 0.005


def overload_enabled() -> bool:
    """Global kill switch (mirrors ``ZKSTREAM_NO_WATCHTABLE`` /
    ``ZKSTREAM_NO_ELECTION``): ``ZKSTREAM_NO_OVERLOAD=1`` turns the
    whole plane off for A/B bisection."""
    return os.environ.get(NO_OVERLOAD_ENV) != '1'


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw:
        try:
            return int(raw)
        except ValueError:
            return default
    return default


def _sid(conn) -> str | None:
    """The connection's session id in the span convention ('%016x',
    matching ZKSession.get_session_id); None before the handshake."""
    sid = getattr(conn, 'session_id', None)
    return '%016x' % (sid,) if sid is not None else None


@dataclasses.dataclass
class OverloadConfig:
    """The plane's knob inventory.  ``0`` disables the specific limit
    (the plane's accounting still runs — the metrics stay live).

    Defaults are sized for the test/bench rig, not production: caps
    generous enough that no existing test ever trips them, watermarks
    low enough that the overload tests trip them cheaply."""

    #: Global connection cap (``maxClientCnxns`` analogue, but
    #: member-wide; the per-shard cap is derived as cap/nshards).
    max_conns: int = 4096
    #: Per-connection inflight-request throttle: a single rx drain
    #: decoding this many requests pauses that connection's reader.
    max_inflight: int = 256
    #: Soft per-connection tx watermark: above it, watch notifications
    #: for that connection are dropped (legally lossy channel).
    tx_soft: int = 1 * 1024 * 1024
    #: Hard per-connection tx watermark: above it, the connection is
    #: evicted with a traced, typed close and its buffer discarded.
    tx_hard: int = 4 * 1024 * 1024
    #: Global soft memory watermark over the aggregate of all
    #: connections' tx-buffered bytes: above it new writes bounce
    #: with ``THROTTLED`` while reads keep flowing.
    mem_soft: int = 64 * 1024 * 1024
    #: Handshake pacer: accepted connections admitted per window
    #: (0 = unpaced).  Overflow accepts are deferred, not refused.
    accept_pace: int = 0
    #: Pacer window, in seconds.
    accept_window_s: float = 0.05

    @classmethod
    def resolve(cls, max_conns: int | None = None,
                max_inflight: int | None = None,
                tx_soft: int | None = None,
                tx_hard: int | None = None,
                mem_soft: int | None = None,
                accept_pace: int | None = None) -> 'OverloadConfig':
        """Constructor args beat env beats defaults (the same ladder
        every other subsystem knob uses)."""
        d = cls()
        cfg = cls(
            max_conns=max_conns if max_conns is not None
            else _env_int(MAX_CONNS_ENV, d.max_conns),
            max_inflight=max_inflight if max_inflight is not None
            else _env_int(MAX_INFLIGHT_ENV, d.max_inflight),
            tx_soft=tx_soft if tx_soft is not None
            else _env_int(TX_SOFT_ENV, d.tx_soft),
            tx_hard=tx_hard if tx_hard is not None
            else _env_int(TX_HARD_ENV, d.tx_hard),
            mem_soft=mem_soft if mem_soft is not None
            else _env_int(MEM_SOFT_ENV, d.mem_soft),
            accept_pace=accept_pace if accept_pace is not None
            else _env_int(ACCEPT_PACE_ENV, d.accept_pace),
        )
        # A hard watermark below the soft one is a config bug; repair
        # rather than raise (env strings come from operators).
        if cfg.tx_hard and cfg.tx_soft and cfg.tx_hard < cfg.tx_soft:
            cfg.tx_hard = cfg.tx_soft
        return cfg


class OverloadPlane:
    """One member's overload state: admission census, pacer window,
    per-connection rx pause bookkeeping, tx watermark checks, and the
    memoized global aggregate.  Owned by :class:`ZKServer`; ``None``
    when the plane is disabled (every call site null-checks, so the
    disabled path adds zero work to the hot loops)."""

    __slots__ = ('server', 'cfg', 'sheds', 'throttled_writes',
                 'evictions', 'notifications_dropped',
                 'persistent_evictions', 'rx_pauses',
                 '_throttled_on', '_win_start', '_win_n', '_agg',
                 '_agg_at', '_ctr_throttled', '_ctr_evicted',
                 '_ctr_dropped', '_hist_tx')

    def __init__(self, server, cfg: OverloadConfig | None = None,
                 collector=None):
        self.server = server
        self.cfg = cfg if cfg is not None else OverloadConfig.resolve()
        self.sheds = 0
        self.throttled_writes = 0
        self.evictions = 0
        self.notifications_dropped = 0
        self.persistent_evictions = 0
        self.rx_pauses = 0
        self._throttled_on = False
        self._win_start = 0.0
        self._win_n = 0
        self._agg = 0
        self._agg_at = -1.0
        self._ctr_throttled = None
        self._ctr_evicted = None
        self._ctr_dropped = None
        self._hist_tx = None
        if collector is not None:
            self._ctr_throttled = collector.counter(
                METRIC_THROTTLED,
                'Write ops bounced with THROTTLED at the global '
                'memory watermark')
            self._ctr_evicted = collector.counter(
                METRIC_EVICTED,
                'Connections evicted at the hard tx watermark or '
                'shed at admission')
            self._ctr_dropped = collector.counter(
                METRIC_NOTIF_DROPPED,
                'Watch notifications dropped at the soft tx '
                'watermark (client re-syncs via SET_WATCHES)')
            self._hist_tx = collector.histogram(
                METRIC_TX_BUFFERED,
                'Per-connection tx-buffered bytes (plane + tier + '
                'transport) sampled at watermark checks',
                buckets=TX_BUCKETS)

    # -- admission -------------------------------------------------

    def admit(self, total: int, shard_n: int | None = None,
              nshards: int = 1) -> str | None:
        """Admission verdict for one accepted socket: ``None`` to
        adopt, else the shed reason.  ``total`` is the member-wide
        census, ``shard_n`` the owning shard's census (sharded
        ingress only)."""
        cap = self.cfg.max_conns
        if cap > 0:
            if total >= cap:
                return 'conn_cap'
            if shard_n is not None and nshards > 1:
                # Ceil-divided so the caps sum to >= the global cap
                # and a lopsided hash can't strand capacity.
                if shard_n >= -(-cap // nshards):
                    return 'shard_cap'
        return None

    def pace_delay(self) -> float:
        """Handshake pacer: seconds to defer this accept's adoption
        (0.0 = admit now).  A sliding token window — the first
        ``accept_pace`` accepts in a window go straight through,
        the rest are pushed into subsequent windows, flattening a
        handshake wave into a trickle the session layer can absorb."""
        pace = self.cfg.accept_pace
        if pace <= 0:
            return 0.0
        now = time.monotonic()
        w = self.cfg.accept_window_s
        if now - self._win_start >= w:
            self._win_start = now
            self._win_n = 0
        self._win_n += 1
        if self._win_n <= pace:
            return 0.0
        windows_ahead = (self._win_n - 1) // pace
        return max(0.0, (self._win_start + windows_ahead * w) - now)

    def count_shed(self, reason: str) -> None:
        self.sheds += 1
        if self._ctr_evicted is not None:
            self._ctr_evicted.increment({'reason': 'shed:%s' % reason})

    # -- rx backpressure -------------------------------------------

    def after_drain(self, conn, npkts: int) -> None:
        """Called after one rx drain decoded ``npkts`` requests from
        ``conn``.  An oversized batch pauses the connection's reader:
        no queue forms — the kernel socket buffer fills and TCP flow
        control reaches back to the client."""
        cap = self.cfg.max_inflight
        if cap <= 0 or npkts < cap or conn.closed:
            return
        if getattr(conn, '_rx_paused', False):
            return
        conn._rx_paused = True
        self.rx_pauses += 1
        srv = self.server
        if srv.trace is not None:
            srv.trace.note('OVERLOAD', kind='server',
                           detail='rx_pause', batch=npkts,
                           session_id=_sid(conn))
        ingress = getattr(conn, '_ingress', None)
        if ingress is not None:
            ingress.pause_rx(conn)
        loop = ambient_loop()
        loop.call_later(RX_PAUSE_S, self._resume_rx, conn)

    def _resume_rx(self, conn) -> None:
        if not getattr(conn, '_rx_paused', False):
            return
        conn._rx_paused = False
        if conn.closed:
            return
        ingress = getattr(conn, '_ingress', None)
        if ingress is not None:
            ingress.resume_rx(conn)
        else:
            gate = getattr(conn, '_rx_resume', None)
            if gate is not None:
                gate.set()

    # -- tx watermarks ---------------------------------------------

    def tx_buffered(self, conn) -> int:
        return conn._tx.buffered_bytes()

    def allow_notification(self, conn) -> bool:
        """Soft-watermark gate on the fan-out path: ``False`` means
        drop this connection's watch notification (and count it) —
        the one legally lossy channel, since a reconnecting client
        re-arms via SET_WATCHES and re-reads what it watched."""
        soft = self.cfg.tx_soft
        if soft <= 0 or conn.closed:
            return True
        b = conn._tx.buffered_bytes()
        if b < soft:
            return True
        self.notifications_dropped += 1
        first = not getattr(conn, '_notif_dropping', False)
        conn._notif_dropping = True
        if self._ctr_dropped is not None:
            self._ctr_dropped.increment()
        srv = self.server
        if first and srv.trace is not None:
            # One span per drop *episode*, not per dropped frame — a
            # 100k fan-out against a stalled socket must not flood
            # the trace ring.
            srv.trace.note('OVERLOAD', kind='server',
                           detail='notif_drop', nbytes=b,
                           session_id=_sid(conn))
        return False

    def allow_persistent_notification(self, conn) -> bool:
        """The soft-watermark gate for PERSISTENT-watch subscribers
        (server/watchtable.py ``_fan_persistent``).  The one-shot
        drop contract above is UNSAFE here: a one-shot client re-arms
        and re-reads on reconnect anyway, but a persistent subscriber
        — a watch-backed cache — relies on the invalidation stream
        being gap-free, and a silently dropped frame would leave it
        serving stale data forever with no signal.  So instead of a
        gap the stalled subscriber is EVICTED on the spot (typed
        close, same as the hard watermark): the client observes a
        connection loss, marks its cached subtree stale, re-dials,
        replays via SET_WATCHES2 and re-syncs — coherence preserved
        at the cost of one reconnect."""
        soft = self.cfg.tx_soft
        if soft <= 0 or conn.closed:
            return True
        b = conn._tx.buffered_bytes()
        if b < soft:
            return True
        self.persistent_evictions += 1
        self.evict(conn, 'persistent_gap', buffered=b)
        return False

    def check_tx(self, conn) -> bool:
        """Hard-watermark check, called where tx bytes accumulate
        (fan-out flush, ingress drain).  Returns ``True`` if the
        connection was evicted."""
        if conn.closed:
            return False
        b = conn._tx.buffered_bytes()
        if self._hist_tx is not None:
            self._hist_tx.observe(b)
        if b < self.cfg.tx_soft or b > self.cfg.tx_soft * 2:
            # Cheap hysteresis for the drop-episode marker: well
            # below soft clears it so a later stall traces anew.
            if b < self.cfg.tx_soft:
                conn._notif_dropping = False
        hard = self.cfg.tx_hard
        if hard > 0 and b >= hard:
            self.evict(conn, 'tx_hard', buffered=b)
            return True
        return False

    def evict(self, conn, reason: str, buffered: int | None = None) \
            -> None:
        """Slow-consumer eviction: a traced, typed close that
        *discards* the buffered tx bytes (flushing into the wedged
        socket is how the bloat happened) and aborts the transport.
        The client observes a connection loss, re-dials a healthy
        member and re-syncs watches — the fan-out to everyone else
        proceeds unbloated."""
        if conn.closed:
            return
        self.evictions += 1
        if self._ctr_evicted is not None:
            self._ctr_evicted.increment({'reason': reason})
        srv = self.server
        if srv.trace is not None:
            srv.trace.note('OVERLOAD', kind='server',
                           detail='evict:%s' % reason,
                           session_id=_sid(conn), nbytes=buffered)
        if srv.blackbox is not None:
            srv.blackbox.capture('overload')
        conn.evicted = reason
        sess = getattr(conn, 'session', None)
        if sess is not None:
            # the session event: a resuming connection (any member)
            # can see WHY its predecessor died and that watches may
            # have been dropped — re-sync via SET_WATCHES
            sess.overload_evicted = reason
        conn.abort()

    # -- global write throttle -------------------------------------

    def aggregate_tx(self) -> int:
        """Member-wide tx-buffered bytes, memoized for one tick."""
        now = time.monotonic()
        if now - self._agg_at < AGG_MEMO_S:
            return self._agg
        total = 0
        for c in self.server.conns:
            if not c.closed:
                total += c._tx.buffered_bytes()
        self._agg = total
        self._agg_at = now
        return total

    def write_throttled(self) -> bool:
        """``True`` when the member is over its global memory
        watermark: new writes must bounce ``THROTTLED`` (reads keep
        flowing).  Crossings in either direction cut a blackbox
        ``overload`` frame — the flight recorder keeps the moment
        the member entered and left degraded mode."""
        soft = self.cfg.mem_soft
        if soft <= 0:
            return False
        over = self.aggregate_tx() >= soft
        if over != self._throttled_on:
            self._throttled_on = over
            srv = self.server
            if srv.trace is not None:
                srv.trace.note('OVERLOAD', kind='server',
                               detail='throttle_on' if over
                               else 'throttle_off',
                               nbytes=self._agg)
            if srv.blackbox is not None:
                srv.blackbox.capture('overload')
        return over

    def count_throttled(self, op: str) -> None:
        self.throttled_writes += 1
        if self._ctr_throttled is not None:
            self._ctr_throttled.increment({'op': op})

    # -- observability ---------------------------------------------

    def mntr_rows(self) -> list:
        return [
            ('zk_overload_sheds', self.sheds),
            ('zk_overload_rx_pauses', self.rx_pauses),
            ('zk_overload_throttled_writes', self.throttled_writes),
            ('zk_overload_evictions', self.evictions),
            ('zk_overload_notifications_dropped',
             self.notifications_dropped),
            ('zk_overload_persistent_evictions',
             self.persistent_evictions),
            ('zk_overload_tx_buffered_bytes', self.aggregate_tx()),
            ('zk_overload_max_frame',
             getattr(self.server, 'max_frame', MAX_PACKET)),
        ]
