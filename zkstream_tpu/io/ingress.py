"""Shared-nothing ingress: per-core accept shards + batched receive
drain beneath the unchanged request-dispatch path.

The send path leaves the kernel as one submission chain per corked
tick (io/transport.py), but ingress was still ONE ``asyncio.start_server``
loop doing one ``reader.read()`` task wakeup per connection per tick —
and the tick ledger (PR 7, PROFILE.md "Where a busy tick goes") says
decode+dispatch eats the majority of every busy tick at every
write-heavy fleet size.  At 10k+ live sessions the per-connection
stream machinery (protocol ``data_received`` → ``StreamReader`` feed →
task wakeup → ``read()`` copy) is the real ceiling: O(connections)
Python-level wakeups and buffer hops per tick before a single request
byte is decoded.  Same thesis as the transport tier — PAPERS.md's
RPCAcc / transparent-InfiniBand-under-netty line batches beneath an
unchanged API — applied to the receive direction.

Two halves, built together:

**Accept shards.**  The listening endpoint becomes N shards over the
one replicated store — ``SO_REUSEPORT`` listeners where the kernel
supports it (the kernel then spreads incoming connections across the
shard listeners by 4-tuple hash), a single-listener round-robin
dispatcher handoff elsewhere.  A connection's shard is its affinity
key for the whole serving plane: its watch-table fan-out shard, its
fan-out buffer, and its send-plane cork all key off the same shard
(server/watchtable.py ``add_conn``), so one connection's state never
crosses shards on the hot path.  Writes still serialize through the
one leader store (the lock-guarded apply, zxid order preserved) and
the fsync/quorum ``CommitBarrier`` stays ONE barrier per tick across
every shard — sharding the ingress never weakens the ack contract.

**Batched receive drain.**  Accepted sockets are adopted with their
transport's reading PAUSED; the plane registers its own readiness
callback per fd.  A readable connection marks itself dirty on its
shard and the shard schedules ONE drain callback for the tick
boundary; the drain then moves every dirty connection's bytes out of
the kernel in one batched call —

- ``uring``  — one io_uring submission per drain: one RECVMSG SQE per
  dirty connection, ONE ``io_uring_enter`` submits and reaps the wave
  (native/zkwire_ext.c ``uring_recv``; the multishot-recv upgrade is
  declared there and carried until a >= 5.19 kernel can measure it).
  Requires Linux >= 5.1 — capability-gated OFF on this image's 4.4
  kernel, exactly like the transport tier's uring arm.
- ``mmsg``   — the whole dirty set in ONE C call
  (``zkwire_ext.drain_recv``: flat fds array, one ``recv(2)`` per fd
  inside the call — TCP has no cross-fd ``recvmmsg``, so the kernel
  crossing count stays O(dirty conns) while the Python-level
  submission count drops to O(dirty shards)); a pure-Python
  ``os.read`` loop when the extension is not (yet) built.
- ``asyncio`` — the single-loop validator: ``asyncio.start_server``
  plus the per-connection ``reader.read()`` task, exactly yesterday's
  path (``shards=1`` resolves here too).

Knobs, capability-probed and env-forced exactly like io/transport.py
(forcing falls DOWN the order, never up):

- ``ZKSTREAM_INGRESS=uring|mmsg|asyncio`` / ``ZKServer(ingress_backend=)``
- ``ZKSTREAM_INGRESS_SHARDS=N`` / ``ZKServer(ingress_shards=)`` /
  ``ZKEnsemble(ingress_shards=)`` — default sized from the CPU count
  (capped at :data:`MAX_DEFAULT_SHARDS`); ``1`` keeps the single-loop
  validator.
- ``ZKSTREAM_RX_BUF`` — receive buffer per drained connection per
  drain (the former hardcoded ``read(65536)``), both paths.

Correctness contract (tests/test_ingress.py holds every backend to
identical per-connection frame streams over the full opcode corpus):

- **Per-connection frame order is arrival order.**  One drain reads
  each dirty fd once, in dirty order; bytes feed the connection's
  codec exactly as the validator's ``read()`` loop would, partial
  frames at any byte offset included (the codec accumulates).
- **Fault injection stays a per-frame boundary BEFORE the batch.**
  Each connection's drained bytes pass the injector's ``server_rx``
  hook individually before any decode (io/faults.py) — the PR 4 tx
  rule mirrored on the receive side — so an injected split/delay/reset
  perturbs one connection's stream without reordering it, on every
  backend.
- **EOF and dead sockets close the connection** exactly as the
  validator's empty read does.

Observability: ``zookeeper_recv_syscalls_total{plane,backend}``
counts receive submissions per backend (O(dirty conns) per drain on
mmsg — honest: the C call still crosses the kernel once per fd —
O(1) enters on uring, one per ``read()`` on the validator) and
``zookeeper_recv_drain_depth`` histograms connections covered per
batched drain (the O(dirty-shards)-submissions-per-tick number).
``mntr`` reports ``zk_ingress_shards`` / ``zk_ingress_backend`` and a
per-shard connection census.  Scraped by ``bench.py --ingress``
(`make bench-ingress`).
"""

from __future__ import annotations

import asyncio
import dataclasses
import errno
import logging
import os
import socket
import struct
import sys

from ..utils.aio import ambient_loop

log = logging.getLogger('zkstream_tpu.ingress')

#: Fallback order: forcing an unavailable tier falls DOWN this list.
BACKENDS = ('uring', 'mmsg', 'asyncio')

METRIC_RECV_SYSCALLS = 'zookeeper_recv_syscalls_total'
METRIC_RECV_DRAIN_DEPTH = 'zookeeper_recv_drain_depth'

#: Connections per batched receive drain (1 = the drain bought
#: nothing that tick; the interesting mass is 2+).
DEPTH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096)

#: Receive buffer per connection per drain — the former hardcoded
#: ``reader.read(65536)`` magic number, now a documented knob
#: (``ZKSTREAM_RX_BUF``).  Level-triggered readiness re-fires when a
#: connection had more than one buffer pending, so a small value
#: costs extra drains, never lost bytes.
DEFAULT_RX_BUF = 65536

#: Default shard-count ceiling: enough accept shards to keep one
#: shard's dirty set small under a connection storm, few enough that
#: an idle tick schedules almost nothing (and that a many-core box
#: does not pay 64 idle listeners per member).
MAX_DEFAULT_SHARDS = 8

#: io_uring receive-ring depth per plane (drains wider than this
#: submit in waves — still one enter syscall per wave).
URING_DEPTH = 1024

#: recv errnos that mean "nothing to read right now" (level-triggered
#: readiness raced a drain that already emptied the socket): skip the
#: connection, never close it.
_SOFT_ERRNOS = frozenset({errno.EAGAIN, errno.EWOULDBLOCK,
                          errno.EINTR})


@dataclasses.dataclass(frozen=True)
class Probe:
    """What the ingress capability probe found (``zk_ingress_backend``
    and the pytest skip markers read this)."""

    platform: str
    reuseport: bool
    reuseport_reason: str
    uring: bool
    uring_reason: str
    mmsg: bool
    mmsg_reason: str
    forced: str | None
    chosen: str

    def available(self, backend: str) -> bool:
        if backend == 'uring':
            return self.uring
        if backend == 'mmsg':
            return self.mmsg
        return True


#: Cached CAPABILITY results only — the env force is re-read on every
#: probe() call (like io/transport.py), so tests and the chaos CLI
#: can flip ZKSTREAM_INGRESS mid-process.
_caps_cache: tuple | None = None


def _probe_reuseport() -> tuple[bool, str]:
    """Can this kernel spread accepts across per-shard listeners?"""
    if not hasattr(socket, 'SO_REUSEPORT'):
        return False, 'SO_REUSEPORT not exposed'
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    except OSError as e:
        return False, 'setsockopt: %s' % (e.strerror or e,)
    finally:
        s.close()
    return True, 'ok'


def _probe_uring() -> tuple[bool, str]:
    """Can this process batch receives through an io_uring?  Needs
    Linux, the native extension with the recv arm (``uring_recv``),
    and a kernel that answers io_uring_setup (>= 5.1)."""
    if not sys.platform.startswith('linux'):
        return False, 'not linux'
    from ..utils.native import get_ext
    ext = get_ext()
    if ext is None:
        return False, 'native ext unavailable (build pending or off)'
    if not hasattr(ext, 'uring_recv'):
        return False, 'native ext predates uring recv support'
    try:
        ring = ext.uring_create(8)
    except OSError as e:
        return False, 'io_uring_setup: %s' % (e.strerror or e,)
    ext.uring_close(ring)
    return True, 'ok'


def _probe_mmsg() -> tuple[bool, str]:
    if sys.platform.startswith('win'):
        return False, 'not posix'
    return True, 'ok'


def probe(refresh: bool = False) -> Probe:
    """Resolve the process's ingress tier: capability probe (cached;
    ``refresh=True`` re-probes after a mid-process native build) plus
    the env force, re-read every call."""
    global _caps_cache
    if _caps_cache is None or refresh:
        _caps_cache = (_probe_reuseport(), _probe_uring(),
                       _probe_mmsg())
    (rp_ok, rp_why), (uring_ok, uring_why), (mmsg_ok, mmsg_why) = \
        _caps_cache
    forced = os.environ.get('ZKSTREAM_INGRESS') or None
    if forced is not None and forced not in BACKENDS:
        forced = None
    order = BACKENDS[BACKENDS.index(forced):] if forced else BACKENDS
    chosen = 'asyncio'
    for b in order:
        if (b == 'uring' and uring_ok) or (b == 'mmsg' and mmsg_ok) \
                or b == 'asyncio':
            chosen = b
            break
    return Probe(platform=sys.platform, reuseport=rp_ok,
                 reuseport_reason=rp_why, uring=uring_ok,
                 uring_reason=uring_why, mmsg=mmsg_ok,
                 mmsg_reason=mmsg_why, forced=forced, chosen=chosen)


def backend_default() -> str:
    """The process-wide rx backend (env force resolved against the
    probe) — what a knobless ZKServer runs."""
    return probe().chosen


def resolve_backend(arg: str | None) -> str:
    """Resolve an explicit constructor knob ('uring'|'mmsg'|'asyncio',
    None = process default) against availability, falling down the
    tier order like the env force does."""
    if arg is None:
        return backend_default()
    if arg not in BACKENDS:
        raise ValueError('unknown ingress backend %r (choose from '
                         '%s)' % (arg, '|'.join(BACKENDS)))
    p = probe()
    for b in BACKENDS[BACKENDS.index(arg):]:
        if p.available(b):
            return b
    return 'asyncio'


def shards_default() -> int:
    """Process-wide shard count: ``ZKSTREAM_INGRESS_SHARDS`` when set
    and positive, else sized from the CPU count (one accept shard per
    core, capped at :data:`MAX_DEFAULT_SHARDS`)."""
    try:
        n = int(os.environ.get('ZKSTREAM_INGRESS_SHARDS', ''))
    except ValueError:
        n = 0
    if n > 0:
        return n
    return max(1, min(os.cpu_count() or 1, MAX_DEFAULT_SHARDS))


def resolve_shards(arg: int | None) -> int:
    """Resolve a constructor shard knob (None = process default)."""
    if arg is None:
        return shards_default()
    if arg < 1:
        raise ValueError('ingress_shards must be >= 1 (1 = the '
                         'single-loop validator)')
    return arg


def rx_buf_default() -> int:
    """Receive-buffer size per drained connection: ``ZKSTREAM_RX_BUF``
    (bytes) when set and positive, else :data:`DEFAULT_RX_BUF`."""
    try:
        v = int(os.environ.get('ZKSTREAM_RX_BUF', ''))
    except ValueError:
        return DEFAULT_RX_BUF
    return v if v > 0 else DEFAULT_RX_BUF


class _IngressShard:
    """One accept shard's state: its listener (SO_REUSEPORT mode), the
    connections it owns, and the per-tick dirty set."""

    __slots__ = ('idx', 'conns', 'dirty', 'scheduled')

    def __init__(self, idx: int) -> None:
        self.idx = idx
        self.conns: set = set()
        self.dirty: list = []
        self.scheduled = False


class _ShardProtocol(asyncio.streams.FlowControlMixin):
    """The adopted socket's protocol: pauses transport reading the
    instant the connection exists (receive belongs to the shard
    drain, not the stream machinery) and routes connection teardown
    back to the ServerConnection.  FlowControlMixin supplies the
    drain helper a StreamWriter needs."""

    def __init__(self, plane: 'IngressPlane', shard_idx: int):
        super().__init__()
        self.plane = plane
        self.shard_idx = shard_idx
        self.conn = None

    def connection_made(self, transport) -> None:
        # pause before the transport's own (queued) reader
        # registration runs; the plane claims the fd one callback
        # later (see IngressPlane._adopted)
        transport.pause_reading()
        self.plane._protocols.add(self)
        self.conn = self.plane._adopted(transport, self,
                                        self.shard_idx)

    def data_received(self, data: bytes) -> None:
        # unreachable while reading is paused; kept as a safety net
        # for exotic transports — same feed path, same semantics
        conn = self.conn
        if conn is not None and not conn.closed and not conn.feed(data):
            conn.close()

    def eof_received(self) -> bool:
        return False        # close the transport; connection_lost runs

    def connection_lost(self, exc) -> None:
        super().connection_lost(exc)
        conn, self.conn = self.conn, None
        if conn is not None:
            conn.close()
        self.plane._proto_lost(self)


class IngressPlane:
    """One member's sharded ingress: N accept shards, each draining
    its dirty connections in one batched receive per busy tick.

    Owned by :class:`~..server.server.ZKServer`; ``None`` on a server
    whose resolved backend is ``asyncio`` (the single-loop validator
    keeps ``asyncio.start_server``)."""

    def __init__(self, server, shards: int, backend: str,
                 collector=None):
        assert backend in ('uring', 'mmsg'), backend
        assert shards >= 1
        self.server = server
        self.backend = backend
        self.nshards = shards
        self.rx_buf = rx_buf_default()
        self.reuseport = probe().reuseport
        self.shards = [_IngressShard(i) for i in range(shards)]
        self.port = 0
        self._lsocks: list[socket.socket] = []
        self._rr = 0             # dispatcher-handoff round-robin
        self._adopting: set = set()
        #: Live adopted protocols: what ``wait_closed`` drains —
        #: ZKServer.stop awaits every severed connection's
        #: ``connection_lost``, mirroring what the validator path's
        #: handler-task teardown provided (a stop that completed in
        #: zero loop iterations would let an in-process client keep
        #: believing it is connected).
        self._protocols: set = set()
        self._closed_waiters: list = []
        #: Stale-readiness suppression: a drain runs at the tick
        #: boundary AFTER the iteration's readiness events were
        #: reported, so the event for the bytes it just consumed is
        #: still in the ready queue and would re-dirty the connection
        #: into an EAGAIN drain next tick — measured at exactly 2x
        #: the recv count.  Each drained connection skips ONE
        #: readiness event; the skips clear at the head of the next
        #: iteration (before its fresh events run), so no real event
        #: is ever lost — level-triggered epoll re-reports anything
        #: still pending.
        self._skip_clear: list = []
        self._skip_scheduled = False
        self._uring = None
        self._uring_dead = False
        self.syscalls = 0        # lifetime receive submissions
        self.drains = 0          # batched drain rounds
        self._recv_ctr = None
        self._depth_hist = None
        #: per-backend label dicts, keyed by what a drain actually
        #: ran (a uring plane that latches down mid-life must account
        #: under mmsg, not under its configured tier)
        self._labels = {b: {'plane': 'server', 'backend': b}
                        for b in BACKENDS}
        if collector is not None:
            self._recv_ctr = collector.counter(
                METRIC_RECV_SYSCALLS,
                'Receive submissions issued by the ingress plane, by '
                'plane and backend')
            self._depth_hist = collector.histogram(
                METRIC_RECV_DRAIN_DEPTH,
                'Connections covered per batched receive drain, by '
                'plane and backend', buckets=DEPTH_BUCKETS)

    @property
    def running(self) -> bool:
        return bool(self._lsocks)

    # -- listeners ------------------------------------------------------

    def start(self, host: str, port: int) -> None:
        """Bind and register the shard listeners.  SO_REUSEPORT mode
        binds one listener per shard on the same port (the kernel
        spreads accepts); dispatcher mode binds one listener and
        hands accepted sockets round-robin to the shards."""
        assert not self._lsocks, 'ingress already started'
        loop = ambient_loop()
        n_listen = self.nshards if self.reuseport else 1
        self.port = port
        for k in range(n_listen):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            try:
                # what asyncio.start_server sets on POSIX: without it
                # a stop()/restart() on the same port can hit
                # EADDRINUSE from the closed connections' TIME_WAIT
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                if self.reuseport:
                    s.setsockopt(socket.SOL_SOCKET,
                                 socket.SO_REUSEPORT, 1)
                s.setblocking(False)
                s.bind((host, self.port))
                s.listen(self.server.BACKLOG)
            except OSError:
                s.close()
                for other in self._lsocks:
                    try:
                        loop.remove_reader(other.fileno())
                    except (OSError, ValueError, RuntimeError):
                        pass
                    other.close()
                self._lsocks = []
                raise
            if self.port == 0:
                self.port = s.getsockname()[1]
            self._lsocks.append(s)
            loop.add_reader(s.fileno(), self._on_accept, s,
                            k if self.reuseport else None)

    def stop(self) -> None:
        """Close the shard listeners (connections are the server's to
        sever) and release the receive ring."""
        loop = ambient_loop()
        for s in self._lsocks:
            try:
                loop.remove_reader(s.fileno())
            except (OSError, ValueError, RuntimeError):
                pass
            s.close()
        self._lsocks = []
        for t in list(self._adopting):
            t.cancel()
        if self._uring is not None:
            from ..utils.native import get_ext
            ext = get_ext()
            if ext is not None:
                try:
                    ext.uring_close(self._uring)
                except (OSError, ValueError):
                    pass
            self._uring = None

    def _proto_lost(self, proto: _ShardProtocol) -> None:
        self._protocols.discard(proto)
        if not self._protocols and self._closed_waiters:
            waiters, self._closed_waiters = self._closed_waiters, []
            for w in waiters:
                if not w.done():
                    w.set_result(None)

    async def wait_closed(self) -> None:
        """Wait for every adopted connection's transport teardown to
        complete (``connection_lost`` ran) — the sharded twin of the
        validator path's wait-for-handlers semantics.  The caller has
        already severed the connections; this only yields until the
        loop processed their closes."""
        while self._protocols:
            w = ambient_loop().create_future()
            self._closed_waiters.append(w)
            await w
        # the validator's stop returned only after the per-connection
        # handler tasks unwound — one task wakeup past connection_lost
        # — which is also what let an in-process peer's transport poll
        # the FIN before stop() returned.  Match that tail.
        for _ in range(3):
            await asyncio.sleep(0)

    # -- accept ---------------------------------------------------------

    def _on_accept(self, lsock: socket.socket,
                   shard_idx: int | None) -> None:
        """One listener's readiness callback: drain the accept queue.
        SO_REUSEPORT listeners pin their accepts to their own shard;
        the dispatcher listener hands off round-robin."""
        srv = self.server
        while True:
            try:
                sock, _addr = lsock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return          # listener closed under the callback
            if srv.faults is not None and srv.faults.accept_refuse():
                # injected accept-loop refusal: RST, like the
                # validator path's transport.abort() — accounted
                # through the same shed helper (traced + counted)
                srv.note_shed('accept_refuse')
                self._rst_close(sock)
                continue
            ov = srv.overload
            delay = 0.0
            if ov is not None:
                # admission (io/overload.py): the global cap and this
                # accept's shard cap, checked BEFORE adoption — an
                # over-cap socket costs one RST, never a transport
                k_probe = (shard_idx if shard_idx is not None
                           else self._rr % self.nshards)
                why = ov.admit(len(srv.conns),
                               len(self.shards[k_probe].conns),
                               self.nshards)
                if why is not None:
                    srv.note_shed(why)
                    self._rst_close(sock)
                    continue
                delay = ov.pace_delay()
            try:
                sock.setblocking(False)
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            except OSError:
                pass
            if shard_idx is None:
                k = self._rr % self.nshards
                self._rr += 1
            else:
                k = shard_idx
            task = asyncio.ensure_future(self._adopt(sock, k, delay))
            self._adopting.add(task)
            task.add_done_callback(self._adopting.discard)

    @staticmethod
    def _rst_close(sock: socket.socket) -> None:
        """Shed one accepted-but-never-adopted socket: linger-0 close
        (RST) so the peer learns immediately and no FIN state lingers
        through a connection flood."""
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack('ii', 1, 0))
        except OSError:
            pass
        sock.close()

    async def _adopt(self, sock: socket.socket, shard_idx: int,
                     delay: float = 0.0) -> None:
        """Wrap one accepted socket in an asyncio transport (the send
        plane, fault gates and teardown paths all speak transport) —
        reading paused from birth; the shard drain owns receive.
        ``delay`` is the handshake pacer's verdict: an over-window
        accept adopts late, flattening a dial wave."""
        loop = ambient_loop()
        if delay > 0.0:
            await asyncio.sleep(delay)
            if not self.running:
                self.server.note_shed('pacer_shutdown')
                try:
                    sock.close()
                except OSError:
                    pass
                return
        try:
            await loop.connect_accepted_socket(
                lambda: _ShardProtocol(self, shard_idx), sock)
        except (OSError, RuntimeError, asyncio.CancelledError):
            try:
                sock.close()
            except OSError:
                pass

    def _adopted(self, transport, proto: _ShardProtocol,
                 shard_idx: int):
        """Protocol handshake done (synchronously, inside
        ``connection_made``): build the ServerConnection and register
        the fd with the shard drain."""
        from ..server.server import ServerConnection
        loop = ambient_loop()
        writer = asyncio.StreamWriter(transport, proto, None, loop)
        srv = self.server
        conn = ServerConnection(srv, None, writer)
        conn._ingress = self
        conn._ingress_shard = shard_idx
        srv.conns.add(conn)
        self.shards[shard_idx].conns.add(conn)
        sock = transport.get_extra_info('socket')
        fd = -1
        if sock is not None:
            try:
                fd = sock.fileno()
            except (OSError, ValueError):
                fd = -1
        conn._rx_fd = fd
        # Claiming the fd must happen ONE callback later: the
        # selector transport queued its own reader registration at
        # construction, behind connection_made — and (3.10) that
        # registration checks only _closing, not _paused, so it will
        # re-take the fd after this method returns.  _claim_fd runs
        # after it and installs the drain's callback through the
        # loop's _add_reader (the public add_reader refuses
        # transport-owned fds; the private call replaces an existing
        # registration atomically — the transport itself uses it).
        # A chunk landing in that one-callback window arrives via
        # data_received, which feeds the same decode path.  Loops
        # without _add_reader (proactor) stay on protocol push.
        if fd >= 0 and hasattr(loop, '_add_reader'):
            loop.call_soon(self._claim_fd, conn)
        else:
            conn._rx_fd = -1
            transport.resume_reading()
        return conn

    def _claim_fd(self, conn) -> None:
        fd = conn._rx_fd
        if conn.closed or fd < 0:
            return
        try:
            ambient_loop()._add_reader(fd, self._on_readable, conn)
        except (OSError, ValueError, RuntimeError):
            conn._rx_fd = -1

    def forget(self, conn) -> None:
        """Connection closed: unregister its readiness callback and
        drop it from its shard (ServerConnection.close calls in)."""
        fd, conn._rx_fd = conn._rx_fd, -1
        if fd >= 0:
            # the private-API twin of the registration in _adopted
            # (the transport's own close() also unregisters the fd,
            # so a remove after transport teardown is a no-op)
            try:
                remove = getattr(ambient_loop(), '_remove_reader',
                                 None)
                if remove is not None:
                    remove(fd)
            except (OSError, ValueError, RuntimeError):
                pass
        shard = self.shards[conn._ingress_shard]
        shard.conns.discard(conn)

    # -- rx pause (the overload plane's inflight throttle) --------------

    def pause_rx(self, conn) -> None:
        """Stop draining one connection (io/overload.py): unregister
        its readiness callback so it can never go dirty — the kernel
        socket buffer then fills and TCP flow control pushes back on
        the client.  No user-space queue forms; that is the point."""
        fd = conn._rx_fd
        if fd < 0:
            return
        try:
            remove = getattr(ambient_loop(), '_remove_reader', None)
            if remove is not None:
                remove(fd)
        except (OSError, ValueError, RuntimeError):
            pass

    def resume_rx(self, conn) -> None:
        """Re-register a paused connection's reader and force one
        drain: bytes that arrived during the pause already sit in the
        kernel buffer, and a level-triggered selector only reports
        them to a registered reader."""
        if conn.closed or conn._rx_fd < 0:
            return
        try:
            ambient_loop()._add_reader(conn._rx_fd, self._on_readable,
                                       conn)
        except (OSError, ValueError, RuntimeError):
            conn._rx_fd = -1
            return
        conn._rx_skip = False
        if not conn._rx_dirty:
            conn._rx_dirty = True
            shard = self.shards[conn._ingress_shard]
            shard.dirty.append(conn)
            if not shard.scheduled:
                shard.scheduled = True
                ambient_loop().call_soon(self._drain_shard, shard)

    # -- the batched drain ----------------------------------------------

    def _on_readable(self, conn) -> None:
        """One connection's readiness callback: mark dirty, schedule
        the shard's one drain for the tick boundary.  Level-triggered
        readiness re-fires while a drain is pending — the dirty flag
        makes that a no-op."""
        if conn._rx_dirty or conn.closed or conn._rx_paused:
            return
        if conn._rx_skip:
            # the event for bytes a drain already consumed this
            # iteration: swallow exactly one
            conn._rx_skip = False
            return
        conn._rx_dirty = True
        shard = self.shards[conn._ingress_shard]
        shard.dirty.append(conn)
        if not shard.scheduled:
            shard.scheduled = True
            ambient_loop().call_soon(self._drain_shard, shard)

    def _drain_shard(self, shard: _IngressShard) -> None:
        """One shard's tick drain: every dirty connection's pending
        bytes leave the kernel in one batched receive, then feed the
        decode path per connection, in dirty order."""
        shard.scheduled = False
        dirty, shard.dirty = shard.dirty, []
        conns = []
        fds = []
        for conn in dirty:
            conn._rx_dirty = False
            if conn.closed or conn._rx_fd < 0 or conn._rx_paused:
                # a paused connection's bytes wait in the kernel;
                # resume_rx re-dirties it when the throttle lifts
                continue
            conns.append(conn)
            fds.append(conn._rx_fd)
        if not fds:
            return
        ledger = self.server.ledger
        if ledger is not None:
            # the tick's rx_drain phase: kernel-to-user time only
            # (decode + dispatch lands in decode_apply inside feed)
            ledger.enter('rx_drain')
        try:
            results, nsys, backend = self._drain_fds(fds)
        finally:
            if ledger is not None:
                ledger.exit()
        for conn in conns:
            conn._rx_skip = True
        self._skip_clear.extend(conns)
        if not self._skip_scheduled:
            self._skip_scheduled = True
            ambient_loop().call_soon(self._clear_skips)
        self.drains += 1
        self.syscalls += nsys
        labels = self._labels[backend]
        if self._recv_ctr is not None and nsys:
            self._recv_ctr.increment(labels, by=nsys)
        if self._depth_hist is not None:
            self._depth_hist.observe(len(fds), labels)
        for conn, res in zip(conns, results):
            if conn.closed:
                continue        # an earlier feed's handler closed it
            if isinstance(res, int):
                if -res in _SOFT_ERRNOS:
                    continue    # raced an already-drained socket
                conn.close()    # dead socket: same as a failed read
                continue
            if not res:
                conn.close()    # EOF — the validator's empty read
                continue
            # one connection's failure must not take the rest of the
            # batch with it: the validator isolated a raising handler
            # to its own task, and the shared drain is no weaker
            try:
                keep = conn.feed(res)
            except Exception:
                log.exception('ingress: dispatch failed; closing '
                              'connection')
                keep = False
            if not keep:
                conn.close()
                continue
            ov = self.server.overload
            if ov is not None and not conn.closed:
                # the drain is the natural per-conn-per-tick boundary
                # for the hard tx watermark: a reply backlog that
                # outgrew it evicts here
                ov.check_tx(conn)

    def _clear_skips(self) -> None:
        """Head of the next loop iteration: un-skip every connection
        a drain marked — fresh readiness events (appended behind this
        callback) then flow normally."""
        self._skip_scheduled = False
        conns, self._skip_clear = self._skip_clear, []
        for conn in conns:
            conn._rx_skip = False

    def _drain_fds(self, fds: list[int]
                   ) -> tuple[list, int, str]:
        """Move the dirty set's bytes out of the kernel; returns
        (per-fd bytes-or-negative-errno, receive submissions issued,
        backend that carried them)."""
        if self.backend == 'uring':
            out = self._drain_uring(fds)
            if out is not None:
                return out
            # ring creation failed after probe said OK (fd limits,
            # seccomp, pre-5.6 RECVMSG): latch down to the batch call
        from ..utils.native import get_ext
        ext = get_ext()
        if ext is not None and hasattr(ext, 'drain_recv'):
            # ONE C call for the whole dirty set: one recv(2) per fd
            # inside it, zero per-fd Python dispatch
            return ext.drain_recv(fds, self.rx_buf), len(fds), 'mmsg'
        results: list = []
        nbuf = self.rx_buf
        for fd in fds:
            try:
                results.append(os.read(fd, nbuf))
            except BlockingIOError:
                results.append(-errno.EAGAIN)
            except OSError as e:
                results.append(-(e.errno or 1))
        return results, len(fds), 'mmsg'

    def _drain_uring(self, fds: list[int]
                     ) -> tuple[list, int, str] | None:
        if self._uring_dead:
            return None
        from ..utils.native import get_ext
        ext = get_ext()
        if ext is None or not hasattr(ext, 'uring_recv'):
            return None
        if self._uring is None:
            try:
                self._uring = ext.uring_create(URING_DEPTH)
            except OSError:
                self._uring_dead = True
                return None
        try:
            results, enters = ext.uring_recv(self._uring, fds,
                                             self.rx_buf)
        except OSError:
            self._uring_dead = True
            return None
        return results, enters, 'uring'

    # -- introspection --------------------------------------------------

    def shard_census(self) -> list[int]:
        """Connections per shard (the mntr per-shard census rows)."""
        return [len(s.conns) for s in self.shards]


def make_plane(server, shards: int | None, backend: str | None,
               collector=None) -> IngressPlane | None:
    """Build one server's ingress plane, or None when the resolved
    configuration is the single-loop validator (``shards=1`` or a
    resolved ``asyncio`` backend — ``asyncio.start_server`` then
    serves exactly as before)."""
    nshards = resolve_shards(shards)
    resolved = resolve_backend(backend)
    if nshards <= 1 or resolved == 'asyncio':
        return None
    return IngressPlane(server, nshards, resolved,
                        collector=collector)


def scrape_recv_cells(collector) -> dict:
    """Summarize the receive-direction counters for bench cells
    (bench.py --ingress): submissions by backend plus drain-depth
    distribution — the rx sibling of the transport tier's syscall
    scrape."""
    out: dict = {}
    try:
        ctr = collector.get_collector(METRIC_RECV_SYSCALLS)
    except ValueError:
        ctr = None
    if ctr is not None:
        by_backend = {}
        for key in ctr.label_keys():
            labels = dict(key)
            if labels.get('plane') == 'server':
                by_backend[labels.get('backend', '?')] = \
                    ctr.value(labels)
        if by_backend:
            out['recv_syscalls'] = by_backend
    try:
        dep = collector.get_collector(METRIC_RECV_DRAIN_DEPTH)
    except ValueError:
        dep = None
    if dep is not None:
        # every server-plane backend series (a uring plane latched
        # down mid-cell reports under both tiers — the scrape must
        # cover all of a cell's drains, like the syscalls scrape)
        by_backend = {}
        for key in dep.label_keys():
            labels = dict(key)
            if labels.get('plane') != 'server':
                continue
            n = dep.count(labels)
            if n:
                by_backend[labels.get('backend', '?')] = {
                    'drains': n,
                    'mean': round(dep.sum(labels) / n, 1),
                    'p99': round(dep.percentile(99, labels), 1)}
        if by_backend:
            out['drain_depth'] = by_backend
    return out
