"""Tick-corked outbound write coalescing — the send-side twin of the
batched ingest drain.

Without it, every client op and every server reply is its own
``transport.write`` — one syscall per frame, the per-message overhead
the RPC-batching literature (PAPERS.md: RPCAcc, the transparent
InfiniBand transports) amortizes at the transport boundary.  A
``SendPlane`` sits between a connection's encoder and its transport:
frames appended during one event-loop iteration are joined and written
as a single buffer when the loop's ready-callback batch drains (one
``call_soon``-scheduled flush per busy tick), with a size-capped early
flush so a large burst cannot balloon the cork.  ``TCP_NODELAY`` is
set on both ends (utils/aio.set_nodelay) so batching is this explicit
per-tick cork, not the kernel's implicit per-RTT one.

Ordering contract: every byte a connection sends goes through its
plane in call order — either corked (``send``) or after an explicit
``flush_hard`` for paths that must hit the wire mid-tick (fault
injection delivering a truncated frame before its scheduled reset,
CLOSE_SESSION ahead of ``write_eof``, a server connection closing).
Server planes may additionally carry a durability barrier: corked
acks wait (still corked, still ordered) for the WAL's off-loop group
fsync before they reach the transport — see ``barrier`` below and
server/persist.py.
The fault injector's tx hooks stay a per-frame boundary: injection
happens *before* the cork, and an injected delivery pre-flushes the
plane so the faulted frame cannot reorder ahead of earlier corked
frames.

Observability: per-flush batch size lands in the
``zookeeper_flush_batch_frames`` / ``zookeeper_flush_batch_bytes``
histograms (labelled ``plane="client"|"server"``; the watch table's
per-shard fan-out flushes record under ``plane="fanout"``,
server/watchtable.py), scraped by bench.py write-heavy cells,
``bench.py --fanout`` and tools/sweep_crossover.py.

``ZKSTREAM_NO_CORK=1`` (or ``cork=False`` on Client / ZKServer)
degrades to write-through — every frame still flows through the plane
(and the histograms), it just flushes per frame.

Beneath the plane sits the batched-syscall transport tier
(io/transport.py, ``ZKSTREAM_TRANSPORT=uring|mmsg|asyncio``): when a
tier is attached, a flush hands its chunk list to the tier's
per-tick submission queue instead of joining and writing — one
io_uring submission (or one C writev batch) then covers EVERY dirty
connection of the tick.  The plane's contracts are tier-independent:
``flush_hard`` still puts bytes on the wire before returning (the
tier drains that entry synchronously), the durability barrier still
gates BEFORE bytes reach any queue, and a disabled cork bypasses the
tier entirely (the frame-per-syscall validator).  The
``ZKSTREAM_FLUSH_CAP`` env (``flush_cap=`` on Client / ZKServer)
resizes the early-flush cap.

The receive direction mirrors this stack one module over
(io/ingress.py): accept shards + one batched receive drain per dirty
shard per tick beneath the unchanged decode path, with the
connection's accept shard doubling as its watch fan-out shard — so a
connection's corked replies, buffered notifications and drained
requests all live with one shard.
"""

from __future__ import annotations

import os

from ..utils.aio import ambient_loop
from .transport import METRIC_FLUSH_SYSCALLS

METRIC_FLUSH_FRAMES = 'zookeeper_flush_batch_frames'
METRIC_FLUSH_BYTES = 'zookeeper_flush_batch_bytes'

#: Frames-per-flush distribution buckets (a flush of 1 = no batching
#: happened this tick; the interesting mass is 2+).
FRAME_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)
#: Bytes-per-flush distribution buckets.
BYTE_BUCKETS = (64, 256, 1024, 4096, 16384, 65536, 262144, 1048576)

#: Early-flush cap: a burst larger than this flushes immediately
#: instead of waiting for the tick boundary (bounds cork memory and
#: keeps huge writes streaming).
DEFAULT_MAX_CORK = 256 * 1024


def cork_default() -> bool:
    """Process-wide default for new planes (env kill switch)."""
    return os.environ.get('ZKSTREAM_NO_CORK') != '1'


def flush_cap_default() -> int:
    """The early-flush cap for new planes: ``ZKSTREAM_FLUSH_CAP``
    (bytes) when set and positive, else :data:`DEFAULT_MAX_CORK`."""
    try:
        v = int(os.environ.get('ZKSTREAM_FLUSH_CAP', ''))
    except ValueError:
        return DEFAULT_MAX_CORK
    return v if v > 0 else DEFAULT_MAX_CORK


class SendPlane:
    """One connection's outbound cork.

    ``write`` is the underlying sink (``transport.write`` behind a
    liveness guard); it is only ever called with already-encoded,
    already-fault-screened frame bytes, joined in append order.
    """

    __slots__ = ('_write', '_chunks', '_pending', '_scheduled',
                 'enabled', 'max_bytes', '_frames_hist', '_bytes_hist',
                 '_labels', '_barrier', '_ledger', '_tier', '_entry',
                 '_syscall_ctr', '_transport_fn')

    def __init__(self, write, *, enabled: bool | None = None,
                 max_bytes: int | None = None,
                 collector=None, plane: str = 'client',
                 barrier=None, ledger=None,
                 tier=None, transport_fn=None):
        self._write = write
        #: Optional io/transport.TransportTier + the live-transport
        #: accessor it resolves an fd from: flushed chunk lists defer
        #: to the tier's per-tick batched submission instead of being
        #: joined and written here.  The cork kill switch bypasses it
        #: (write-through means frame-per-syscall, the validator).
        self._tier = tier
        self._entry = (tier.channel(write, transport_fn)
                       if tier is not None and transport_fn is not None
                       else None)
        #: Kept tier or no tier: :meth:`buffered_bytes` needs the live
        #: transport to include its write buffer in the tx account.
        self._transport_fn = transport_fn
        #: Optional utils/metrics.TickLedger (server planes): flush
        #: time lands in the ``cork_flush`` tick phase, loop-blocking
        #: barrier time in ``fsync_gate``.
        self._ledger = ledger
        #: Optional durability barrier gating corked bytes
        #: (server/persist.py WriteAheadLog): the acks of one tick
        #: share one group fsync, and no ack byte reaches the sink
        #: before its txn is on disk.  ``barrier.gate_flush(release)``
        #: returns True when everything appended is already durable;
        #: otherwise the flush stays corked, a group fsync runs on an
        #: executor thread (the loop keeps serving), and ``release``
        #: re-flushes when durability catches up.  Paths that must
        #: hit the wire mid-tick use :meth:`flush_hard`, which takes
        #: the barrier synchronously instead.  With the cork disabled
        #: frames still flow through the gate one by one — stricter,
        #: never weaker.
        self._barrier = barrier
        self._chunks: list[bytes] = []
        self._pending = 0
        self._scheduled = False
        self.enabled = cork_default() if enabled is None else enabled
        self.max_bytes = (flush_cap_default() if max_bytes is None
                          else max_bytes)
        self._frames_hist = None
        self._bytes_hist = None
        self._syscall_ctr = None
        self._labels = {'plane': plane}
        if collector is not None:
            self._frames_hist = collector.histogram(
                METRIC_FLUSH_FRAMES,
                'Frames per coalesced transport write, by plane',
                buckets=FRAME_BUCKETS)
            self._bytes_hist = collector.histogram(
                METRIC_FLUSH_BYTES,
                'Bytes per coalesced transport write, by plane',
                buckets=BYTE_BUCKETS)
            self._syscall_ctr = collector.counter(
                METRIC_FLUSH_SYSCALLS,
                'Write submissions issued by the outbound plane, by '
                'plane and backend')

    @property
    def pending(self) -> int:
        """Bytes appended but not yet flushed."""
        return self._pending

    def buffered_bytes(self) -> int:
        """Everything this connection has accepted for transmission
        but not yet handed to the kernel: the cork's pending bytes,
        the transport tier entry's deferred chunks, and the asyncio
        transport's own write buffer — the tx-side account the
        overload plane's watermarks compare against (io/overload.py).
        A stalled reader grows exactly this number."""
        n = self._pending
        e = self._entry
        if e is not None:
            n += e.nbytes
        t = (self._transport_fn() if self._transport_fn is not None
             else None)
        if t is not None:
            try:
                n += t.get_write_buffer_size()
            except (OSError, RuntimeError, AttributeError):
                pass
        return n

    def send(self, data: bytes) -> None:
        """Append one encoded frame; it reaches the sink at the next
        tick flush (or immediately: cork disabled / size cap hit)."""
        if not self.enabled:
            if self._barrier is None:
                self._observe(1, len(data))
                self._count_legacy()
                self._write(data)
                return
            # write-through still rides the gate: the frame corks for
            # exactly one (usually immediate) gated flush
            self._chunks.append(data)
            self._pending += len(data)
            self.flush_now()
            return
        self._chunks.append(data)
        self._pending += len(data)
        if self._pending >= self.max_bytes:
            self.flush_now()
            return
        if not self._scheduled:
            self._scheduled = True
            if self._entry is not None:
                # a transport tier owns the tick boundary: ONE loop
                # callback flushes every registered plane and submits
                # the whole batch (instead of one call_soon per
                # connection per tick)
                self._tier.schedule_flush(self)
            else:
                ambient_loop().call_soon(self._tick_flush)

    def _tick_flush(self) -> None:
        self._scheduled = False
        self.flush_now()

    def send_flush(self, data: bytes) -> None:
        """Append one frame and flush immediately — for callers that
        ARE the tick boundary (the watch table's per-shard fan-out
        flush, server/watchtable.py): scheduling the usual deferred
        tick flush from here would add one loop-callback round trip
        per connection per tick, the dominant cost of a 100k-watcher
        fan-out.  Anything already corked (this tick's replies)
        leaves in the same buffer, order preserved; the durability
        barrier is honored exactly as in :meth:`flush_now`."""
        self._chunks.append(data)
        self._pending += len(data)
        self.flush_now()

    def flush_now(self) -> None:
        """Write everything corked, in order, as one buffer — once the
        durability barrier (if any) clears.  A gated flush keeps the
        frames corked while the group fsync runs off-loop and re-runs
        when it completes, so the stream order never changes; callers
        that need the bytes on the wire before they return use
        :meth:`flush_hard`."""
        if not self._chunks:
            return
        if self._barrier is not None:
            led = self._ledger
            if led is not None:
                # the barrier may take the fsync inline (fast-device
                # short-circuit): that is loop-blocked durability time
                led.enter('fsync_gate')
                try:
                    clear = self._barrier.gate_flush(self.flush_now)
                finally:
                    led.exit()
            else:
                clear = self._barrier.gate_flush(self.flush_now)
            if not clear:
                return          # durability pending: released later
        self._write_out()

    def flush_hard(self) -> None:
        """Barrier taken synchronously, bytes written before return —
        for paths where later writes must not overtake (fault-injected
        delivery, CLOSE_SESSION ahead of EOF, connection close).  With
        a transport tier attached the entry's pending bytes are
        submitted on the spot (single-entry submission), so the
        synchronous contract holds on every backend."""
        if self._barrier is not None:
            led = self._ledger
            if led is not None:
                led.enter('fsync_gate')
                try:
                    self._barrier.sync_for_flush()
                finally:
                    led.exit()
            else:
                self._barrier.sync_for_flush()
        self._write_out(hard=True)

    def _write_out(self, hard: bool = False) -> None:
        if not self._chunks:
            # a hard flush must still drain bytes an earlier flush
            # (cap hit, barrier release) parked in the tier entry —
            # or a direct write issued right after would overtake them
            if hard and self._entry is not None:
                self._tier.drain(self._entry)
            return
        chunks = self._chunks
        n = len(chunks)
        size = self._pending
        self._chunks = []
        self._pending = 0
        self._observe(n, size)
        entry = self._entry
        if entry is not None and self.enabled:
            # deferred to the tier's tick submission (one batched
            # syscall chain covering every dirty connection); the
            # tier accounts the syscalls and the ledger's cork_flush.
            # A hard flush drains this entry synchronously instead.
            self._tier.enqueue(entry, chunks, size)
            if hard:
                self._tier.drain(entry)
            return
        self._count_legacy()
        led = self._ledger
        data = chunks[0] if n == 1 else b''.join(chunks)
        if led is not None:
            led.enter('cork_flush')
            try:
                self._write(data)
            finally:
                led.exit()
        else:
            self._write(data)

    def reset(self) -> None:
        """Drop corked frames without writing (connection aborted:
        the bytes have nowhere to go) — anything already deferred to
        the transport tier goes with them."""
        self._chunks = []
        self._pending = 0
        if self._entry is not None:
            self._tier.discard(self._entry)

    def _observe(self, frames: int, nbytes: int) -> None:
        if self._frames_hist is not None:
            self._frames_hist.observe(frames, self._labels)
            self._bytes_hist.observe(nbytes, self._labels)

    def _count_legacy(self) -> None:
        if self._syscall_ctr is not None:
            self._syscall_ctr.increment(
                {'plane': self._labels['plane'], 'backend': 'asyncio'})


def scrape_flush_cells(collector) -> dict:
    """Summarize the flush-batch histograms per plane for bench cells
    (bench.py client_ops, tools/sweep_crossover.py): flush count,
    mean/p50/p99 frames per flush, p50/p99 bytes per flush."""
    out: dict = {}
    try:
        fr = collector.get_collector(METRIC_FLUSH_FRAMES)
        by = collector.get_collector(METRIC_FLUSH_BYTES)
    except ValueError:
        return out
    for key in fr.label_keys():
        labels = dict(key)
        n = fr.count(labels)
        if not n:
            continue
        out[labels.get('plane', '')] = {
            'flushes': n,
            'frames_mean': round(fr.sum(labels) / n, 2),
            'frames_p50': round(fr.percentile(50, labels), 2),
            'frames_p99': round(fr.percentile(99, labels), 2),
            'bytes_p50': round(by.percentile(50, labels), 1),
            'bytes_p99': round(by.percentile(99, labels), 1),
        }
    return out
