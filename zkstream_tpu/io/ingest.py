"""Fleet ingest: the runtime consumer of the TPU wire-decode plane.

The reference drains every connection with its own scalar loop — bytes
-> frames -> header dispatch, once per socket
(lib/zk-streams.js:39-99, lib/connection-fsm.js:213-229).  This module
replaces that per-socket drain at fleet scale: N live connections
append their received bytes to per-connection accumulators, and a
per-event-loop-tick batcher pads them into one [B, L] tensor, runs
:func:`zkstream_tpu.ops.pipeline.wire_pipeline_step` (plus, in
``body_mode='device'``, :func:`~zkstream_tpu.ops.replies.parse_reply_bodies`)
in a single device dispatch, and routes the results back on host —
reply packets to each connection's pending-request futures via the
normal ``packet``/``process_reply`` path, notifications to the session
watcher engine.  Observable semantics are identical to the scalar
drain; the integration tests (tests/test_ingest.py) assert this over
hundreds of live connections.

Division of labor per tick:

- **device**: frame boundary scan, reply-header parse (xid/zxid/err),
  per-stream routing counts, bad-frame flags — the O(bytes) work;
- **host**: per-frame packet-dict assembly.  In ``body_mode='host'``
  the opcode-specific body is parsed by the scalar readers positioned
  at the device-located body offset (no re-framing, exact parity by
  construction).  In ``body_mode='device'`` fixed-layout bodies
  (Stat / data / create-path / notification) come from the tensor
  planes, with the scalar readers as fallback for list-shaped bodies
  (children / ACL), oversized variable fields, and malformed frames —
  so a protocol violation raises byte-for-byte the same error the
  scalar codec would.

Streams flagged ``bad`` by the device scan re-run through the
connection's own ``PacketCodec`` so the error surfaced (BAD_LENGTH /
BAD_DECODE, with pre-error packets attached) matches the scalar path
exactly.

The tick is synchronous inside the event loop: all ``data_received``
callbacks of one select cycle run before the ``call_soon``-scheduled
tick, so one dispatch coalesces everything the loop just read.
"""

from __future__ import annotations

import asyncio
from typing import TYPE_CHECKING

import numpy as np

from ..protocol.consts import REPLY_HDR, SPECIAL_XIDS, err_name
from ..protocol.errors import ZKProtocolError
from ..protocol.jute import JuteReader
from ..protocol.records import (
    _EMPTY_RESPONSES,
    _RESP_READERS,
)
from ..utils.logging import Logger

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .connection import ZKConnection  # noqa: quoted annotations

def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class FleetIngest:
    """Batches the byte streams of many live connections through the
    device wire pipeline, one dispatch per event-loop tick.

    Args:
      max_frames: static per-stream frame bound per tick; streams with
        more complete frames buffered are finished on follow-up ticks.
      body_mode: ``'host'`` (device framing/headers, scalar body
        readers) or ``'device'`` (tensor body parse with scalar
        fallback).
      max_data / max_path: static widths for the device body planes
        (``body_mode='device'`` only); larger fields fall back to the
        scalar reader.
      min_len: smallest padded stream length, to bound jit cache churn.
      log: parent logger.
    """

    def __init__(self, max_frames: int = 32, body_mode: str = 'host',
                 max_data: int = 256, max_path: int = 256,
                 min_len: int = 256, placement: str = 'auto',
                 latency_budget_ms: float = 5.0,
                 bypass_bytes: int = 32768,
                 log: Logger | None = None):
        assert body_mode in ('host', 'device'), body_mode
        assert placement in ('auto', 'accelerator', 'host'), placement
        self.max_frames = max_frames
        self.body_mode = body_mode
        self.max_data = max_data
        self.max_path = max_path
        self.min_len = min_len
        #: Small-tick crossover: when a tick holds fewer than this many
        #: buffered wire bytes in total, the batch dispatch + readback
        #: costs more than it saves, so the tick drains each stream
        #: through its connection's own scalar codec (C-accelerated
        #: when built) instead — identical observable semantics, the
        #: scalar path being the spec.  0 forces every tick onto the
        #: device pipeline (tests, benchmarks).
        self.bypass_bytes = bypass_bytes
        #: Where the tick's XLA program runs.  A tick is latency-bound
        #: (one dispatch + one readback inside the event loop), so
        #: 'auto' probes the default accelerator's dispatch->readback
        #: round trip once and falls back to the host CPU backend when
        #: the link cannot meet ``latency_budget_ms`` (e.g. a tunneled
        #: remote TPU, ~70 ms RTT); throughput work (bulk decode,
        #: benchmarks) is unaffected and stays on the accelerator.
        self.placement = placement
        self.latency_budget_ms = latency_budget_ms
        self._device = None        # resolved lazily at first tick
        self._placed = False
        self.log = (log or Logger()).child(component='FleetIngest')
        #: id(conn) -> (conn, accumulator)
        self._slots: dict[int, tuple['ZKConnection', bytearray]] = {}
        self._scheduled = False
        #: diagnostics for tests/benchmarks (``ticks`` counts device
        #: ticks; small ticks under ``bypass_bytes`` count separately)
        self.ticks = 0
        self.ticks_scalar = 0
        self.frames_routed = 0
        self._fns: dict = {}

    # -- connection registry --

    def register(self, conn: 'ZKConnection') -> None:
        slot = self._slots.setdefault(id(conn), (conn, bytearray()))
        # A partial steady-state frame may have ridden the same TCP
        # segment as the ConnectResponse: migrate it out of the scalar
        # decoder so no byte is stranded there.
        if conn.codec is not None:
            resid = conn.codec.take_pending()
            if resid:
                slot[1].extend(resid)
                self._schedule()

    def unregister(self, conn: 'ZKConnection') -> None:
        slot = self._slots.pop(id(conn), None)
        # Return unprocessed bytes to the scalar decoder: the closing
        # state keeps draining replies through the codec.
        if slot is not None and slot[1] and conn.codec is not None:
            conn.codec.restore_pending(bytes(slot[1]))

    def feed(self, conn: 'ZKConnection', data: bytes) -> None:
        slot = self._slots.get(id(conn))
        if slot is None:  # raced a teardown; the bytes die with the conn
            return
        slot[1].extend(data)
        self._schedule()

    def _schedule(self) -> None:
        if not self._scheduled:
            self._scheduled = True
            asyncio.get_running_loop().call_soon(self._tick)

    # -- the per-tick batch --

    # int32 plane order in the packed tick output; the head columns
    # (n_frames, resid, bad) come first, then these [B, F] planes.
    _HDR_PLANES = ('starts', 'sizes', 'xids', 'errs',
                   'zxid_hi', 'zxid_lo')
    # ReplyBodies int planes appended in device mode (Stat planes are
    # flattened via StatPlanes._fields).
    _BD_PLANES = ('data_len', 'str0_len', 'ntype', 'nstate',
                  'npath_len', 'data_ok', 'str0_ok', 'npath_ok')

    def _step_fn(self, device_bodies: bool):
        """Build (and cache) the jitted one-dispatch decode for this
        configuration; shapes vary per call, jit caches per shape.

        Everything the host needs comes back as ONE packed int32 array
        (plus one uint8 array in device-body mode): on a tunneled
        remote TPU every readback costs milliseconds, so the per-tick
        readback count — not the decode itself — would otherwise
        dominate end-to-end latency."""
        key = device_bodies
        fn = self._fns.get(key)
        if fn is None:
            import jax
            import jax.numpy as jnp

            from ..ops.pipeline import wire_pipeline_step
            from ..ops.replies import StatPlanes, parse_reply_bodies

            def pack_ints(st, extra=()):
                head = jnp.stack(
                    [st.n_frames, st.resid,
                     st.bad.astype(jnp.int32)], axis=1)     # [B, 3]
                planes = [getattr(st, f) for f in self._HDR_PLANES]
                planes += list(extra)
                flat = jnp.stack(planes, axis=1)            # [B, K, F]
                B = flat.shape[0]
                return jnp.concatenate(
                    [head, flat.reshape(B, -1)], axis=1)

            if device_bodies:
                def step(buf, lens, max_frames, max_data, max_path):
                    st = wire_pipeline_step(buf, lens,
                                            max_frames=max_frames)
                    bd = parse_reply_bodies(buf, st.starts, st.sizes,
                                            max_data=max_data,
                                            max_path=max_path)
                    extra = []
                    for sp in (bd.stat0, bd.stat_after_data):
                        extra += [getattr(sp, f).astype(jnp.int32)
                                  for f in StatPlanes._fields]
                    extra += [getattr(bd, f).astype(jnp.int32)
                              for f in self._BD_PLANES]
                    ints = pack_ints(st, extra)
                    byts = jnp.concatenate(
                        [bd.data, bd.str0, bd.npath], axis=2)
                    return ints, byts
                fn = jax.jit(step, static_argnames=(
                    'max_frames', 'max_data', 'max_path'))
            else:
                def step(buf, lens, max_frames):
                    return pack_ints(
                        wire_pipeline_step(buf, lens,
                                           max_frames=max_frames))
                fn = jax.jit(step, static_argnames=('max_frames',))
            self._fns[key] = fn
        return fn

    def _unpack(self, ints, byts):
        """Rebuild the host-side stat/body views from the packed
        arrays (numpy views, no copies)."""
        import types

        from ..ops.replies import StatPlanes

        B = ints.shape[0]
        F = self.max_frames
        head, flat = ints[:, :3], ints[:, 3:].reshape(B, -1, F)
        fields = dict(n_frames=head[:, 0], resid=head[:, 1],
                      bad=head[:, 2])
        names = list(self._HDR_PLANES)
        if byts is not None:
            names += ['stat0.' + f for f in StatPlanes._fields]
            names += ['stat_after_data.' + f for f in StatPlanes._fields]
            names += list(self._BD_PLANES)
        for k, name in enumerate(names):
            fields[name] = flat[:, k]
        st = types.SimpleNamespace(**{
            k: v for k, v in fields.items() if '.' not in k})
        bd = None
        if byts is not None:
            def stat(prefix):
                vals = {f: fields[prefix + '.' + f]
                        for f in StatPlanes._fields}
                vals['valid'] = vals['valid'].astype(bool)
                return StatPlanes(**vals)
            bd = types.SimpleNamespace(
                stat0=stat('stat0'),
                stat_after_data=stat('stat_after_data'),
                data=byts[:, :, :self.max_data],
                str0=byts[:, :, self.max_data:
                          self.max_data + self.max_path],
                npath=byts[:, :, self.max_data + self.max_path:],
                **{f: fields[f] for f in self._BD_PLANES})
        return st, bd

    @staticmethod
    def _cpu_device(timeout_s: float = 15.0):
        """Initialize and return the host CPU backend's device, bounded
        in time: PJRT client creation for a second backend can block
        indefinitely in degraded environments (observed with a wedged
        remote-TPU tunnel), and a latency *optimization* must never be
        able to hang the runtime.  Returns None on timeout/failure (the
        ticks then stay on the default device)."""
        import threading

        out: dict = {}

        def init():
            try:
                import jax
                out['dev'] = jax.devices('cpu')[0]
            except Exception:
                out['dev'] = None
        t = threading.Thread(target=init, daemon=True)
        t.start()
        t.join(timeout_s)
        return out.get('dev')

    def _resolve_placement(self) -> None:
        """Pick the tick's execution device (once, at first tick)."""
        if self._placed:
            return
        self._placed = True
        import time

        import jax
        import jax.numpy as jnp

        if self.placement == 'accelerator':
            return
        cpu = self._cpu_device()
        if cpu is None:
            self.log.warning('host CPU backend unavailable; ticks stay '
                             'on the default device')
            return
        if self.placement == 'host':
            self._device = cpu
            return
        if jax.default_backend() == 'cpu':
            return
        # auto: measure the dispatch->readback round trip of a trivial
        # program on the default device — the floor every tick pays.
        probe = jax.jit(lambda x: x + 1)
        x = jnp.zeros((8,), jnp.int32)
        np.asarray(probe(x))  # compile + first (poisoning) readback
        t0 = time.perf_counter()
        for _ in range(3):
            np.asarray(probe(x))
        rtt_ms = (time.perf_counter() - t0) / 3 * 1e3
        if rtt_ms > self.latency_budget_ms:
            self._device = cpu
            self.log.info(
                'accelerator dispatch+readback RTT %.1f ms exceeds the '
                '%.1f ms tick budget; running ticks on the host CPU '
                'backend', rtt_ms, self.latency_budget_ms)

    def _tick(self) -> None:
        self._scheduled = False
        active = [(conn, buf) for conn, buf in self._slots.values()
                  if buf and conn.is_in_state('connected')]
        if not active:
            return
        if self.bypass_bytes and sum(
                len(buf) for _c, buf in active) < self.bypass_bytes:
            self.ticks_scalar += 1
            for conn, buf in active:
                if id(conn) not in self._slots:  # torn down mid-tick
                    continue
                self._deliver_scalar(conn, buf)
            return
        self.ticks += 1
        self._resolve_placement()

        B = len(active)
        L = _next_pow2(max(self.min_len,
                           max(len(buf) for _c, buf in active)))
        Bp = _next_pow2(max(B, 8))
        batch = np.zeros((Bp, L), np.uint8)
        lens = np.zeros((Bp,), np.int32)
        for i, (_conn, buf) in enumerate(active):
            # frombuffer views the bytearray; the assignment copies it
            # into the batch row before anything can mutate it
            batch[i, :len(buf)] = np.frombuffer(buf, np.uint8)
            lens[i] = len(buf)

        import contextlib

        import jax

        device = self.body_mode == 'device'
        fn = self._step_fn(device)
        ctx = (jax.default_device(self._device) if self._device is not
               None else contextlib.nullcontext())
        with ctx:
            if device:
                ints, byts = fn(batch, lens, self.max_frames,
                                self.max_data, self.max_path)
                ints = np.asarray(ints)  # the only 2 readbacks per tick
                byts = np.asarray(byts)
            else:
                ints = np.asarray(fn(batch, lens, self.max_frames))
                byts = None
        st, bd = self._unpack(ints, byts)

        retick = False
        for i, (conn, buf) in enumerate(active):
            # A user callback from an earlier stream's delivery may
            # have torn this connection down mid-tick (unregister
            # already restored its bytes to the codec): skip it.
            if id(conn) not in self._slots:
                continue
            n = int(st.n_frames[i])
            if bool(st.bad[i]):
                # Exact scalar-error parity: re-run this stream through
                # the connection's own codec, which raises BAD_LENGTH/
                # BAD_DECODE with the pre-error packets attached.
                self._deliver_fallback(conn, buf)
                continue
            pkts, err = self._assemble_stream(conn, buf, st, bd, i, n)
            resid = int(st.resid[i])
            if resid:
                del buf[:resid]
            self.frames_routed += n
            if err is None and n == self.max_frames and len(buf) >= 4:
                retick = True  # more complete frames may be buffered
            if pkts or err is not None:
                conn.emit('ingestDeliver', pkts, err)
        if retick:
            self._schedule()

    def _deliver_scalar(self, conn: 'ZKConnection', buf: bytearray,
                        keep_stream: bool = True) -> None:
        """Drain one stream through the connection's own codec and emit
        the result — the scalar-parity delivery shared by the small-tick
        bypass (``keep_stream=True``: partial-frame residue returns to
        this slot's accumulator, traffic is counted) and the bad-frame
        fallback (``keep_stream=False``: the error the codec raises is
        the point; the stream is about to die)."""
        data, err, pkts = bytes(buf), None, []
        buf.clear()
        try:
            pkts = conn.codec.decode(data)
        except ZKProtocolError as e:
            pkts = getattr(e, 'packets', [])
            err = e
        else:
            if keep_stream:
                resid = conn.codec.take_pending()
                if resid:
                    buf.extend(resid)
        if keep_stream:
            self.frames_routed += len(pkts)
            if not pkts and err is None:
                return
        conn.emit('ingestDeliver', pkts, err)

    def _deliver_fallback(self, conn: 'ZKConnection',
                          buf: bytearray) -> None:
        self._deliver_scalar(conn, buf, keep_stream=False)

    # -- host packet assembly --

    def _assemble_stream(self, conn, buf, st, bd, i: int, n: int):
        """Build the packet dicts for stream ``i``'s ``n`` frames.
        Returns (packets, err); a decode failure mid-stream keeps the
        packets decoded before it, like PacketCodec.decode."""
        from ..ops.bytesops import i64pair_to_int

        pkts: list[dict] = []
        xid_map = conn.codec.xid_map
        for f in range(n):
            xid = int(st.xids[i, f])
            opcode = SPECIAL_XIDS.get(xid)
            if opcode is None:
                opcode = xid_map.pop(xid, None)
            if opcode is None:
                return pkts, ZKProtocolError('BAD_DECODE',
                    'Failed to decode Response: ValueError: reply xid '
                    '%d matches no request' % (xid,))
            pkt = {
                'xid': xid,
                'zxid': i64pair_to_int(st.zxid_hi[i, f],
                                       st.zxid_lo[i, f]),
                'err': err_name(int(st.errs[i, f])),
                'opcode': opcode,
            }
            if pkt['err'] == 'OK' and opcode not in _EMPTY_RESPONSES:
                try:
                    self._read_body(pkt, buf, st, bd, i, f)
                except ZKProtocolError as e:
                    return pkts, e
                except Exception as e:
                    err = ZKProtocolError('BAD_DECODE',
                        'Failed to decode Response: %s: %s'
                        % (type(e).__name__, e))
                    err.__cause__ = e
                    return pkts, err
            pkts.append(pkt)
        return pkts, None

    def _read_body(self, pkt, buf, st, bd, i: int, f: int) -> None:
        """Fill ``pkt`` with its opcode-specific body."""
        opcode = pkt['opcode']
        if bd is not None:
            if self._read_body_device(pkt, bd, i, f):
                return
        # Scalar reader positioned at the device-located body offset.
        start = int(st.starts[i, f])
        size = int(st.sizes[i, f])
        r = JuteReader(bytes(buf[start + REPLY_HDR:start + size]))
        reader = _RESP_READERS.get(opcode)
        if reader is None:
            raise ValueError('unsupported reply opcode %r' % (opcode,))
        reader(r, pkt)

    def _read_body_device(self, pkt, bd, i: int, f: int) -> bool:
        """Assemble the body from the tensor planes; False = this frame
        needs the scalar fallback (list-shaped, oversized, malformed)."""
        from ..ops.replies import stat_from_planes
        from ..protocol.consts import KeeperState, NotificationType

        opcode = pkt['opcode']
        if opcode in ('EXISTS', 'SET_DATA'):
            if not bool(bd.stat0.valid[i, f]):
                return False  # truncated: scalar reader raises exactly
            pkt['stat'] = stat_from_planes(bd.stat0, i, f)
            return True
        if opcode == 'GET_DATA':
            dlen = int(bd.data_len[i, f])
            if dlen > self.max_data or not bool(bd.data_ok[i, f]) or \
                    not bool(bd.stat_after_data.valid[i, f]):
                return False
            pkt['data'] = bytes(bd.data[i, f, :max(dlen, 0)])
            pkt['stat'] = stat_from_planes(bd.stat_after_data, i, f)
            return True
        if opcode == 'CREATE':
            slen = int(bd.str0_len[i, f])
            # not-ok = the length field points past the frame: fall
            # back so the scalar reader raises BAD_DECODE, exactly as
            # the scalar drain would
            if slen > self.max_path or not bool(bd.str0_ok[i, f]):
                return False
            pkt['path'] = bytes(bd.str0[i, f, :max(slen, 0)]).decode()
            return True
        if opcode == 'NOTIFICATION':
            plen = int(bd.npath_len[i, f])
            if plen > self.max_path or not bool(bd.npath_ok[i, f]):
                return False
            pkt['type'] = NotificationType(int(bd.ntype[i, f])).name
            pkt['state'] = KeeperState(int(bd.nstate[i, f])).name
            pkt['path'] = bytes(bd.npath[i, f, :max(plen, 0)]).decode()
            return True
        return False  # children / ACL lists: scalar reader
