"""Fleet ingest: the runtime consumer of the TPU wire-decode plane.

The reference drains every connection with its own scalar loop — bytes
-> frames -> header dispatch, once per socket
(lib/zk-streams.js:39-99, lib/connection-fsm.js:213-229).  This module
replaces that per-socket drain at fleet scale: N live connections
append their received bytes to per-connection accumulators, and a
per-event-loop-tick batcher pads them into one [B, L] tensor, runs
:func:`zkstream_tpu.ops.pipeline.wire_pipeline_step` (plus, in
``body_mode='device'``, :func:`~zkstream_tpu.ops.replies.parse_reply_bodies`)
in a single device dispatch, and routes the results back on host —
reply packets to each connection's pending-request futures via the
normal ``packet``/``process_reply`` path, notifications to the session
watcher engine.  Observable semantics are identical to the scalar
drain; the integration tests (tests/test_ingest.py) assert this over
hundreds of live connections.

Division of labor per tick:

- **device**: frame boundary scan, reply-header parse (xid/zxid/err),
  per-stream routing counts, bad-frame flags — the O(bytes) work;
- **host**: per-frame packet-dict assembly.  In ``body_mode='host'``
  the packets come from the C-extension decoder when it is loaded (one
  zero-copy pass over the device-delimited complete-frame slice —
  byte-identical to the scalar drain because it *is* the scalar
  decoder), else from the scalar readers positioned at the
  device-located body offsets.  In ``body_mode='device'`` fixed-layout
  bodies (Stat / data / create-path / notification) come from the
  tensor planes, with the scalar readers as fallback for list-shaped
  bodies (children / ACL), oversized variable fields, and malformed
  frames — so a protocol violation raises byte-for-byte the same error
  the scalar codec would.

Streams flagged ``bad`` by the device scan re-run through the
connection's own ``PacketCodec`` so the error surfaced (BAD_LENGTH /
BAD_DECODE, with pre-error packets attached) matches the scalar path
exactly.

The tick is synchronous inside the event loop: all ``data_received``
callbacks of one select cycle run before the ``call_soon``-scheduled
tick, so one dispatch coalesces everything the loop just read.

**No tick ever blocks on XLA.**  Compiling the tick program for a new
(batch, length) bucket costs ~1 s on the host CPU backend — 3 orders
of magnitude over a steady tick — and the first-dispatch latency probe
on a tunneled accelerator costs several round trips.  Both therefore
run off-loop: under the default ``warm='background'`` a tick whose
shape bucket has no compiled executable yet is delivered through the
scalar codec (identical semantics) while a daemon thread AOT-compiles
the bucket (``jit(...).lower(...).compile()``); once it lands,
subsequent ticks run the device program.  ``warm='block'`` compiles
inline on first use — deterministic, for tests and one-shot tools —
and :meth:`prewarm` lets benchmarks/servers pay the compile up front.
This is what bounds the ingest latency tail: the worst tick costs
max(scalar drain, steady device tick), never a compile
(measured: tools/diag_ingest.py; VERDICT r2 item 2).
"""

from __future__ import annotations

import asyncio
import queue
import threading
import time
from typing import TYPE_CHECKING

import numpy as np

from ..protocol.consts import MAX_PACKET, REPLY_HDR, SPECIAL_XIDS, err_name
from ..protocol.errors import ZKProtocolError
from ..protocol.jute import JuteReader
from ..protocol.records import (
    _EMPTY_RESPONSES,
    _RESP_READERS,
)
from ..utils.logging import Logger
from ..utils.metrics import Histogram

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .connection import ZKConnection  # noqa: quoted annotations

def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


#: sentinel distinguishing "never compiled" from "compile failed" in
#: the executable cache
_MISSING = object()


def _guard_warm_exit(thread: threading.Thread, q: queue.Queue) -> None:
    """Interpreter-exit guard for one warm worker.  A compile's lazy
    ``import jax`` racing jax's own atexit cache teardown in the main
    thread leaves jax half-imported while ``clear_caches`` walks it —
    observed as a segfault/abort at process exit the first time a
    server spun up LATE in a run (e.g. a member added by a runtime
    reconfiguration) queues its first bucket compile just before the
    CLI returns.  ``threading._register_atexit`` callbacks run at
    threading shutdown, BEFORE the atexit module's handlers — so
    before jax's — where a BOUNDED join lets an in-flight compile
    finish while a wedged one still cannot hang exit (the worker
    stays a daemon).  Plain atexit is the (weaker) fallback when the
    private hook is missing."""
    def _drain_and_join() -> None:
        q.put(None)
        thread.join(timeout=30.0)
    reg = getattr(threading, '_register_atexit', None)
    if reg is not None:
        try:
            reg(_drain_and_join)
            return
        except RuntimeError:    # already shutting down: nothing to do
            return
    import atexit
    atexit.register(_drain_and_join)


class FleetIngest:
    """Batches the byte streams of many live connections through the
    device wire pipeline, one dispatch per event-loop tick.

    Args:
      max_frames: static per-stream frame bound per tick; streams with
        more complete frames buffered are finished on follow-up ticks.
      body_mode: ``'host'`` (device framing/headers, C/scalar body
        readers) or ``'device'`` (tensor body parse with scalar
        fallback).
      max_data / max_path: static widths for the device body planes
        (``body_mode='device'`` only); larger fields fall back to the
        scalar reader.
      min_len: smallest padded stream length, to bound jit cache churn.
      warm: ``'background'`` (default) — a tick whose shape bucket is
        not compiled yet delivers through the scalar codec while the
        XLA program compiles on a daemon thread, so the event loop
        never blocks on a compile; ``'block'`` — compile inline on
        first use (deterministic; tests/tools).
      frag_guard: route fragmented mega-fleet ticks back to the scalar
        drain (see the attribute comment below).  Default ``None`` =
        auto: enabled for production thresholds, disabled when
        ``bypass_bytes=0`` (force-device: tests, benchmarks — "every
        tick on the device pipeline" must mean exactly that).  Pass
        ``True``/``False`` to pin it either way; the mesh proxy
        disables it.
      log: parent logger.
    """

    #: Fragmentation-guard calibration (CROSSOVER.md, 1,024-conn
    #: cells): engage only for fleets at least this large...
    FRAG_MIN_FLEET = 600
    #: ...entering scalar routing when the frames-per-tick EMA drops
    #: below ENTER x fleet size (ticks stopped being batches), leaving
    #: it again above EXIT x fleet size (hysteresis so the router
    #: cannot flap on tick-to-tick noise).
    FRAG_ENTER = 0.25
    FRAG_EXIT = 0.40

    def __init__(self, max_frames: int = 32, body_mode: str = 'host',
                 max_data: int = 256, max_path: int = 256,
                 max_children: int = 16, max_name: int = 64,
                 max_acls: int = 4, max_scheme: int = 16,
                 max_id: int = 64,
                 min_len: int = 256, placement: str = 'auto',
                 latency_budget_ms: float = 5.0,
                 bypass_bytes: int = 16384,
                 warm: str = 'background',
                 frag_guard: bool | None = None,
                 log: Logger | None = None):
        assert body_mode in ('host', 'device'), body_mode
        assert placement in ('auto', 'accelerator', 'host'), placement
        assert warm in ('background', 'block'), warm
        self.max_frames = max_frames
        self.body_mode = body_mode
        self.max_data = max_data
        self.max_path = max_path
        #: bounds for the device list parse (children / ACL replies,
        #: ops/replies.parse_list_bodies); longer lists fall back to
        #: the scalar reader per frame
        self.max_children = max_children
        self.max_name = max_name
        self.max_acls = max_acls
        self.max_scheme = max_scheme
        self.max_id = max_id
        self.min_len = min_len
        self.warm = warm
        #: Small-tick crossover: while the fleet's bytes-per-tick EMA
        #: sits under this threshold, the ingest runs as a PASS-THROUGH
        #: — ``feed`` delivers straight through each connection's own
        #: scalar codec (C-accelerated when built), no accumulator, no
        #: deferred tick — identical observable semantics, the scalar
        #: path being the spec, and none of the batching overhead the
        #: r4 re-sweep measured costing 10-24% when the old design
        #: still accumulated + tick-drained in this regime.  0 forces
        #: every tick onto the device pipeline (tests, benchmarks) —
        #: including disabling the fragmentation guard, which would
        #: otherwise still divert >=600-connection fragmented fleets
        #: to the scalar drain.  Default 16 KiB = the measured parity
        #: point (~128
        #: connections x ~135 B frames, CROSSOVER.md): below it the
        #: scalar drain wins outright; above it the device path is
        #: free e2e and adds the stats plane + device bodies +
        #: offload.
        self.bypass_bytes = bypass_bytes
        #: Where the tick's XLA program runs.  A tick is latency-bound
        #: (one dispatch + one readback inside the event loop), so
        #: 'auto' probes the default accelerator's dispatch->readback
        #: round trip once and falls back to the host CPU backend when
        #: the link cannot meet ``latency_budget_ms`` (e.g. a tunneled
        #: remote TPU, ~70 ms RTT); throughput work (bulk decode,
        #: benchmarks) is unaffected and stays on the accelerator.
        self.placement = placement
        self.latency_budget_ms = latency_budget_ms
        self._device = None        # resolved lazily at first warm
        self._placed = False
        self._place_lock = threading.Lock()
        self.log = (log or Logger()).child(component='FleetIngest')
        #: id(conn) -> (conn, accumulator)
        self._slots: dict[int, tuple['ZKConnection', bytearray]] = {}
        self._scheduled = False
        #: diagnostics for tests/benchmarks (``ticks`` counts device
        #: ticks; small ticks under ``bypass_bytes`` and ticks deferred
        #: to the scalar drain while a shape bucket compiles count
        #: separately)
        self.ticks = 0
        self.ticks_scalar = 0
        self.ticks_warming = 0
        #: Batched-drain latency distribution: wall time of each tick
        #: that routed work (device dispatch or scalar drain), ms.
        #: Standalone until bind_metrics() swaps in a collector-
        #: registered histogram at setup time.
        self.tick_hist = Histogram(
            'zkstream_ingest_tick_ms',
            'Ingest tick (batched drain) duration, milliseconds')
        #: ticks routed to the scalar drain by the fragmentation guard
        self.ticks_frag = 0
        self.frames_routed = 0
        #: Upper dispatch guard (CROSSOVER.md: at 1,024 desynchronized
        #: connections the tick batches fragment to ~16% fill and the
        #: batched path loses ~37% to the per-socket C drain — the
        #: measured losing regime the byte threshold cannot see,
        #: because fragmented mega-fleets still clear 16 KiB/tick).
        #: An EMA of frames routed per tick, compared against the
        #: registered fleet size with hysteresis, routes those ticks
        #: back to the scalar drain.  Auto (None): enabled only with a
        #: production byte threshold — ``bypass_bytes=0`` (force-device:
        #: tests, benchmarks) must mean every tick on the device
        #: pipeline, so auto disables the guard there.
        self.frag_guard = (bypass_bytes > 0 if frag_guard is None
                           else frag_guard)
        self._ema_frames: float | None = None
        self._frag_scalar = False
        #: Regime flag: in DIRECT mode ``feed`` delivers through the
        #: connection's own codec immediately — the per-socket scalar
        #: drain itself, zero accumulate/copy/defer overhead — because
        #: the dispatch policy says batching does not pay (bytes/tick
        #: under ``bypass_bytes``, or the fragmentation guard).  In
        #: BATCH mode bytes accumulate per slot and the tick
        #: dispatches the device program.  The r4 re-sweep measured
        #: the old design (accumulate + per-tick scalar drain even
        #: when bypassing) costing 10-24% vs the native drain — a
        #: replacement may never regress the drain it replaces, so the
        #: bypass is now a true pass-through.
        self._direct = bypass_bytes > 0
        self._window_bytes = 0
        self._ema_bytes: float | None = None
        self._frames_mark = 0
        #: device-body mode: frames whose body needed the scalar
        #: reader (oversized/list-overflow/malformed)
        self.body_fallbacks = 0
        self._fns: dict = {}
        #: (device_bodies, Bp, L) -> AOT executable (None = compile
        #: failed; that bucket stays on the scalar drain)
        self._exec: dict = {}
        self._warm_events: dict = {}
        #: background compiles drain FIFO through a one-thread
        #: executor (created lazily): a load pattern hopping several
        #: (Bp, L) buckets at once must not stack ~1 s XLA compiles
        #: concurrently on the host that is also serving scalar ticks
        self._warm_queue: queue.Queue | None = None
        #: Optional seeded FaultInjector (io/faults.py): tick-time
        #: faults in the BATCH regime — a slot's buffered suffix held
        #: back across a tick boundary (the device scan must handle a
        #: partial frame at an arbitrary cut and finish it next tick)
        #: or a connection reset at tick time (teardown mid-batch:
        #: unregister/restore_pending while other streams route).  In
        #: the pass-through regime the per-connection rx gate already
        #: owns byte-level faults — the drain there IS the scalar
        #: codec — so these hooks fire only on the batched tick.
        self.faults = None
        #: id(conn) -> bytes withheld from the current tick by the
        #: injector; re-appended after the tick routes (FIFO: the
        #: suffix of a slot goes back to the same position).
        self._held: dict[int, bytes] = {}
        #: slots whose withheld suffix was just released: exempt from
        #: a fresh hold for one tick, so the follow-up tick finishes
        #: the partial frame instead of re-cutting the same bytes in
        #: a busy loop until new data arrives
        self._no_hold: set[int] = set()

    # -- connection registry --

    def register(self, conn: 'ZKConnection') -> None:
        slot = self._slots.setdefault(id(conn), (conn, bytearray()))
        # A partial steady-state frame may have ridden the same TCP
        # segment as the ConnectResponse.  In the BATCH regime it must
        # migrate out of the scalar decoder into the slot (the tick
        # scan owns the stream).  In the DIRECT regime the codec keeps
        # draining the stream itself, so the residue must STAY there —
        # moving it into a slot nothing drains would strand it and
        # misframe every later byte.
        if not self._direct and conn.codec is not None:
            resid = conn.codec.take_pending()
            if resid:
                slot[1].extend(resid)
                self._schedule()

    def unregister(self, conn: 'ZKConnection') -> None:
        slot = self._slots.pop(id(conn), None)
        self._no_hold.discard(id(conn))
        held = self._held.pop(id(conn), None)
        if held is not None and slot is not None:
            slot[1].extend(held)     # withheld suffix rejoins in order
        # Return unprocessed bytes to the scalar decoder: the closing
        # state keeps draining replies through the codec.
        if slot is not None and slot[1] and conn.codec is not None:
            conn.codec.restore_pending(bytes(slot[1]))

    def feed(self, conn: 'ZKConnection', data: bytes) -> None:
        slot = self._slots.get(id(conn))
        if slot is None:  # raced a teardown; the bytes die with the conn
            return
        self._window_bytes += len(data)
        if self._direct:
            self._schedule()          # bookkeeping tick at cycle end
            if slot[1]:               # leftover from a regime flip
                slot[1].extend(data)
                data = bytes(slot[1])
                slot[1].clear()
            self._deliver_direct(conn, data)
            return
        slot[1].extend(data)
        self._schedule()

    @property
    def direct(self) -> bool:
        """True while the ingest is in its pass-through regime: the
        connection should run the per-socket drain itself and report
        the counts via :meth:`note_direct` (io/connection.py wires
        this)."""
        return self._direct

    def note_direct(self, nbytes: int, nframes: int) -> None:
        """Bookkeeping for a connection-side direct delivery: feeds
        the dispatch policy's byte/frame EMAs and schedules the
        regime-decision tick."""
        self._window_bytes += nbytes
        self.frames_routed += nframes
        self._schedule()

    def _deliver_direct(self, conn: 'ZKConnection',
                        data: bytes) -> None:
        """The pass-through drain: decode straight through the
        connection's codec (which keeps its own partial-frame state
        across feeds, exactly like the per-socket scalar drain) and
        emit.  No accumulator, no copy, no deferred tick."""
        err = None
        try:
            pkts = conn.codec.decode(data)
        except ZKProtocolError as e:
            pkts = getattr(e, 'packets', [])
            err = e
        self.frames_routed += len(pkts)
        if pkts or err is not None:
            conn.emit('ingestDeliver', pkts, err)

    def _schedule(self) -> None:
        if not self._scheduled:
            self._scheduled = True
            asyncio.get_running_loop().call_soon(self._tick)

    # -- the per-tick batch --

    # int32 plane order in the packed tick output; the head columns
    # (n_frames, resid, bad) come first, then these [B, F] planes.
    _HDR_PLANES = ('starts', 'sizes', 'xids', 'errs',
                   'zxid_hi', 'zxid_lo')

    def _body_schema(self):
        """Declarative layout of the device-body planes inside the
        packed tick output — one source of truth for the device-side
        pack and the host-side unpack.  Entry kinds:

        - ``('plane', name)``: one int32 [B, F] plane;
        - ``('multi', name, K)``: an int32 [B, F, K] tensor as K planes;
        - ``('stat', name)``: a StatPlanes (one plane per field).
        """
        K, A = self.max_children, self.max_acls
        return (
            ('stat', 'stat0'), ('stat', 'stat_after_data'),
            ('plane', 'data_len'), ('plane', 'str0_len'),
            ('plane', 'ntype'), ('plane', 'nstate'),
            ('plane', 'npath_len'), ('plane', 'data_ok'),
            ('plane', 'str0_ok'), ('plane', 'npath_ok'),
            ('plane', 'ch_count'), ('plane', 'ch_ok'),
            ('multi', 'ch_len', K),
            ('stat', 'stat_after_children'),
            ('plane', 'acl_count'), ('plane', 'acl_ok'),
            ('multi', 'acl_perms', A),
            ('multi', 'acl_scheme_len', A),
            ('multi', 'acl_id_len', A),
            ('stat', 'stat_after_acl'),
        )

    def _bytes_schema(self):
        """Widths of the uint8 [B, F, w] segments concatenated into the
        packed byte plane (4-d sources flatten their trailing axes)."""
        return (
            ('data', self.max_data),
            ('str0', self.max_path),
            ('npath', self.max_path),
            ('ch_bytes', self.max_children * self.max_name),
            ('acl_scheme', self.max_acls * self.max_scheme),
            ('acl_id', self.max_acls * self.max_id),
        )

    def _trace_step(self, buf, lens, device_bodies: bool):
        """The traced tick computation: decode ``buf``/``lens`` and
        pack the results into (ints, byts-or-None).  Pure array code —
        jitted directly here, re-wrapped in ``shard_map`` by the
        mesh-aware subclass (parallel/fleet.py)."""
        import jax.numpy as jnp

        from ..ops.pipeline import wire_pipeline_step_auto
        from ..ops.replies import (
            StatPlanes,
            parse_list_bodies,
            parse_reply_bodies,
        )

        # auto-dispatch picks the measured winner for this shape and
        # target platform (jnp on the host CPU backend; the Pallas
        # kernel only in its recorded TPU win pocket — PROFILE.md)
        st = wire_pipeline_step_auto(buf, lens,
                                     max_frames=self.max_frames)

        def pack_ints(extra=()):
            head = jnp.stack(
                [st.n_frames, st.resid,
                 st.bad.astype(jnp.int32)], axis=1)     # [B, 3]
            planes = [getattr(st, f) for f in self._HDR_PLANES]
            planes += list(extra)
            flat = jnp.stack(planes, axis=1)            # [B, K, F]
            B = flat.shape[0]
            return jnp.concatenate([head, flat.reshape(B, -1)], axis=1)

        if not device_bodies:
            return st, pack_ints(), None
        bd = parse_reply_bodies(
            buf, st.starts, st.sizes,
            max_data=self.max_data, max_path=self.max_path)
        lb = parse_list_bodies(
            buf, st.starts, st.sizes,
            max_children=self.max_children, max_name=self.max_name,
            max_acls=self.max_acls, max_scheme=self.max_scheme,
            max_id=self.max_id)

        def src(name):
            v = getattr(bd, name, None)
            return v if v is not None else getattr(lb, name)

        extra = []
        for ent in self._body_schema():
            if ent[0] == 'plane':
                extra.append(src(ent[1]).astype(jnp.int32))
            elif ent[0] == 'multi':
                t = src(ent[1]).astype(jnp.int32)
                extra += [t[:, :, k] for k in range(ent[2])]
            else:
                sp = src(ent[1])
                extra += [getattr(sp, f).astype(jnp.int32)
                          for f in StatPlanes._fields]
        B = buf.shape[0]
        byts = jnp.concatenate(
            [src(name).reshape(B, self.max_frames, -1)
             for name, _w in self._bytes_schema()], axis=2)
        return st, pack_ints(extra), byts

    def _step_fn(self, device_bodies: bool):
        """Build (and cache) the jittable one-dispatch decode for this
        configuration — the lowering source for the per-shape AOT
        executables (:meth:`_compile`).

        Everything the host needs comes back as ONE packed int32 array
        (plus one uint8 array in device-body mode): on a tunneled
        remote TPU every readback costs milliseconds, so the per-tick
        readback count — not the decode itself — would otherwise
        dominate end-to-end latency."""
        key = device_bodies
        fn = self._fns.get(key)
        if fn is None:
            import jax

            if device_bodies:
                def step(buf, lens):
                    _st, ints, byts = self._trace_step(buf, lens, True)
                    return ints, byts
            else:
                def step(buf, lens):
                    _st, ints, _n = self._trace_step(buf, lens, False)
                    return ints
            fn = jax.jit(step)
            self._fns[key] = fn
        return fn

    # -- shape-bucket warm-up (AOT compile off the event loop) --

    def _bucket(self, n_streams: int, nbytes: int) -> tuple:
        Bp = _next_pow2(max(n_streams, 8))
        L = _next_pow2(max(self.min_len, nbytes))
        return (self.body_mode == 'device', Bp, L)

    def _compile(self, key: tuple):
        """Lower + AOT-compile the tick program for one shape bucket.
        Runs on the warm thread (or inline under warm='block')."""
        import contextlib

        import jax

        device_bodies, Bp, L = key
        self._resolve_placement()
        fn = self._step_fn(device_bodies)
        batch = np.zeros((Bp, L), np.uint8)
        lens = np.zeros((Bp,), np.int32)
        ctx = (jax.default_device(self._device) if self._device is not
               None else contextlib.nullcontext())
        with ctx:
            return fn.lower(batch, lens).compile()

    def _try_compile(self, key: tuple):
        """Compile ``key``'s bucket; a failure logs and returns None
        (one policy for the inline and background warm paths)."""
        try:
            return self._compile(key)
        except Exception as e:
            self.log.warning('tick program compile failed for '
                             'bucket %r: %s', key, e)
            return None

    def _compile_or_latch(self, key: tuple):
        """Inline warm: compile and store, latching a failure as None
        so the bucket permanently drains scalar."""
        ex = self._exec[key] = self._try_compile(key)
        return ex

    def _start_warm(self, key: tuple) -> asyncio.Event:
        """Queue (or join) the background compile for ``key``;
        returns the event set when the bucket is ready (or failed).
        Compiles drain FIFO through one DAEMON worker thread, so at
        most one XLA compile runs at any moment, a failure is contained
        to its task (never to the serialization mechanism), and — the
        reason it must be a daemon, not an executor worker — a compile
        wedged on an unreachable accelerator backend can never hang
        interpreter exit (concurrent.futures joins its non-daemon
        workers at shutdown; a daemon thread just dies)."""
        ev = self._warm_events.get(key)
        if ev is not None:
            return ev
        ev = asyncio.Event()
        self._warm_events[key] = ev
        loop = asyncio.get_running_loop()
        if self._warm_queue is None:
            q = self._warm_queue = queue.Queue()

            # the drain closure must reference only the QUEUE, never
            # self: a thread parked in q.get() would otherwise pin the
            # whole ingest (compiled executables included) for the
            # process lifetime; None is the close() shutdown sentinel
            def drain():
                while True:
                    task = q.get()
                    try:
                        if task is None:
                            return
                        task()
                    except Exception:   # containment; _try_compile
                        pass            # already latches failures
                    finally:
                        q.task_done()

            t = threading.Thread(target=drain, daemon=True,
                                 name='ingest-warm')
            t.start()
            _guard_warm_exit(t, q)

        def work():
            ex = self._try_compile(key)

            def done():
                self._exec[key] = ex
                ev.set()
                # bytes may be waiting that deferred to scalar
                self._schedule()
            try:
                # the _exec write happens on the loop thread (done)
                loop.call_soon_threadsafe(done)
            except RuntimeError:     # loop closed mid-compile
                pass

        self._warm_queue.put(work)
        return ev

    def close(self) -> None:
        """Release the background warm worker (idempotent).  Queued
        compiles still drain first (FIFO), then the daemon thread
        exits; without this the parked worker lives until process
        exit — harmless (it holds only the queue, never the ingest)
        but untidy in thread dumps.  The ingest itself needs no other
        teardown: connections unregister themselves."""
        if self._warm_queue is not None:
            self._warm_queue.put(None)
            self._warm_queue = None

    def bind_metrics(self, collector, prefix: str = '') -> None:
        """Expose this ingest's tick/frame counters as pull-model
        gauges on ``collector`` (utils/metrics.Collector) — the
        observability twin of the reference's artedi counters
        (lib/client.js:29,58-61) for the batched plane.  When several
        ingests share one collector, give each a distinct ``prefix``
        (name collisions raise rather than silently dropping a
        registrant's series)."""
        for name, attr, help_text in (
                ('zkstream_ingest_ticks', 'ticks',
                 'device ticks dispatched'),
                ('zkstream_ingest_scalar_ticks', 'ticks_scalar',
                 'ticks drained through the scalar codec (bypass or '
                 'failed bucket)'),
                ('zkstream_ingest_warming_ticks', 'ticks_warming',
                 'ticks deferred to scalar while a shape bucket '
                 'compiled'),
                ('zkstream_ingest_frag_ticks', 'ticks_frag',
                 'ticks routed to the scalar drain by the '
                 'fragmentation guard (fleet large, ticks sparse)'),
                ('zkstream_ingest_frames_routed', 'frames_routed',
                 'frames delivered through the ingest'),
                ('zkstream_ingest_body_fallbacks', 'body_fallbacks',
                 'device-body frames that needed the scalar reader')):
            collector.gauge(prefix + name,
                            (lambda a=attr: getattr(self, a)),
                            help_text)
        # swap the standalone tick-duration histogram for a collector-
        # registered one; samples observed before binding stay with the
        # discarded instance (bind at setup time)
        self.tick_hist = collector.histogram(
            prefix + 'zkstream_ingest_tick_ms',
            'Ingest tick (batched drain) duration, milliseconds')

    async def prewarm(self, n_streams: int,
                      nbytes: int | None = None) -> None:
        """Compile the tick program for an expected fleet shape up
        front (servers at startup, benchmarks before timing): the
        bucket for ``n_streams`` connections holding up to ``nbytes``
        buffered bytes each tick (default: ``min_len``).  Concurrent
        prewarms for several buckets drain through the single warm
        worker one at a time (total ~= sum of compiles, not max) — the
        same serialization that keeps background warms from
        oversubscribing a host mid-service.

        On an UNREACHABLE accelerator backend (e.g. a dead tunnel)
        the XLA compile itself can block indefinitely; traffic keeps
        flowing through the scalar drain regardless (no tick ever
        waits on a compile), but this await would wait with it —
        callers that must bound startup should wrap it in
        ``asyncio.wait_for``."""
        key = self._bucket(n_streams, nbytes or self.min_len)
        if self._exec.get(key, _MISSING) is not _MISSING:
            return
        if self.warm == 'block':
            self._compile_or_latch(key)
            return
        await self._start_warm(key).wait()

    @staticmethod
    def _cpu_device(timeout_s: float = 15.0):
        """Initialize and return the host CPU backend's device, bounded
        in time: PJRT client creation for a second backend can block
        indefinitely in degraded environments (observed with a wedged
        remote-TPU tunnel), and a latency *optimization* must never be
        able to hang the runtime.  Returns None on timeout/failure (the
        ticks then stay on the default device)."""
        out: dict = {}

        def init():
            try:
                import jax
                out['dev'] = jax.devices('cpu')[0]
            except Exception:
                out['dev'] = None
        t = threading.Thread(target=init, daemon=True)
        t.start()
        t.join(timeout_s)
        return out.get('dev')

    def _resolve_placement(self) -> None:
        """Pick the tick's execution device (once, at first warm-up —
        never on the event loop under warm='background': the probe
        costs several accelerator round trips)."""
        with self._place_lock:
            if self._placed:
                return
            self._placed = True
            import time

            import jax
            import jax.numpy as jnp

            if self.placement == 'accelerator':
                return
            cpu = self._cpu_device()
            if cpu is None:
                self.log.warning('host CPU backend unavailable; ticks '
                                 'stay on the default device')
                return
            if self.placement == 'host':
                self._device = cpu
                return
            if jax.default_backend() == 'cpu':
                return
            # auto: measure the dispatch->readback round trip of a
            # trivial program on the default device — the floor every
            # tick pays.
            probe = jax.jit(lambda x: x + 1)
            x = jnp.zeros((8,), jnp.int32)
            np.asarray(probe(x))  # compile + first (poisoning) readback
            t0 = time.perf_counter()
            for _ in range(3):
                np.asarray(probe(x))
            rtt_ms = (time.perf_counter() - t0) / 3 * 1e3
            if rtt_ms > self.latency_budget_ms:
                self._device = cpu
                self.log.info(
                    'accelerator dispatch+readback RTT %.1f ms exceeds '
                    'the %.1f ms tick budget; running ticks on the '
                    'host CPU backend', rtt_ms, self.latency_budget_ms)

    def _unpack(self, ints, byts):
        """Rebuild the host-side stat/body views from the packed
        arrays (numpy views, no copies), walking the same schema the
        device-side pack wrote."""
        import types

        from ..ops.replies import StatPlanes

        B = ints.shape[0]
        F = self.max_frames
        head, flat = ints[:, :3], ints[:, 3:].reshape(B, -1, F)
        st = types.SimpleNamespace(n_frames=head[:, 0],
                                   resid=head[:, 1], bad=head[:, 2])
        k = 0
        for name in self._HDR_PLANES:
            setattr(st, name, flat[:, k])
            k += 1
        if byts is None:
            return st, None

        bd = types.SimpleNamespace()
        for ent in self._body_schema():
            if ent[0] == 'plane':
                setattr(bd, ent[1], flat[:, k])
                k += 1
            elif ent[0] == 'multi':
                K = ent[2]
                # K consecutive planes -> a [B, F, K] view
                setattr(bd, ent[1],
                        np.moveaxis(flat[:, k:k + K], 1, 2))
                k += K
            else:
                vals = {}
                for f in StatPlanes._fields:
                    vals[f] = flat[:, k]
                    k += 1
                vals['valid'] = vals['valid'].astype(bool)
                setattr(bd, ent[1], StatPlanes(**vals))
        off = 0
        for name, w in self._bytes_schema():
            setattr(bd, name, byts[:, :, off:off + w])
            off += w
        return st, bd

    def _note_frames(self, n: int) -> None:
        """Feed the fragmentation EMA with one tick's routed frames
        (every path: device, bypass, warming, guard)."""
        self._ema_frames = (float(n) if self._ema_frames is None
                            else 0.2 * n + 0.8 * self._ema_frames)

    def _frag_guarded(self) -> bool:
        """The upper dispatch guard: True routes this tick to the
        scalar drain because the fleet is large but its ticks are
        fragmented (frames/tick ≪ fleet size — the measured losing
        regime, CROSSOVER.md).  Hysteresis keeps the router from
        flapping on tick noise."""
        if not self.frag_guard:
            return False
        n = len(self._slots)
        if n < self.FRAG_MIN_FLEET or self._ema_frames is None:
            self._frag_scalar = False
            return False
        if self._frag_scalar:
            if self._ema_frames >= self.FRAG_EXIT * n:
                self._frag_scalar = False
        elif self._ema_frames < self.FRAG_ENTER * n:
            self._frag_scalar = True
        return self._frag_scalar

    def _want_direct(self) -> bool:
        """The dispatch policy: should the ingest run as a
        pass-through drain?  True when the byte volume per tick sits
        under ``bypass_bytes`` (the measured low-end crossover) or the
        fragmentation guard says a mega-fleet's ticks stopped being
        batches (the measured high-end losing regime)."""
        frag = self._frag_guarded()
        if frag:
            return True
        if not self.bypass_bytes or self._ema_bytes is None:
            return False
        if self._direct:
            # hysteresis: leave the pass-through only once the volume
            # clearly justifies batching
            return self._ema_bytes < 1.25 * self.bypass_bytes
        return self._ema_bytes < self.bypass_bytes

    def _flip_direct(self, active) -> None:
        """Batch -> pass-through: drain what the slots hold, hand each
        codec its partial-frame residue, switch.  Fault-withheld
        suffixes rejoin their slots FIRST — the direct regime never
        drains slot buffers, so a tail left in ``_held`` across the
        flip would strand, then reorder behind fresh rx bytes."""
        self._release_held()
        for conn, buf in active:
            if id(conn) not in self._slots:
                continue
            self._deliver_scalar(conn, buf)
        for _cid, (conn, buf) in list(self._slots.items()):
            if buf and conn.codec is not None:
                conn.codec.restore_pending(bytes(buf))
                buf.clear()
        self._direct = True

    def _flip_batch(self) -> None:
        """Pass-through -> batch: reclaim each codec's partial-frame
        residue into its slot so the next tick's scan continues it."""
        self._direct = False
        for _cid, (conn, buf) in list(self._slots.items()):
            if conn.codec is not None:
                resid = conn.codec.take_pending()
                if resid:
                    buf[:0] = resid

    def _tick(self) -> None:
        t0 = time.perf_counter()
        if self._tick_impl():
            self.tick_hist.observe((time.perf_counter() - t0) * 1000.0)

    def _tick_impl(self) -> bool:
        """One drain tick; returns True when it routed work (those
        ticks feed the duration histogram — empty bookkeeping wakeups
        would only blur the distribution's low end)."""
        self._scheduled = False
        win = self._window_bytes
        self._window_bytes = 0
        if win:
            self._ema_bytes = (float(win) if self._ema_bytes is None
                               else 0.2 * win + 0.8 * self._ema_bytes)
        if self._direct:
            if not win:
                return False
            # deliveries already happened inline (connection-side
            # drain or feed()); this tick is bookkeeping + the regime
            # decision.  Policy FIRST, then count: ticks_frag must
            # reflect the updated guard state, not last tick's.
            self._note_frames(self.frames_routed - self._frames_mark)
            self._frames_mark = self.frames_routed
            self.ticks_scalar += 1
            still_direct = self._want_direct()
            if self._frag_scalar:
                self.ticks_frag += 1
            if not still_direct:
                self._flip_batch()
            return True
        if self.faults is not None:
            self._inject_tick_faults()
        active = [(conn, buf) for conn, buf in self._slots.values()
                  if buf and conn.is_in_state('connected')]
        if not active:
            if self._release_held():
                self._schedule()     # finish the withheld suffixes
            return False
        before = self.frames_routed
        try:
            self._tick_inner(active)
        finally:
            self._note_frames(self.frames_routed - before)
            self._frames_mark = self.frames_routed
            if self._release_held():
                self._schedule()
        return True

    def _inject_tick_faults(self) -> None:
        """Apply the injector's tick-time decisions to the batch-regime
        slots: a connection reset at the tick boundary, or a suffix of
        a slot's buffered bytes withheld from this tick (a partial
        frame at an arbitrary cut for the device scan to finish on the
        follow-up tick)."""
        fi = self.faults
        for cid, (conn, buf) in list(self._slots.items()):
            if not buf or not conn.is_in_state('connected'):
                continue
            if fi.ingest_reset(conn):
                conn.emit('sockError', ConnectionResetError(
                    'injected ingest tick reset'))
                continue
            if cid in self._no_hold:
                self._no_hold.discard(cid)
                continue
            cut = fi.ingest_cut(conn, len(buf))
            if cut:
                self._held[cid] = \
                    self._held.get(cid, b'') + bytes(buf[-cut:])
                del buf[-cut:]

    def _release_held(self) -> bool:
        """Re-append every withheld suffix to its slot (in order);
        True when any slot got bytes back (a follow-up tick is due)."""
        if not self._held:
            return False
        released = False
        held, self._held = self._held, {}
        for cid, tail in held.items():
            slot = self._slots.get(cid)
            if slot is None:
                continue             # conn died; its bytes die with it
            slot[1].extend(tail)
            self._no_hold.add(cid)
            released = True
        return released

    def _tick_inner(self, active) -> None:
        if self._want_direct():
            self.ticks_scalar += 1
            if self._frag_scalar:
                self.ticks_frag += 1
            self._flip_direct(active)
            return

        B = len(active)
        maxlen = max(len(buf) for _c, buf in active)
        key = self._bucket(B, maxlen)
        ex = self._exec.get(key, _MISSING)
        if ex is _MISSING:
            if self.warm == 'block':
                ex = self._compile_or_latch(key)
            else:
                # never block the loop on a compile: drain this tick
                # through the scalar codec while the bucket warms
                self._start_warm(key)
                self.ticks_warming += 1
                for conn, buf in active:
                    if id(conn) not in self._slots:
                        continue
                    self._deliver_scalar(conn, buf)
                return
        if ex is None:  # compile failed: this bucket stays scalar
            self.ticks_scalar += 1
            for conn, buf in active:
                if id(conn) not in self._slots:
                    continue
                self._deliver_scalar(conn, buf)
            return
        self.ticks += 1

        device, Bp, L = key
        batch = np.zeros((Bp, L), np.uint8)
        lens = np.zeros((Bp,), np.int32)
        for i, (_conn, buf) in enumerate(active):
            # frombuffer views the bytearray; the assignment copies it
            # into the batch row before anything can mutate it
            batch[i, :len(buf)] = np.frombuffer(buf, np.uint8)
            lens[i] = len(buf)

        if device:
            ints, byts = ex(batch, lens)
            ints = np.asarray(ints)  # the only 2 readbacks per tick
            byts = np.asarray(byts)
        else:
            ints = np.asarray(ex(batch, lens))
            byts = None
        st, bd = self._unpack(ints, byts)

        retick = False
        for i, (conn, buf) in enumerate(active):
            if self._route_stream(conn, buf, st, bd, i):
                retick = True
        if retick:
            self._schedule()

    def _route_stream(self, conn, buf, st, bd, i: int) -> bool:
        """Deliver stream ``i``'s decoded tick results to its
        connection (shared by the event-driven tick and the multihost
        cadence tick).  Returns True when more complete frames may
        still be buffered (the per-stream frame bound was hit)."""
        # A user callback from an earlier stream's delivery may have
        # torn this connection down mid-tick (unregister already
        # restored its bytes to the codec): skip it.
        if id(conn) not in self._slots:
            return False
        n = int(st.n_frames[i])
        if bool(st.bad[i]):
            # Exact scalar-error parity: re-run this stream through
            # the connection's own codec, which raises BAD_LENGTH/
            # BAD_DECODE with the pre-error packets attached.
            self._deliver_fallback(conn, buf)
            return False
        pkts, err = self._assemble_stream(conn, buf, st, bd, i, n)
        resid = int(st.resid[i])
        if resid:
            del buf[:resid]
        self.frames_routed += n
        if pkts or err is not None:
            conn.emit('ingestDeliver', pkts, err)
        return (err is None and n == self.max_frames
                and len(buf) >= 4)

    def _deliver_scalar(self, conn: 'ZKConnection', buf: bytearray,
                        keep_stream: bool = True) -> None:
        """Drain one stream through the connection's own codec and emit
        the result — the scalar-parity delivery shared by the small-tick
        bypass (``keep_stream=True``: partial-frame residue returns to
        this slot's accumulator, traffic is counted) and the bad-frame
        fallback (``keep_stream=False``: the error the codec raises is
        the point; the stream is about to die)."""
        data, err, pkts = bytes(buf), None, []
        buf.clear()
        try:
            pkts = conn.codec.decode(data)
        except ZKProtocolError as e:
            pkts = getattr(e, 'packets', [])
            err = e
        else:
            if keep_stream:
                resid = conn.codec.take_pending()
                if resid:
                    buf.extend(resid)
        if keep_stream:
            self.frames_routed += len(pkts)
            if not pkts and err is None:
                return
        conn.emit('ingestDeliver', pkts, err)

    def _deliver_fallback(self, conn: 'ZKConnection',
                          buf: bytearray) -> None:
        self._deliver_scalar(conn, buf, keep_stream=False)

    # -- host packet assembly --

    def _assemble_stream(self, conn, buf, st, bd, i: int, n: int):
        """Build the packet dicts for stream ``i``'s ``n`` frames.
        Returns (packets, err); a decode failure mid-stream keeps the
        packets decoded before it, like PacketCodec.decode."""
        if not n:
            return [], None
        if bd is None:
            ext = conn.codec.ext
            if ext is not None:
                return self._assemble_ext(conn, buf, st, ext, i)
        pkts: list[dict] = []
        xid_map = conn.codec.xid_map
        # bulk-convert the header planes for this stream to Python ints
        # once: per-element numpy scalar indexing and (hi, lo) numpy
        # arithmetic cost ~10x the whole packet-dict build
        xids = st.xids[i, :n].tolist()
        zhis = st.zxid_hi[i, :n].tolist()
        zlos = st.zxid_lo[i, :n].tolist()
        errs = st.errs[i, :n].tolist()
        for f in range(n):
            xid = xids[f]
            opcode = SPECIAL_XIDS.get(xid)
            if opcode is None:
                opcode = xid_map.pop(xid, None)
            if opcode is None:
                return pkts, ZKProtocolError('BAD_DECODE',
                    'Failed to decode Response: ValueError: reply xid '
                    '%d matches no request' % (xid,))
            zxid = ((zhis[f] & 0xFFFFFFFF) << 32) | (zlos[f] & 0xFFFFFFFF)
            if zxid >= 1 << 63:
                zxid -= 1 << 64
            pkt = {
                'xid': xid,
                'zxid': zxid,
                'err': err_name(errs[f]),
                'opcode': opcode,
            }
            if pkt['err'] == 'OK' and opcode not in _EMPTY_RESPONSES:
                try:
                    self._read_body(pkt, buf, st, bd, i, f)
                except ZKProtocolError as e:
                    return pkts, e
                except Exception as e:
                    err = ZKProtocolError('BAD_DECODE',
                        'Failed to decode Response: %s: %s'
                        % (type(e).__name__, e))
                    err.__cause__ = e
                    return pkts, err
            pkts.append(pkt)
        return pkts, None

    def _assemble_ext(self, conn, buf, st, ext, i: int):
        """C fast path for ``body_mode='host'``: decode stream ``i``'s
        device-delimited complete-frame slice in one zero-copy pass of
        the C-extension decoder — the same code the scalar drain runs,
        so parity is by construction, at C speed.  The device scan
        already proved the slice frame-complete and length-valid
        (``bad`` streams took :meth:`_deliver_fallback`)."""
        resid = int(st.resid[i])
        if not resid:
            return [], None
        mv = memoryview(buf)
        sl = mv[:resid]
        try:
            pkts, _consumed, kind, msg = ext.decode_responses(
                sl, conn.codec.xid_map, MAX_PACKET)
        except Exception as e:
            err = ZKProtocolError('BAD_DECODE',
                'Failed to decode Response: %s: %s'
                % (type(e).__name__, e))
            err.__cause__ = e
            return [], err
        finally:
            # Release the views NOW: an exception's traceback (kept
            # alive via err.__cause__) can pin the call frame and with
            # it the buffer export, and an exported bytearray cannot
            # be resized — the caller's `del buf[:resid]` would raise
            # BufferError and kill the whole tick.
            sl.release()
            mv.release()
        if kind is not None:
            return pkts, ZKProtocolError(kind, msg)
        return pkts, None

    def _read_body(self, pkt, buf, st, bd, i: int, f: int) -> None:
        """Fill ``pkt`` with its opcode-specific body."""
        opcode = pkt['opcode']
        if bd is not None:
            if self._read_body_device(pkt, bd, i, f):
                return
            self.body_fallbacks += 1
        # Scalar reader positioned at the device-located body offset.
        start = int(st.starts[i, f])
        size = int(st.sizes[i, f])
        r = JuteReader(bytes(buf[start + REPLY_HDR:start + size]))
        reader = _RESP_READERS.get(opcode)
        if reader is None:
            raise ValueError('unsupported reply opcode %r' % (opcode,))
        reader(r, pkt)

    def _read_body_device(self, pkt, bd, i: int, f: int) -> bool:
        """Assemble the body from the tensor planes; False = this frame
        needs the scalar fallback (list-shaped, oversized, malformed)."""
        from ..ops.replies import stat_from_planes
        from ..protocol.consts import KeeperState, NotificationType

        opcode = pkt['opcode']
        if opcode in ('EXISTS', 'SET_DATA'):
            if not bool(bd.stat0.valid[i, f]):
                return False  # truncated: scalar reader raises exactly
            pkt['stat'] = stat_from_planes(bd.stat0, i, f)
            return True
        if opcode == 'GET_DATA':
            dlen = int(bd.data_len[i, f])
            if dlen > self.max_data or not bool(bd.data_ok[i, f]) or \
                    not bool(bd.stat_after_data.valid[i, f]):
                return False
            pkt['data'] = bytes(bd.data[i, f, :max(dlen, 0)])
            pkt['stat'] = stat_from_planes(bd.stat_after_data, i, f)
            return True
        if opcode == 'CREATE':
            slen = int(bd.str0_len[i, f])
            # not-ok = the length field points past the frame: fall
            # back so the scalar reader raises BAD_DECODE, exactly as
            # the scalar drain would
            if slen > self.max_path or not bool(bd.str0_ok[i, f]):
                return False
            pkt['path'] = bytes(bd.str0[i, f, :max(slen, 0)]).decode()
            return True
        if opcode == 'NOTIFICATION':
            plen = int(bd.npath_len[i, f])
            if plen > self.max_path or not bool(bd.npath_ok[i, f]):
                return False
            pkt['type'] = NotificationType(int(bd.ntype[i, f])).name
            pkt['state'] = KeeperState(int(bd.nstate[i, f])).name
            pkt['path'] = bytes(bd.npath[i, f, :max(plen, 0)]).decode()
            return True
        if opcode in ('GET_CHILDREN', 'GET_CHILDREN2'):
            if not bool(bd.ch_ok[i, f]):
                return False  # oversized/malformed list: scalar reader
            if opcode == 'GET_CHILDREN2':
                if not bool(bd.stat_after_children.valid[i, f]):
                    return False  # truncated Stat: scalar raises
                pkt['stat'] = stat_from_planes(
                    bd.stat_after_children, i, f)
            cnt = int(bd.ch_count[i, f])
            # plane contract: ch_ok => lens already clamped to [0, S]
            lens = bd.ch_len[i, f, :cnt].tolist()
            row, S = bd.ch_bytes[i, f], self.max_name
            pkt['children'] = [
                bytes(row[k * S:k * S + lens[k]]).decode()
                for k in range(cnt)]
            return True
        if opcode == 'GET_ACL':
            if not bool(bd.acl_ok[i, f]) or \
                    not bool(bd.stat_after_acl.valid[i, f]):
                return False
            from ..protocol.consts import Perm
            from ..protocol.records import ACL, Id

            cnt = int(bd.acl_count[i, f])
            perms = bd.acl_perms[i, f, :cnt].tolist()
            slens = bd.acl_scheme_len[i, f, :cnt].tolist()
            ilens = bd.acl_id_len[i, f, :cnt].tolist()
            srow, SS = bd.acl_scheme[i, f], self.max_scheme
            irow, SI = bd.acl_id[i, f], self.max_id
            pkt['acl'] = [
                ACL(Perm(perms[k]), Id(
                    bytes(srow[k * SS:k * SS + slens[k]]).decode(),
                    bytes(irow[k * SI:k * SI + ilens[k]]).decode()))
                for k in range(cnt)]
            pkt['stat'] = stat_from_planes(bd.stat_after_acl, i, f)
            return True
        return False
