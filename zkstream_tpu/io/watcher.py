"""The watcher engine: per-path user-facing emitters backed by per-watch
re-arm state machines.

ZooKeeper watches are one-shot on the server: a notification consumes
the watch, so the client must re-issue the read (with ``watch=True``) to
re-arm it, de-duplicating the re-read against the last seen zxid.  This
module ports that loop faithfully (reference: lib/zk-session.js:482-1005,
including the state diagram at :616-674).

Watch-kind compatibility matrix (reference: lib/zk-session.js:496-526):
the protocol pretends existence and data watches are distinct, but older
ZK servers keep them in one list, so which user events fire for which
server notification varies by server version.  ``ZKWatcher.notify`` maps
conservatively — every event FSM that *might* have had its server-side
watch consumed gets notified so it re-arms, and the zxid dedup suppresses
the duplicate user-facing emits this can cause.

  Older ZK versions:           created  deleted  dataCh  childrenCh
    GET_DATA                      X        X       X
    EXISTS                        X        X       X
    GET_CHILDREN2                          X               X
  Newer ZK versions (>=3.5?):
    GET_DATA                               X       X
    EXISTS                        X        X
    GET_CHILDREN2                          X               X
"""

from __future__ import annotations

import random
import time

from ..utils.events import EventEmitter
from ..utils.fsm import FSM
from ..utils.logging import Logger
from .backoff import BackoffPolicy

METRIC_ZK_WATCH_REARM_LATENCY = 'zookeeper_watch_rearm_latency_ms'

#: Re-arm pacing after consecutive arm failures: base 5 ms doubling to
#: a 500 ms cap — well below any session timeout, so a watch is never
#: dark long, but enough to keep churn from spinning the FSM hot.
ARM_RETRY_POLICY = BackoffPolicy(delay=5, cap=500, factor=2.0)

#: Idle window after which an armed watch probes the server to check it
#: has not missed a wakeup (reference: lib/zk-session.js:27-36).
DOUBLECHECK_TIMEOUT = 4 * 3600 * 1000
DOUBLECHECK_RAND = 8 * 3600 * 1000


class LostWakeupError(RuntimeError):
    """The doublecheck probe found the zxid moved without a notification:
    the watch machinery missed an event.  Deliberately fatal — this is a
    crash-on-bug self-check (reference: lib/zk-session.js:916-919)."""


class ZKWatcher(EventEmitter):
    """The per-path emitter returned by ``session.watcher(path)``.  User
    events: 'created', 'deleted', 'dataChanged', 'childrenChanged'.
    Spins up at most three ZKWatchEvent FSMs (created+deleted collapse
    into one existence watch) (reference: lib/zk-session.js:527-614)."""

    def __init__(self, session, path: str):
        super().__init__()
        self.path = path
        self.session = session
        self.watch_events: dict[str, 'ZKWatchEvent'] = {}

    def events(self) -> list['ZKWatchEvent']:
        out = []
        for evt in ('createdOrDeleted', 'dataChanged', 'childrenChanged'):
            if evt in self.watch_events:
                out.append(self.watch_events[evt])
        return out

    def once(self, event, cb):
        raise NotImplementedError(
            'ZKWatcher does not support once() (use on)')

    def notify(self, evt: str) -> None:
        """Fan a server notification out to the event FSMs per the
        compatibility matrix; crash if nothing matched, because that
        means our model of ZK watch semantics is wrong and we cannot
        guarantee a working watcher (reference: lib/zk-session.js:556-593).
        """
        if evt == 'created':
            to_notify = ['createdOrDeleted', 'dataChanged']
        elif evt == 'deleted':
            to_notify = ['createdOrDeleted', 'dataChanged',
                         'childrenChanged']
        elif evt == 'dataChanged':
            to_notify = ['dataChanged', 'createdOrDeleted']
        elif evt == 'childrenChanged':
            to_notify = ['childrenChanged']
        else:
            raise ValueError('Unknown notification type: %s' % (evt,))
        notified = False
        for kind in to_notify:
            event = self.watch_events.get(kind)
            if event is not None and not event.is_in_state('disarmed'):
                event.notify()
                notified = True
        if not notified:
            # Crash-on-bug: escalate through the session's fatal path
            # (teardown + 'failed'/'expire' + loop exception handler by
            # default) so the failure is loud even with no handler
            # installed (reference throws: lib/zk-session.js:584-592).
            self.session.fatal_error(LostWakeupError(
                'Got notification for %s but have no matching events '
                'on %s' % (evt, self.path)))

    def on(self, evt: str, cb) -> 'ZKWatcher':
        first = self.listener_count(evt) < 1
        super().on(evt, cb)
        if evt != 'error' and first:
            self._arm_event(evt)
        return self

    def _arm_event(self, evt: str) -> None:
        if evt in ('deleted', 'created'):
            evt = 'createdOrDeleted'
        if evt not in self.watch_events:
            self.watch_events[evt] = ZKWatchEvent(
                self.session, self.path, self, evt)
        if self.watch_events[evt].is_in_state('disarmed'):
            self.watch_events[evt].arm()


class ZKPersistentWatcher(EventEmitter):
    """One persistent (ADD_WATCH, opcode 106) registration: the
    client-side half of the watch family the one-shot engine above
    never had.  No re-arm FSM — the server-side subscription survives
    fires, so this object is just the session-lifetime emitter plus
    the replay bookkeeping.

    User events, each emitted with ``(path, zxid)``:

    - ``'created'`` / ``'deleted'`` / ``'dataChanged'`` — for the
      registered node and, in recursive mode, every descendant;
    - ``'childrenChanged'`` — exact (non-recursive) mode only: a
      recursive subscriber sees the child's own CREATED/DELETED
      instead (upstream PERSISTENT_RECURSIVE semantics);
    - ``'resumed'`` — the session re-established and the server-side
      subscription was re-armed via SET_WATCHES2 replay.  Anything
      may have changed in the gap: a subscriber maintaining derived
      state (io/cache.py) must resync, not trust it;
    - ``'lost'`` — the owning session died for good (expired/closed);
      the registration is gone and must be re-created on a new
      session.

    Exact-mode registrations dedup on a monotone zxid: the replay
    catch-up nudge can restate an event the old connection already
    delivered.  Recursive mode interleaves many paths and stays
    dedup-free — duplicate delivery after a reconnect is part of its
    contract (subscribers resync on 'resumed' anyway)."""

    def __init__(self, session, path: str, recursive: bool):
        super().__init__()
        self.session = session
        self.path = path
        self.recursive = recursive
        self.last_zxid = 0

    def _notify(self, evt: str, path: str, zxid: int) -> None:
        if not self.recursive:
            if zxid <= self.last_zxid:
                return
            self.last_zxid = zxid
        self.emit(evt, path, zxid)

    def _resumed(self) -> None:
        self.emit('resumed')

    def _lost(self) -> None:
        self.emit('lost')


class ZKWatchEvent(FSM):
    """One watch's arm / re-arm loop (state diagram: reference
    lib/zk-session.js:616-674).  Lives as long as the session."""

    def __init__(self, session, path: str, emitter: ZKWatcher, evt: str):
        self.path = path
        self.session = session
        self.emitter = emitter
        self.evt = evt
        self.log = getattr(session, 'log', Logger()).child(
            component='ZKWatchEvent', path=path, event=evt)
        self.prev_zxid: int | None = None
        #: Paces re-arm retries: under injected churn the arming read
        #: can fail over and over while the session flaps between
        #: attached and detached; without a growing delay the
        #: wait_session -> wait_connected -> arming cycle becomes a
        #: hot loop that floods the dying connection with re-arm
        #: reads.  Shared jittered-backoff machinery (io/backoff.py);
        #: ``_arm_retry`` is the "last attempt failed" latch.
        self._arm_backoff = ARM_RETRY_POLICY.backoff()
        self._arm_retry = False
        #: (Re-)arm latency instrumentation: the arming read's
        #: round-trip, labelled by watch kind — the window a watch is
        #: dark after a notification consumed it server-side.
        collector = getattr(session, 'collector', None)
        self._rearm_latency = None
        if collector is not None:
            self._rearm_latency = collector.histogram(
                METRIC_ZK_WATCH_REARM_LATENCY,
                'Watch (re-)arm read round-trip latency, '
                'milliseconds, by watch event kind')
            self.bind_fsm_metrics(collector, 'ZKWatchEvent')
        #: True after 'deleted' was emitted for the node's current
        #: absence: re-arming an existence watch on a still-missing
        #: node (connection churn forces re-arms) must not re-emit
        #: 'deleted' for the same deletion.
        self._deleted_seen = False
        super().__init__('disarmed')

    def _arm_ok(self) -> None:
        self._arm_retry = False
        self._arm_backoff.reset()

    def _observe_rearm(self, t0: float) -> None:
        if self._rearm_latency is not None:
            self._rearm_latency.observe(
                (time.monotonic() - t0) * 1000.0, {'event': self.evt})

    def get_event(self) -> str:
        return self.evt

    def arm(self) -> None:
        self.emit('armAsserted')

    def notify(self) -> None:
        """A matching notification arrived.  Only meaningful when armed
        or resuming; in other states we are already mid-(re)arm
        (reference: lib/zk-session.js:703-711)."""
        # A server notification means the node genuinely changed, so
        # the deleted-emit latch no longer describes the current
        # absence: a create-then-delete pulse must re-report 'deleted'
        # from the re-arm read (only *churn-forced* re-arms — which
        # never come through here — stay suppressed).
        self._deleted_seen = False
        if self.is_in_state('armed') or self.is_in_state('resuming'):
            self.emit('notifyAsserted')

    def disconnected(self) -> None:
        """The session detached; if armed, we are on its auto-resume
        list (reference: lib/zk-session.js:722-730)."""
        if self.is_in_state('armed'):
            self.emit('disconnectAsserted')

    def resume(self) -> None:
        """Auto-resume (server-side SET_WATCHES re-arm) completed.  If a
        catch-up notification already moved us along, ignore it
        (reference: lib/zk-session.js:732-740)."""
        if self.is_in_state('resuming'):
            self.emit('resumeAsserted')

    # -- states --

    def state_disarmed(self, S) -> None:
        S.on(self, 'armAsserted', lambda: S.goto_state('wait_session'))

    def state_wait_session(self, S) -> None:
        if self.session.is_in_state('attached'):
            S.goto_state('wait_connected')
            return

        def on_state(state):
            if state == 'attached':
                S.goto_state('wait_connected')
        S.on(self.session, 'stateChanged', on_state)
        self.log.debug('deferring watcher arm until after reconnect')

    def state_wait_connected(self, S) -> None:
        conn = self.session.get_connection()
        if conn is None or not conn.is_in_state('connected'):
            # Do not bounce back synchronously: give the connection a
            # chance to finish its own transition this turn
            # (reference: lib/zk-session.js:781-790).
            S.immediate(lambda: S.goto_state('wait_session'))
            return
        if self._arm_retry:
            # Previous arming attempt(s) failed: pace the retry so
            # connection churn cannot spin this FSM hot.  The timer is
            # scope-bound — a disconnect mid-wait disposes it and the
            # normal wait_session path takes over.
            S.timeout(self._arm_backoff.next_delay(),
                      lambda: S.goto_state('arming'))
            return
        S.goto_state('arming')

    def state_arming(self, S) -> None:
        """Issue the read-with-watch; a valid reply (or certain errors)
        means the watch is armed (reference: lib/zk-session.js:803-888)."""
        conn = self.session.get_connection()
        if conn is None or not conn.is_in_state('connected'):
            # The connection died while a paced retry timer was
            # pending (state_wait_connected's check is stale by the
            # time the timer fires): back to waiting, don't throw.
            self._arm_retry = True
            S.immediate(lambda: S.goto_state('wait_session'))
            return
        arm_t0 = time.monotonic()
        req = conn.request(self.to_packet())

        def on_reply(pkt):
            if self.evt == 'createdOrDeleted':
                # EXISTS returned OK: the node exists.
                args = ('created', pkt['stat'])
                zxid = pkt['stat'].czxid
            elif self.evt == 'dataChanged':
                args = ('dataChanged', pkt['data'], pkt['stat'])
                zxid = pkt['stat'].mzxid
            elif self.evt == 'childrenChanged':
                args = ('childrenChanged', pkt['children'], pkt['stat'])
                zxid = pkt['stat'].pzxid
            else:
                raise ValueError('Unknown watcher event %s' % (self.evt,))
            # Emit only if the relevant zxid moved FORWARD since the
            # last emit: equality suppresses duplicate notifications
            # from the server watch-kind overlap (reference:
            # lib/zk-session.js:849-856), and an OLDER zxid is a
            # stale read — a churn-forced re-arm can land on a
            # lagging follower that has not applied a change this
            # watcher already delivered, and re-emitting the old
            # state would be a duplicate fire for a change the
            # watcher saw (the at-most-once invariant,
            # io/invariants.py check_watch_once).
            self._arm_ok()
            self._observe_rearm(arm_t0)
            self._deleted_seen = False
            if self.prev_zxid is not None and zxid <= self.prev_zxid:
                S.goto_state('armed')
                return
            EventEmitter.emit(self.emitter, *args)
            self.prev_zxid = zxid
            S.goto_state('armed')
        S.on(req, 'reply', on_reply)

        def on_error(err, *a):
            code = getattr(err, 'code', None)
            if code == 'PING_TIMEOUT':
                self._arm_retry = True
                S.goto_state('wait_session')
                return
            if self.evt == 'createdOrDeleted' and code == 'NO_NODE':
                # Existence watches arm fine on a missing node
                # (reference: lib/zk-session.js:865-874).  Emit
                # 'deleted' once per disappearance: churn-forced
                # re-arms over the same absence stay silent.
                self._arm_ok()
                self._observe_rearm(arm_t0)
                if not self._deleted_seen:
                    self._deleted_seen = True
                    EventEmitter.emit(self.emitter, 'deleted')
                S.goto_state('armed')
                return
            if code == 'NO_NODE':
                # Other watch kinds cannot attach to a missing node;
                # park until it is created.
                self._arm_ok()
                self._observe_rearm(arm_t0)
                S.goto_state('wait_node')
                return
            self._arm_retry = True
            self.log.debug('watcher attach failure (%s); will retry',
                           err)
            S.goto_state('wait_session')
        S.on(req, 'error', on_error)

    def state_wait_node(self, S) -> None:
        S.on(self.emitter, 'created',
             lambda *a: S.goto_state('wait_session'))

    def state_armed(self, S) -> None:
        S.on(self, 'notifyAsserted', lambda: S.goto_state('wait_session'))
        S.on(self, 'disconnectAsserted', lambda: S.goto_state('resuming'))
        dbl = round(DOUBLECHECK_TIMEOUT + random.random() * DOUBLECHECK_RAND)
        S.timeout(dbl, lambda: S.goto_state('armed.doublecheck'))

    def state_armed_doublecheck(self, S) -> None:
        """Probe EXISTS (no watch) and compare zxids; a moved zxid with
        no notification means we missed a wakeup — crash on the bug
        (reference: lib/zk-session.js:923-970).  Inherits armed's
        notify/disconnect transitions via the substate scope stack."""
        if not self.session.is_in_state('attached'):
            S.goto_state('armed')
            return
        conn = self.session.get_connection()
        if conn is None or not conn.is_in_state('connected'):
            S.goto_state('armed')
            return
        req = conn.request({'path': self.path, 'opcode': 'EXISTS',
                            'watch': False})

        def on_reply(pkt):
            if self.evt == 'createdOrDeleted':
                zxid = pkt['stat'].czxid
            elif self.evt == 'dataChanged':
                zxid = pkt['stat'].mzxid
            elif self.evt == 'childrenChanged':
                zxid = pkt['stat'].pzxid
            else:
                raise ValueError('Unknown watcher event %s' % (self.evt,))
            if self.prev_zxid is None or zxid > self.prev_zxid:
                # Crash-on-bug (see ZKWatcher.notify): fatal by
                # default, never a swallowed callback exception
                # (reference throws: lib/zk-session.js:916-919).
                # Only a zxid AHEAD of the last emit is a missed
                # wakeup; an older one is a stale read from a
                # lagging member (the next probe re-checks).
                self.session.fatal_error(LostWakeupError(
                    'ZKWatchEvent double-check failed: a ZK event '
                    'wakeup was missed, this is a bug'))
                return
            S.goto_state('armed')
        S.on(req, 'reply', on_reply)
        S.on(req, 'error', lambda err, *a: S.goto_state('armed'))

    def state_resuming(self, S) -> None:
        S.on(self, 'resumeAsserted', lambda: S.goto_state('armed'))
        S.on(self, 'notifyAsserted', lambda: S.goto_state('wait_session'))

    def to_packet(self) -> dict:
        opcode = {'createdOrDeleted': 'EXISTS',
                  'dataChanged': 'GET_DATA',
                  'childrenChanged': 'GET_CHILDREN2'}.get(self.evt)
        if opcode is None:
            raise ValueError('Unknown watcher event %s' % (self.evt,))
        return {'path': self.path, 'opcode': opcode, 'watch': True}
