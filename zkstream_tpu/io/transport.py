"""Batched-syscall transport backends beneath the send plane.

The tick cork (io/sendplane.py) already joins every frame a connection
sends within one event-loop iteration into one ``transport.write`` —
but a WIDE tick still costs one write(2) per dirty connection, so a
busy server at 1k–10k connections spends its ``cork_flush`` /
``fanout_flush`` tick phases (the PR 7 ledger's numbers) on pure
syscall dispatch.  This module swaps the syscall layer underneath the
unchanged SendPlane API — the PAPERS.md thread (RPCAcc, ACCL+,
transparent InfiniBand under netty) applied here: the RPC surface
stays put, the batching decision lives in exactly one place.

Three tiers, capability-probed and env-forced exactly like the codec
tiers (``ZKSTREAM_TRANSPORT=uring|mmsg|asyncio``):

- ``uring``   — a shared io_uring submission queue: ONE
  ``io_uring_enter`` per corked tick covers every dirty connection
  (one ``IORING_OP_SENDMSG`` SQE per connection, iovec-joined, so no
  intermediate Python ``bytes`` is materialized per connection
  either).  Requires Linux >= 5.1 and the native extension
  (native/zkwire_ext.c ``uring_*``).
- ``mmsg``    — per-connection vectored writes: one ``writev(2)`` per
  dirty connection per tick, submitted for the whole batch in ONE C
  call (``zkwire_ext.submit_writev``) when the extension is built, an
  ``os.writev`` loop otherwise.  TCP has no cross-fd ``sendmmsg``;
  the vectored submit is its stream-socket equivalent — the syscall
  count stays O(dirty conns) but the join and the per-write asyncio
  transport walk disappear.
- ``asyncio`` — the existing per-plane ``transport.write`` path,
  untouched: the env-gated validator (and the only tier off Linux).

The default is the best available tier; forcing an unavailable tier
falls DOWN the order (never up), so an exported ``uring`` on an old
kernel degrades to ``mmsg`` instead of failing — ``probe()`` records
why, and the ``zk_transport_backend`` mntr row shows what a member
actually runs.

Correctness contract (the parity suite in tests/test_transport.py
holds all tiers to byte-identical per-connection streams):

- **Per-connection ordering is submission order.**  An entry's chunks
  append in plane-flush order; raw submission happens at the tick
  boundary; a partial or refused (``EAGAIN``) raw write routes the
  REMAINDER through the asyncio transport, and every subsequent tick
  defers to the transport until its buffer drains (`` raw writes only
  when get_write_buffer_size() == 0``) — so the kernel sees every
  byte exactly once, in order, whichever path carried it.
- **Hard flushes stay synchronous.**  ``SendPlane.flush_hard`` (fault
  injection delivering mid-tick, CLOSE_SESSION ahead of EOF,
  connection close) drains that entry's pending bytes with an
  immediate single-entry submission before returning — the fault
  injector's per-frame boundary rule (io/faults.py) is unchanged.
- **The durability barrier is upstream.**  The plane gates corked
  acks on the WAL's group fsync BEFORE handing bytes to the tier
  (SendPlane.flush_now), so no ack byte reaches a submission queue
  before its txn is on disk — backend-independent.

Observability: ``zookeeper_flush_syscalls_total{plane,backend}``
counts actual write submissions (the A/B number: O(dirty conns) per
tick on mmsg/asyncio, O(1) on uring) and ``zookeeper_submit_depth``
histograms connections covered per batched submission.  Scraped by
``bench.py --transport`` (`make bench-transport`).
"""

from __future__ import annotations

import dataclasses
import errno
import logging
import os
import sys

from ..utils.aio import ambient_loop

log = logging.getLogger('zkstream_tpu.transport')

TRANSPORT_ENV = 'ZKSTREAM_TRANSPORT'

#: Fallback order: forcing an unavailable tier falls DOWN this list.
BACKENDS = ('uring', 'mmsg', 'asyncio')

METRIC_FLUSH_SYSCALLS = 'zookeeper_flush_syscalls_total'
METRIC_SUBMIT_DEPTH = 'zookeeper_submit_depth'

#: Connections per batched submission (the depth distribution: 1 =
#: batching bought nothing that tick, the interesting mass is 2+).
DEPTH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096)

#: Per-entry chunk-count guard: above this the entry's chunks are
#: coalesced in place before submission so one connection's frame
#: count can never overflow an iovec array (IOV_MAX is 1024).
IOV_GUARD = 512

#: io_uring submission-queue depth (entries per ring; batches wider
#: than this submit in waves — still one enter syscall per wave).
URING_DEPTH = 1024

#: Raw-write errnos meaning the connection itself is gone (drop the
#: bytes, exactly as an aborted transport would) — anything else
#: (EAGAIN backpressure, ring-level transients like EBUSY/ENOMEM/
#: ENOBUFS) re-routes through the asyncio transport, which either
#: delivers or runs its own teardown.  EIO doubles as the native
#: uring layer's "submission state unknown" sentinel: a resend there
#: could duplicate bytes, so those drop.
_DEAD_ERRNOS = frozenset({errno.EPIPE, errno.ECONNRESET,
                          errno.EBADF, errno.ENOTCONN,
                          errno.ESHUTDOWN, errno.ECONNABORTED,
                          errno.EIO})


@dataclasses.dataclass(frozen=True)
class Probe:
    """What the capability probe found (``zk_transport_backend`` and
    the pytest skip markers read this)."""

    platform: str
    uring: bool
    uring_reason: str
    mmsg: bool
    mmsg_reason: str
    forced: str | None
    chosen: str

    def available(self, backend: str) -> bool:
        if backend == 'uring':
            return self.uring
        if backend == 'mmsg':
            return self.mmsg
        return True


#: Cached CAPABILITY results only — the env force is re-read on every
#: probe() call (like cork_default), so tests and the chaos CLI can
#: flip ZKSTREAM_TRANSPORT mid-process.
_caps_cache: tuple[tuple[bool, str], tuple[bool, str]] | None = None


def _probe_uring() -> tuple[bool, str]:
    """Can this process create an io_uring?  Needs Linux, the native
    extension (the ring lives in native/zkwire_ext.c), and a kernel
    that answers io_uring_setup (>= 5.1)."""
    if not sys.platform.startswith('linux'):
        return False, 'not linux'
    from ..utils.native import get_ext
    ext = get_ext()
    if ext is None:
        return False, 'native ext unavailable (build pending or off)'
    if not hasattr(ext, 'uring_create'):
        return False, 'native ext predates uring support'
    try:
        ring = ext.uring_create(8)
    except OSError as e:
        return False, 'io_uring_setup: %s' % (e.strerror or e,)
    ext.uring_close(ring)
    return True, 'ok'


def _probe_mmsg() -> tuple[bool, str]:
    if not hasattr(os, 'writev'):
        return False, 'os.writev unavailable'
    if sys.platform.startswith('win'):
        return False, 'not posix'
    return True, 'ok'


def probe(refresh: bool = False) -> Probe:
    """Resolve the process's transport tier: capability probe
    (cached; ``refresh=True`` re-probes — tests that build the native
    extension mid-process use it, and a tier created before the
    background ext build lands simply runs one tier lower) plus the
    env force, re-read every call."""
    global _caps_cache
    if _caps_cache is None or refresh:
        _caps_cache = (_probe_uring(), _probe_mmsg())
    (uring_ok, uring_why), (mmsg_ok, mmsg_why) = _caps_cache
    forced = os.environ.get(TRANSPORT_ENV) or None
    if forced is not None and forced not in BACKENDS:
        forced = None
    order = BACKENDS[BACKENDS.index(forced):] if forced else BACKENDS
    chosen = 'asyncio'
    for b in order:
        if (b == 'uring' and uring_ok) or (b == 'mmsg' and mmsg_ok) \
                or b == 'asyncio':
            chosen = b
            break
    return Probe(platform=sys.platform, uring=uring_ok,
                 uring_reason=uring_why, mmsg=mmsg_ok,
                 mmsg_reason=mmsg_why, forced=forced, chosen=chosen)


def backend_default() -> str:
    """The process-wide backend (env force resolved against the
    probe) — what a knobless ZKServer/Client runs."""
    return probe().chosen


def resolve_backend(arg: str | None) -> str:
    """Resolve an explicit constructor knob ('uring'|'mmsg'|'asyncio',
    None = process default) against availability, falling down the
    tier order like the env force does."""
    if arg is None:
        return backend_default()
    if arg not in BACKENDS:
        raise ValueError('unknown transport backend %r (choose from '
                         '%s)' % (arg, '|'.join(BACKENDS)))
    p = probe()
    for b in BACKENDS[BACKENDS.index(arg):]:
        if p.available(b):
            return b
    return 'asyncio'


class _Entry:
    """One connection's slot in the tier: the transport accessor (the
    live asyncio transport, or None once the socket is gone), the
    legacy sink for fallback writes, and the chunks deferred to the
    next tick submission.  The resolved fd is cached keyed on the
    transport's identity — safe against fd reuse because it is only
    consulted while ``transport_fn()`` returns that same, still-open
    transport object."""

    __slots__ = ('transport_fn', 'write', 'chunks', 'nbytes',
                 '_t', '_fd')

    def __init__(self, write, transport_fn):
        self.write = write              # the plane's asyncio sink
        self.transport_fn = transport_fn
        self.chunks: list[bytes] = []
        self.nbytes = 0
        self._t = None
        self._fd = -1

    def resolve_fd(self, t) -> int:
        if t is self._t:
            return self._fd
        fd = -1
        sock = t.get_extra_info('socket')
        if sock is not None:
            try:
                fd = sock.fileno()
            except (OSError, ValueError):
                fd = -1
        self._t = t
        self._fd = fd
        return fd

    def take(self) -> list[bytes]:
        chunks = self.chunks
        self.chunks = []
        self.nbytes = 0
        return chunks


class TransportTier:
    """One event loop's batched submission queue: SendPlanes enqueue
    their flushed chunk lists here instead of writing, and ONE
    deferred callback per busy tick submits every dirty connection's
    buffer in a single batched syscall chain."""

    def __init__(self, backend: str, collector=None,
                 plane: str = 'server', ledger=None):
        assert backend in ('uring', 'mmsg'), backend
        self.backend = backend
        self.plane = plane
        #: Optional utils/metrics.TickLedger: submission time is the
        #: tick's ``cork_flush`` phase (the same phase the per-plane
        #: asyncio writes account under, so ledger shares stay
        #: comparable across backends).
        self.ledger = ledger
        self._dirty: list[_Entry] = []
        #: Planes that corked frames this tick and delegated their
        #: tick flush here: ONE loop callback flushes them all and
        #: submits the resulting batch — the per-connection
        #: ``call_soon`` the legacy path pays per tick (PR 6 measured
        #: it at ~45% of a wide fan-out; the reply path paid it
        #: until now) collapses into this single callback.
        self._tick_work: list = []
        #: The loop holding the pending tick callback (None = none).
        #: Loop identity, not a bool: a callback stranded on a dead
        #: loop (a client reused across asyncio.run calls) must not
        #: block scheduling on the next loop forever.
        self._scheduled_on = None
        self._uring = None
        self._uring_dead = False
        self.syscalls = 0        # lifetime submissions (tests/mntr)
        self.submissions = 0     # batched submit rounds
        self._syscall_ctr = None
        self._depth_hist = None
        if collector is not None:
            self._syscall_ctr = collector.counter(
                METRIC_FLUSH_SYSCALLS,
                'Write submissions issued by the outbound plane, by '
                'plane and backend')
            self._depth_hist = collector.histogram(
                METRIC_SUBMIT_DEPTH,
                'Connections covered per batched transport '
                'submission, by plane and backend',
                buckets=DEPTH_BUCKETS)

    # -- SendPlane-facing API --

    def channel(self, write, transport_fn) -> _Entry:
        """One per SendPlane: created at plane construction, reused
        for the connection's lifetime."""
        return _Entry(write, transport_fn)

    def enqueue(self, entry: _Entry, chunks: list[bytes],
                nbytes: int) -> None:
        """Defer one plane flush to the tick submission.  The entry's
        transport is resolved at submit time — an entry whose
        transport is already gone falls back to its plane sink there
        (where the write is a no-op on a dead connection anyway)."""
        if not entry.chunks:
            self._dirty.append(entry)
            entry.chunks = chunks       # adopt: the plane released it
        else:
            entry.chunks.extend(chunks)
        entry.nbytes += nbytes
        if len(entry.chunks) > IOV_GUARD:
            # bound the iovec array a pathological tick could build
            entry.chunks = [b''.join(entry.chunks)]
        self._schedule()

    def _schedule(self) -> None:
        """Ensure the tick callback is pending on the CURRENT loop.
        ``is_closed`` on the stored loop (cheap, ~75 ns) — not a loop
        compare via ``get_running_loop`` (which pays a getpid syscall
        per call on this image) — detects a callback stranded on a
        dead loop, so a tier reused across asyncio.run calls can
        never wedge."""
        sched = self._scheduled_on
        if sched is not None and not sched.is_closed():
            return
        loop = ambient_loop()
        self._scheduled_on = loop
        loop.call_soon(self._tick)

    def schedule_flush(self, plane) -> None:
        """Register one plane for the tick's shared flush callback
        (SendPlane.send calls this instead of scheduling its own
        ``call_soon`` when a tier is attached).  The plane guards
        against double registration with its own ``_scheduled``
        flag."""
        self._tick_work.append(plane._tick_flush)
        self._schedule()

    def schedule_call(self, fn) -> None:
        """Run ``fn`` inside the tick callback, BEFORE the batched
        submission — for flush work that feeds the tier (the watch
        table's per-shard fan-out flushes): scheduling it as its own
        ``call_soon`` would land its bytes one loop hop after the
        submission that should have carried them."""
        self._tick_work.append(fn)
        self._schedule()

    def drain(self, entry: _Entry) -> None:
        """Hard flush: submit THIS entry's pending bytes now (the
        flush_hard contract — bytes on the wire before return).  The
        entry may stay in the dirty list; the tick submission skips
        entries whose chunks are already gone."""
        if entry.chunks:
            self._submit([entry])

    def discard(self, entry: _Entry) -> None:
        """Connection aborted: its pending bytes have nowhere to go
        (SendPlane.reset)."""
        entry.take()

    # -- the tick submission --

    def _tick(self) -> None:
        """The tick boundary: run every registered flush (plane tick
        flushes and shard fan-out flushes — their enqueues land while
        the schedule slot is still held, so they cannot re-schedule),
        then submit the whole dirty set as one batch — flush and
        submission share the one callback, so batched bytes reach the
        kernel in the same loop iteration the legacy per-plane
        flushes would have used.

        One raising flush must not take the rest of the tick with it:
        the legacy path isolated a callback failure to its one
        connection (each flush was its own ``call_soon``), and the
        shared callback must be no weaker — errors are logged per
        flush, and the submission + schedule-slot release always
        run."""
        work, self._tick_work = self._tick_work, []
        try:
            for fn in work:
                try:
                    fn()
                except Exception:
                    log.exception('transport tick flush failed')
        finally:
            self._scheduled_on = None
            dirty, self._dirty = self._dirty, []
            self._submit(dirty)

    def _count(self, n: int, backend: str) -> None:
        self.syscalls += n
        if self._syscall_ctr is not None and n:
            self._syscall_ctr.increment(
                {'plane': self.plane, 'backend': backend}, by=n)

    def _submit(self, entries: list[_Entry]) -> None:
        """Resolve each entry's fd and submit the whole batch through
        the backend; anything raw-ineligible (no socket, transport
        already buffering, closing) routes through its asyncio sink —
        the FIFO transport buffer keeps ordering either way."""
        batch_fds: list[int] = []
        batch_chunks: list[list[bytes]] = []
        raw_entries: list[tuple[_Entry, list[bytes], int]] = []
        for e in entries:
            chunks = e.chunks
            if not chunks:
                continue        # drained hard mid-tick, or reset
            # take the chunks NOW: a hard-drained entry re-dirtied in
            # the same tick appears in `entries` twice, and only an
            # emptied entry makes the second visit a no-op
            nbytes = e.nbytes
            e.chunks = []
            e.nbytes = 0
            fd = -1
            t = e.transport_fn()
            if t is not None:
                # fast paths over the selector transport's private
                # state: is_closing() is an attribute read behind a
                # method call, and get_write_buffer_size() allocates
                # (sum(map(len, deque))) — at 10k dirty connections
                # per tick both matter.  Transports without the
                # attributes (uvloop, proactor) take the public API.
                closing = getattr(t, '_closing', None)
                if closing is None:
                    closing = t.is_closing()
                if not closing:
                    wbuf = getattr(t, '_buffer', None)
                    if (not wbuf if wbuf is not None
                            else t.get_write_buffer_size() == 0):
                        fd = e.resolve_fd(t)
            if fd < 0:
                self._count(1, 'asyncio')
                e.write(chunks[0] if len(chunks) == 1
                        else b''.join(chunks))
                continue
            batch_fds.append(fd)
            batch_chunks.append(chunks)
            raw_entries.append((e, chunks, nbytes))
        if not batch_fds:
            return
        led = self.ledger
        if led is not None:
            led.enter('cork_flush')
        try:
            results, nsys = self._submit_raw(batch_fds, batch_chunks)
        finally:
            if led is not None:
                led.exit()
        self.submissions += 1
        self._count(nsys, self.backend)
        if self._depth_hist is not None:
            self._depth_hist.observe(
                len(batch_fds), {'plane': self.plane,
                                 'backend': self.backend})
        for (e, chunks, nbytes), res in zip(raw_entries, results):
            if res != nbytes:       # the hot path writes everything
                self._settle(e, chunks, nbytes, res)

    def _settle(self, entry: _Entry, chunks: list[bytes],
                nbytes: int, res: int) -> None:
        """Apply one incomplete raw-write result: a short or refused
        write hands the remainder to the asyncio transport (which
        queues FIFO and re-enables raw writes only once drained); a
        dead-socket errno drops the bytes exactly as a closed
        transport would.  Transient errnos (backpressure, a failed
        ring submission that provably sent nothing) resend through
        the transport — never a silent drop on a live connection."""
        if res < 0:
            if -res not in _DEAD_ERRNOS:
                self._count(1, 'asyncio')
                entry.write(b''.join(chunks))
            return
        if res >= nbytes:
            return
        # partial write: the kernel buffer filled mid-entry — the
        # remainder must queue in the transport so later ticks (which
        # see a nonzero write buffer) stay behind it
        rem = memoryview(b''.join(chunks))[res:]
        self._count(1, 'asyncio')
        entry.write(bytes(rem))

    # -- backends --

    def _submit_raw(self, fds, chunklists) -> tuple[list[int], int]:
        if self.backend == 'uring':
            out = self._submit_uring(fds, chunklists)
            if out is not None:
                return out
            # ring creation failed after probe said OK (fd limits,
            # seccomp): latch down to the mmsg path for this tier
        return self._submit_mmsg(fds, chunklists)

    def _submit_uring(self, fds, chunklists
                      ) -> tuple[list[int], int] | None:
        if self._uring_dead:
            return None
        from ..utils.native import get_ext
        ext = get_ext()
        if ext is None or not hasattr(ext, 'uring_submit'):
            return None
        if self._uring is None:
            try:
                self._uring = ext.uring_create(URING_DEPTH)
            except OSError:
                self._uring_dead = True
                return None
        try:
            results, enters = ext.uring_submit(self._uring, fds,
                                               chunklists)
        except OSError:
            self._uring_dead = True
            return None
        return results, enters

    def _submit_mmsg(self, fds, chunklists) -> tuple[list[int], int]:
        from ..utils.native import get_ext
        ext = get_ext()
        if ext is not None and hasattr(ext, 'submit_writev'):
            # ONE C call for the whole batch: per-entry writev loops
            # (the join-and-write boundary) without a Python-level
            # join or per-connection Python syscall dispatch
            return ext.submit_writev(fds, chunklists), len(fds)
        results = []
        for fd, chunks in zip(fds, chunklists):
            try:
                results.append(os.writev(fd, chunks))
            except BlockingIOError:
                results.append(-errno.EAGAIN)
            except OSError as e:
                results.append(-(e.errno or 1))
        return results, len(fds)

    def close(self) -> None:
        """Release the ring fd + mmaps now (ZKServer.stop /
        Client.close call this — the plane/entry closures hold the
        tier in reference cycles, so refcount-time release never
        happens; the capsule destructor remains the GC backstop).
        The next submission lazily re-creates the ring, so a
        restarted server/client keeps working."""
        if self._uring is not None:
            from ..utils.native import get_ext
            ext = get_ext()
            if ext is not None:
                try:
                    ext.uring_close(self._uring)
                except (OSError, ValueError):
                    pass
            self._uring = None


def make_tier(arg: str | None, collector=None, plane: str = 'server',
              ledger=None) -> TransportTier | None:
    """Build the tier for one server/client, or None when the
    resolved backend is ``asyncio`` (planes then keep their legacy
    write path untouched)."""
    backend = resolve_backend(arg)
    if backend == 'asyncio':
        return None
    return TransportTier(backend, collector=collector, plane=plane,
                         ledger=ledger)
