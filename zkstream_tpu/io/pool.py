"""Backend-set management: the rebuild's replacement for cueball.

The reference delegates multi-server handling to the cueball library: a
static resolver over the ``servers[]`` list, a ConnectionSet holding one
live connection (target 1, max 3), a retry/backoff recovery policy, and
periodic "decoherence" rebalancing toward more-preferred backends
(reference: lib/client.js:88-118).  There is no Python cueball, so this
module implements the same observable behavior directly:

- dial backends in preference order (optionally shuffled, seeded);
- per-attempt connect timeout + retry/delay policy matching the
  reference's recovery numbers (connect: 3000 ms x 3, 500 ms delay;
  default: 5000 ms x 3, 1000 ms delay);
- emit ``failed`` once when the initial retry policy exhausts on every
  backend, then keep dialing in monitor mode (cueball's failed state);
- when connected to a less-preferred backend, periodically try to move
  to a more-preferred one (decoherence; the live-session migration
  itself is the session's ``reattaching`` state, which reverts on
  failure);
- events: ``added(key, conn)``, ``removed(key, conn)``,
  ``stateChanged(state)`` with states starting/running/failed/stopped.
"""

from __future__ import annotations

import asyncio
import os
import random
import zlib

from ..utils.events import EventEmitter
from ..utils.fsm import note_transition
from ..utils.logging import Logger
from .backoff import BackoffPolicy
from .connection import Backend, ZKConnection
from ..utils.aio import ambient_loop

#: Back-compat alias: the reference's recovery objects carried fixed
#: {timeout, retries, delay}; the same constructor calls now get capped
#: exponential backoff + full jitter on the delay (io/backoff.py).
RecoveryPolicy = BackoffPolicy

DEFAULT_CONNECT_POLICY = BackoffPolicy(timeout=3000, retries=3,
                                       delay=500, cap=5000)
DEFAULT_POLICY = BackoffPolicy(timeout=5000, retries=3,
                               delay=1000, cap=30000)

#: How often to try moving back to a more-preferred backend, ms
#: (reference: decoherenceInterval 600 s, lib/client.js:110-111).
DEFAULT_DECOHERENCE_INTERVAL = 600 * 1000


def read_distribution_default() -> bool:
    """Process-wide default for new clients: ``ZKSTREAM_READ_
    DISTRIBUTION=1`` turns the client-side read plane on (off by
    default — single-connection clients keep the legacy shape)."""
    return os.environ.get('ZKSTREAM_READ_DISTRIBUTION') == '1'


def read_subset_default() -> int | None:
    """Process-wide read-plane subset cap: ``ZKSTREAM_READ_SUBSET=K``
    makes each client dial at most K read sessions from the live
    member list instead of one per backend (None/unset/0 = dial them
    all, the legacy shape).  Large fleets want this: per-client
    session count stays O(K) while membership grows."""
    v = os.environ.get('ZKSTREAM_READ_SUBSET')
    if not v:
        return None
    k = int(v)
    return k if k > 0 else None


class Resolver(EventEmitter):
    """Elastic backend source (README "Dynamic membership"): the
    live member list behind a client, replacing the static
    ``servers[]`` snapshot taken at construction.

    ``update(backends)`` adopts a new fleet — fed by whatever learns
    of a membership change first: the chaos campaigns push the
    ensemble's post-reconfig config directly, an operator can push a
    list scraped from ``mntr``'s ``zk_config_members`` row — and
    emits ``changed(backends)`` so subscribers (the ReadPlane)
    rebalance their dialed subset.  The primary session is NOT torn
    down on update: the pool keeps its current connection until it
    dies, then redials against the updated list (``backends`` is read
    per dial cycle), so a removed member drains rather than drops."""

    def __init__(self, backends: list[Backend]):
        super().__init__()
        self._backends = list(backends)

    @property
    def backends(self) -> list[Backend]:
        return list(self._backends)

    def update(self, backends) -> bool:
        """Adopt ``backends`` (Backend objects or (address, port)
        pairs) as the live list.  Returns True — and notifies
        subscribers — only when the membership actually changed."""
        new = []
        for b in backends:
            if isinstance(b, Backend):
                new.append(b)
            else:
                a, p = b
                new.append(Backend(a, int(p)))
        if [b.key for b in new] == [b.key for b in self._backends]:
            return False
        self._backends = new
        self.emit('changed', list(new))
        return True


class ReadPlane:
    """Client-side read scale-out (README "Read plane"): one
    lightweight read client per backend, so ``get``/``exists``/
    ``getACL``/``list`` fan out across followers and observers while
    writes, watches, MULTI and ``sync`` stay on the primary session.

    The ZooKeeper session contract survives the fan-out because every
    distributed read is zxid-gated TWICE:

    - client-side, the reply header carries the serving member's
      applied zxid; a reply below the client's floor (the newest zxid
      any of its connections has shown it — writes, reads, watch
      fires and the ``sync`` barrier all advance it) is DISCARDED and
      the read re-issued on the primary connection, whose member view
      is session-consistent by construction.  Stale state is never
      surfaced (``bounced`` counts these);
    - server-side, each read session carries its own
      ``lastZxidSeen``-seeded floor and the member's ReadGate blocks
      or bounces behind it (server/server.py).

    Spec verdicts (NO_NODE...) from a read session CANNOT be
    zxid-validated — an error reply carries no observable state — so
    they bounce to the primary too; only the primary's verdict is
    ever surfaced.  Every read therefore costs at most two RTTs and
    usually one, on a member that is not the write path.

    With ``subset=K`` the plane dials at most K read sessions, chosen
    from the live list by rendezvous hashing on the client seed —
    deterministic per client, spread across clients, and minimally
    churned when the membership changes.  A :class:`Resolver` makes
    the list live: on ``changed`` the plane retires subs whose
    backend left its selection and dials the newcomers (README
    "Dynamic membership")."""

    def __init__(self, client, backends: list[Backend],
                 subset: int | None = None,
                 resolver: Resolver | None = None):
        self._client = client
        self._resolver = (resolver if resolver is not None
                          else Resolver(backends))
        self._backends = self._resolver.backends
        self.subset = subset
        self.subs: list = []          # one lightweight Client each
        self._rr = 0
        self.started = False
        #: Monotone dial counter: each sub's seed derives from its
        #: dial ORDINAL, not its position in a mutable list, so the
        #: rerun-key determinism of chaos campaigns survives
        #: membership churn.
        self._dialed = 0
        #: Rendezvous-hash salt for subset selection (no seed: pick
        #: one per plane so unseeded clients still spread).
        self._salt = (client._seed if client._seed is not None
                      else random.randrange(1 << 30))
        #: reads served by the plane / discarded-stale re-issues /
        #: sub-connection failures that fell back to the primary
        self.distributed = 0
        self.bounced = 0
        self.fallbacks = 0
        #: config-change rebalances applied since start
        self.rebalances = 0
        self._resolver.on('changed', self._on_config_change)

    def summary(self) -> dict:
        """Read-path accounting for bench/campaign reports: where
        this client's reads actually went.  Reads the cache plane
        absorbed (README "Client cache plane") never reach this
        plane at all, so they are reported alongside — the cached
        arm of ``bench.py --read`` keys on exactly this split."""
        out = {'distributed': self.distributed,
               'bounced': self.bounced,
               'fallbacks': self.fallbacks,
               'rebalances': self.rebalances}
        cache = getattr(self._client, 'cache', None)
        if cache is not None:
            out['cached'] = cache.hits
            out['cache_misses'] = cache.misses
        return out

    def _select(self) -> list[Backend]:
        """The ≤``subset`` backends this plane should be dialing.
        Rendezvous hashing (highest crc32(salt|key) wins) keeps the
        choice deterministic per (seed, member list) and moves at
        most the displaced sessions when membership changes — a
        joining member steals ~K/N of the fleet's read sessions
        instead of triggering a full reshuffle."""
        backs = self._backends
        k = self.subset
        if k is None or k >= len(backs):
            return list(backs)
        scored = sorted(
            backs,
            key=lambda b: zlib.crc32(
                (b'%d|' % self._salt) + b.key.encode()))
        return scored[:k]

    def _dial(self, b: Backend):
        from ..client import Client   # deferred: client.py imports us
        c = self._client
        # inherit the parent's seed (derived per dial ordinal) and
        # retry policies: chaos rerun-key determinism reaches the
        # read sessions' backoff jitter too
        self._dialed += 1
        seed = (None if c._seed is None
                else c._seed * 1000003 + self._dialed)
        sub = Client(address=b.address, port=b.port,
                     session_timeout=c.session_timeout,
                     shuffle_backends=False, max_spares=0,
                     op_timeout=c.op_timeout, faults=c.faults,
                     log=c.log, seed=seed,
                     connect_policy=c.pool._connect_policy,
                     default_policy=c._retry_policy,
                     read_distribution=False)
        sub.start()
        self.subs.append(sub)
        return sub

    def start(self) -> None:
        """Dial one read client per selected backend (lazy
        sub-sessions: each is a full handshake — the read capacity IS
        those sessions landing on followers/observers)."""
        if self.started:
            return
        self.started = True
        for b in self._select():
            self._dial(b)

    def _on_config_change(self, backends: list[Backend]) -> None:
        """Resolver callback: re-run subset selection against the new
        member list, retire subs whose backend left it, dial the
        newcomers.  Retirement is a clean async close (the session's
        CLOSE_SESSION drains in the background) so in-flight reads on
        a leaving member finish or bounce — never hang."""
        self._backends = list(backends)
        if not self.started:
            return
        want = {b.key: b for b in self._select()}
        have = {}
        changed = False
        for sub in list(self.subs):
            key = sub.pool.backends[0].key
            if key in want and key not in have:
                have[key] = sub
            else:
                self.subs.remove(sub)
                ambient_loop().create_task(self._retire(sub))
                changed = True
        for key, b in want.items():
            if key not in have:
                self._dial(b)
                changed = True
        if changed:
            self.rebalances += 1

    @staticmethod
    async def _retire(sub) -> None:
        try:
            await asyncio.wait_for(sub.close(), 5)
        except (asyncio.TimeoutError, TimeoutError):
            sub.pool.stop()

    def pick(self, avoid_key: str | None = None):
        """The next connected read client, round-robin, preferring
        backends other than ``avoid_key`` (the primary's — reading
        there would not offload it); None when none is usable."""
        if not self.subs:
            return None
        n = len(self.subs)
        fallback = None
        for i in range(n):
            sub = self.subs[(self._rr + i) % n]
            if not sub.is_connected():
                continue
            key = sub.pool.backends[0].key
            if avoid_key is not None and key == avoid_key:
                fallback = fallback or (i, sub)
                continue
            self._rr = (self._rr + i + 1) % n
            return sub
        if fallback is not None:
            i, sub = fallback
            self._rr = (self._rr + i + 1) % n
            return sub
        return None

    async def close(self) -> None:
        self._resolver.remove_listener('changed',
                                       self._on_config_change)
        subs, self.subs = self.subs, []
        for sub in subs:
            try:
                await asyncio.wait_for(sub.close(), 5)
            except (asyncio.TimeoutError, TimeoutError):
                sub.pool.stop()


class ConnectionPool(EventEmitter):
    def __init__(self, client, backends: list[Backend],
                 connect_policy: BackoffPolicy = DEFAULT_CONNECT_POLICY,
                 default_policy: BackoffPolicy = DEFAULT_POLICY,
                 decoherence_interval: int = DEFAULT_DECOHERENCE_INTERVAL,
                 shuffle: bool = True, seed: int | None = None,
                 max_spares: int = 2):
        super().__init__()
        assert backends, 'at least one backend required'
        self._client = client
        self.log = getattr(client, 'log', Logger()).child(
            component='ConnectionPool')
        self._backends = list(backends)
        if shuffle:
            random.Random(seed).shuffle(self._backends)
        self._connect_policy = connect_policy
        self._default_policy = default_policy
        self._decoherence_interval = decoherence_interval
        #: Jitter stream for retry delays; derived from (not equal to)
        #: the shuffle seed so seeding one does not couple the other.
        self._jitter_seed = None if seed is None else seed ^ 0x5eed
        #: Monitor-mode redial backoff: persists across dial cycles so
        #: a long outage walks the delay up to the cap (storm
        #: decorrelation) and resets only on a successful connect.
        self._monitor_backoff = default_policy.backoff(self._jitter_seed)

        #: Circuit-breaker flag: True from the moment the initial
        #: retry policy exhausts on every backend ('failed' edge) until
        #: the next successful connect.  Surfaced as the 'degraded' /
        #: 'recovered' events here, re-emitted by the client, and read
        #: by the client's zookeeper_degraded gauge.
        self.degraded = False

        self.state = 'stopped'
        self.conn: ZKConnection | None = None
        self._conn_index: int | None = None
        #: Resolved when the pool's *current* connection dies; the dial
        #: loop parks on it while a connection is live.
        self._hold: asyncio.Future | None = None
        self._task: asyncio.Task | None = None
        self._decoherence_handle: asyncio.TimerHandle | None = None
        self._decoherence_task: asyncio.Task | None = None
        #: True while _try_rebalance is mid-flight: the old connection's
        #: death is then expected (the session migration destroys it)
        #: and must not wake the dial loop.
        self._rebalancing = False
        self._stopping = False
        self._failed_emitted = False

        #: Warm spares: TCP-connected, pre-handshake standbys promoted
        #: on failover instead of paying a fresh dial (cueball keeps up
        #: to 3 connections, target 1 — reference: lib/client.js:108-109).
        self.max_spares = max_spares
        self.spares: list[ZKConnection] = []
        self._spare_task: asyncio.Task | None = None
        self._spare_wake: asyncio.Event | None = None

    @property
    def backends(self) -> list[Backend]:
        return list(self._backends)

    def current_backend(self) -> Backend | None:
        return self.conn.backend if self.conn is not None else None

    def set_backends(self, backends: list[Backend]) -> None:
        """Adopt a new live backend list (README "Dynamic
        membership").  The current connection is left alone — a
        removed member drains in place and its eventual death redials
        against the updated list (the dial loop reads ``_backends``
        each cycle) — but parked spares on departed backends are
        destroyed so a failover cannot promote onto one."""
        self._backends = list(backends)
        keys = {b.key for b in self._backends}
        if self.conn is not None:
            self._conn_index = (
                self._backend_index(self.conn.backend)
                if self.conn.backend.key in keys else None)
        drop = [s for s in self.spares if s.backend.key not in keys]
        if drop:
            self.spares = [s for s in self.spares if s not in drop]
            for s in drop:
                s.destroy()
            self._wake_spares()

    # -- lifecycle --

    def start(self) -> None:
        assert self._task is None, 'pool already started'
        self._stopping = False
        self._set_state('starting')
        loop = ambient_loop()
        self._task = loop.create_task(self._dial_loop())
        if self.max_spares > 0:
            self._spare_wake = asyncio.Event()
            self._spare_task = loop.create_task(self._spare_loop())

    def stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._spare_task is not None:
            self._spare_task.cancel()
            self._spare_task = None
        spares, self.spares = self.spares, []
        for s in spares:
            s.destroy()
        self._cancel_decoherence()
        if self._decoherence_task is not None:
            self._decoherence_task.cancel()
            self._decoherence_task = None
        self._drop_conn(destroy=True)
        self._set_state('stopped')

    def get_state(self) -> str:
        """The pool's state name — the not-quite-FSM's analogue of
        FSM.get_state(), so the fsm metric bindings (utils/fsm.py)
        census it alongside the real machines."""
        return self.state

    def _set_state(self, st: str) -> None:
        if self.state != st:
            note_transition(self, self.state, st)
            self.state = st
            self.emit('stateChanged', st)

    # -- current-connection bookkeeping --

    def _install_conn(self, idx: int, conn: ZKConnection) -> None:
        self.conn = conn
        self._conn_index = idx
        self.emit('added', conn.backend.key, conn)
        self._wake_spares()

        def on_dead(*args):
            # Only react if this is still the pool's current connection
            # (after a rebalance swap the old conn dies later, already
            # dropped from our bookkeeping).
            if self.conn is conn:
                self._drop_conn(destroy=True)
                # During a rebalance the old connection's death is the
                # session migration destroying it; the rebalance task
                # owns the hold future's fate then.
                if self._rebalancing:
                    return
                if self._hold is not None and not self._hold.done():
                    self._hold.set_result(None)
        conn.on('error', on_dead)
        conn.on('close', on_dead)
        if not (conn.is_in_state('connected') or
                conn.is_in_state('closing')):
            on_dead()

    def _drop_conn(self, destroy: bool) -> None:
        if self.conn is None:
            return
        conn, self.conn = self.conn, None
        self._conn_index = None
        self.emit('removed', conn.backend.key, conn)
        if destroy:
            conn.destroy()

    # -- dialing --

    async def _await_conn(self, conn: ZKConnection, want_state: str,
                          timeout_ms: int) -> ZKConnection | None:
        """Wait until ``conn`` reaches ``want_state`` or dies (timeout
        included); returns the connection on success, else destroys it
        and returns None.  Shared by dialing, spare parking, and spare
        promotion so the wait/cleanup/cancel handling cannot diverge."""
        loop = ambient_loop()
        fut: asyncio.Future = loop.create_future()

        def settle(*args):
            if not fut.done():
                fut.set_result(None)

        def on_state(st):
            if st == want_state:
                settle()
        conn.on('stateChanged', on_state)
        conn.on('error', settle)
        conn.on('close', settle)
        try:
            await asyncio.wait_for(asyncio.shield(fut),
                                   timeout_ms / 1000.0)
        except asyncio.TimeoutError:
            pass
        except asyncio.CancelledError:
            conn.destroy()
            raise
        finally:
            conn.remove_listener('stateChanged', on_state)
            conn.remove_listener('error', settle)
            conn.remove_listener('close', settle)
        if conn.is_in_state(want_state):
            return conn
        conn.destroy()
        return None

    async def _dial_one(self, backend: Backend,
                        timeout_ms: int) -> ZKConnection | None:
        """Dial one backend; resolve to the connection if it reaches
        'connected' within the timeout, else None."""
        conn = ZKConnection(self._client, backend)
        conn.connect()
        return await self._await_conn(conn, 'connected', timeout_ms)

    def _note_connected(self) -> None:
        """A connect landed: clear the failure latches and reset the
        monitor backoff so the next outage starts from the base delay."""
        self._failed_emitted = False
        self._monitor_backoff.reset()
        if self.degraded:
            self.degraded = False
            self.log.info('left degraded mode: backend reachable again')
            self.emit('recovered')

    async def _dial_loop(self) -> None:
        """Keep one live connection.  The initial phase uses the connect
        policy; once it exhausts on all backends, emit 'failed', enter
        degraded mode, and keep dialing under the default policy
        (cueball monitor mode).  All retry delays are capped-exponential
        with full jitter (io/backoff.py) so a fleet of clients losing
        the same backend does not redial in synchronized waves.
        Failover promotes a warm spare when one is parked — no fresh
        TCP dial."""
        policy = self._connect_policy
        while not self._stopping:
            promoted = await self._promote_spare()
            if promoted is not None:
                idx, conn = promoted
                self._note_connected()
                await self._hold_connection(idx, conn)
                policy = self._connect_policy
                continue
            connected = False
            attempt_backoff = policy.backoff(self._jitter_seed)
            for attempt in range(policy.retries):
                for idx, backend in enumerate(self._backends):
                    if self._stopping:
                        return
                    conn = await self._dial_one(backend, policy.timeout)
                    if conn is None:
                        continue
                    self._note_connected()
                    connected = True
                    await self._hold_connection(idx, conn)
                    break
                if connected:
                    break
                if attempt + 1 < policy.retries:
                    await asyncio.sleep(
                        attempt_backoff.next_delay() / 1000.0)
            if connected:
                # The connection (or its successor) died; dial again
                # under the fresh-connect policy.
                policy = self._connect_policy
                continue
            if not self._failed_emitted:
                self._failed_emitted = True
                self.degraded = True
                self._set_state('failed')
                self.emit('degraded')
                self.log.warning('failed to connect to any ZK backend '
                                 '(exhausted retry policy); entering '
                                 'monitor mode (degraded)')
            policy = self._default_policy
            await asyncio.sleep(
                self._monitor_backoff.next_delay() / 1000.0)

    async def _hold_connection(self, idx: int, conn: ZKConnection) -> None:
        """Park while a connection (or a rebalance successor) is live."""
        loop = ambient_loop()
        self._hold = loop.create_future()
        self._install_conn(idx, conn)
        self._set_state('running')
        if idx > 0:
            self._arm_decoherence()
        try:
            await self._hold
        finally:
            self._hold = None
            self._cancel_decoherence()

    # -- warm spares (cueball target 1 / max 3) --

    def _wake_spares(self) -> None:
        if self._spare_wake is not None:
            self._spare_wake.set()

    def _backend_index(self, backend: Backend) -> int:
        for i, b in enumerate(self._backends):
            if b.key == backend.key:
                return i
        return len(self._backends) - 1

    async def _spare_loop(self) -> None:
        """Keep up to ``max_spares`` parked standbys while a live
        connection exists.  Dial failures retry on the default policy's
        delay; an unfillable deficit (no candidate backends, e.g. a
        single-address client already holding its one spare) parks on
        the wake event instead of polling."""
        while not self._stopping:
            await self._spare_wake.wait()
            self._spare_wake.clear()
            while (not self._stopping and self.conn is not None
                   and len(self.spares) < self.max_spares):
                outcome = await self._add_one_spare()
                if outcome is True:
                    continue
                if outcome is None:
                    break  # no candidates: wait for a wake, not a timer
                try:
                    await asyncio.wait_for(
                        self._spare_wake.wait(),
                        self._default_policy.delay / 1000.0)
                except asyncio.TimeoutError:
                    pass
                self._spare_wake.clear()

    async def _add_one_spare(self) -> bool | None:
        """True = spare added; False = candidates exist but none
        reachable (caller retries on a delay); None = no candidate
        backends at all (caller waits for a wake)."""
        cur = self.conn.backend.key if self.conn is not None else None
        have = {s.backend.key for s in self.spares}
        cands = [b for b in self._backends
                 if b.key != cur and b.key not in have]
        if not cands and len(self._backends) == 1 and not self.spares:
            # single-backend config: a same-backend spare still skips
            # the TCP dial on failover
            cands = [self._backends[0]]
        if not cands:
            return None
        for backend in cands:
            conn = await self._dial_spare(backend)
            if self._stopping or self.conn is None:
                if conn is not None:
                    conn.destroy()
                return False
            if conn is not None:
                self._install_spare(conn)
                return True
        return False

    async def _dial_spare(self, backend: Backend) -> ZKConnection | None:
        """TCP-connect a spare; resolve once it parks (or dies)."""
        conn = ZKConnection(self._client, backend, spare=True)
        conn.connect()
        return await self._await_conn(conn, 'parked',
                                      self._connect_policy.timeout)

    def _install_spare(self, conn: ZKConnection) -> None:
        self.spares.append(conn)
        self.log.debug('warm spare parked for %s', conn.backend.key)

        def on_dead(*args):
            if conn in self.spares:
                self.spares.remove(conn)
                self._wake_spares()
        conn.on('error', on_dead)
        conn.on('close', on_dead)

    async def _promote_spare(self) -> tuple[int, ZKConnection] | None:
        """Promote the most-preferred parked spare into a live
        connection (handshake only — the TCP dial already happened)."""
        while self.spares and not self._stopping:
            conn = min(self.spares,
                       key=lambda s: self._backend_index(s.backend))
            self.spares.remove(conn)
            if not conn.is_in_state('parked'):
                conn.destroy()
                continue
            self.log.info('promoting warm spare to %s', conn.backend.key)
            conn.promote()
            if await self._await_conn(conn, 'connected',
                                      self._connect_policy.timeout):
                self._wake_spares()
                return self._backend_index(conn.backend), conn
        return None

    # -- decoherence: move toward preferred backends --

    def rebalance_now(self) -> None:
        """Trigger one decoherence pass immediately instead of waiting
        out the interval: if the pool currently serves a less-preferred
        backend, dial the more-preferred ones and migrate the live
        session on success (the session's 'reattaching' state reverts
        on failure).  A no-op while already rebalancing, stopped, or
        on the most-preferred backend.  The ensemble chaos campaign
        uses this to force session migration mid-operation."""
        if self._stopping:
            return
        if self._decoherence_task is None or \
                self._decoherence_task.done():
            self._decoherence_task = ambient_loop().create_task(
                self._try_rebalance())

    def _arm_decoherence(self) -> None:
        self._cancel_decoherence()
        loop = ambient_loop()

        def fire():
            if self._decoherence_task is None or \
               self._decoherence_task.done():
                self._decoherence_task = loop.create_task(
                    self._try_rebalance())
        self._decoherence_handle = loop.call_later(
            self._decoherence_interval / 1000.0, fire)

    def _cancel_decoherence(self) -> None:
        if self._decoherence_handle is not None:
            self._decoherence_handle.cancel()
            self._decoherence_handle = None

    async def _try_rebalance(self) -> None:
        """Dial more-preferred backends; a successful handshake makes
        the session migrate (its 'reattaching' state handles revert on
        failure).  On success, swap the pool's current connection; the
        old one is destroyed by the session once the new one connects —
        an expected death that must not wake the dial loop (it would
        dial a redundant connection and force another migration)."""
        cur = self._conn_index
        if cur is None or cur == 0 or self.conn is None:
            return
        self._rebalancing = True
        try:
            for idx in range(cur):
                if self._stopping:
                    return
                backend = self._backends[idx]
                self.log.debug('decoherence: trying preferred backend '
                               '%s', backend.key)
                conn = await self._dial_one(backend,
                                            self._connect_policy.timeout)
                if self._stopping:
                    if conn is not None:
                        conn.destroy()
                    return
                if conn is not None:
                    old = self.conn
                    # Drop the old conn from bookkeeping without
                    # destroying it: the session owns its teardown
                    # after migration (it may already be dead and
                    # dropped by its death watch).
                    self.conn = None
                    self._conn_index = None
                    if old is not None:
                        self.emit('removed', old.backend.key, old)
                    self._install_conn(idx, conn)
                    if idx > 0:
                        self._arm_decoherence()
                    return
        finally:
            self._rebalancing = False
            # If every attempt failed AND the old connection died while
            # we were trying (its death watch deferred to us), wake the
            # dial loop now.
            if self.conn is None and self._hold is not None and \
               not self._hold.done():
                self._hold.set_result(None)
        if self._conn_index is not None and self._conn_index > 0:
            self._arm_decoherence()
