"""The per-TCP-connection state machine.

One ``ZKConnection`` owns one socket to one ZooKeeper backend and drives
it through ``init -> connecting -> handshaking -> connected ->
closing/error -> closed`` (reference: lib/connection-fsm.js:78-351).
Responsibilities mirror the reference exactly: xid allocation, the
pending-request table, reply routing, automatic ping keepalive with
piggybacking, SET_WATCHES queueing, and failing every outstanding
request exactly once on each teardown path.

Where the reference wires Node streams and sockets together, this uses
an asyncio ``Protocol`` feeding the symmetric ``PacketCodec``; requests
are represented by ``ZKRequest`` emitters ('reply'/'error'), which the
client facade adapts to awaitables.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Callable

from ..protocol import consts
from ..protocol.errors import ZKError, ZKPingTimeoutError, \
    ZKProtocolError, ZKThrottledError
from ..protocol.framing import PacketCodec
from ..utils.aio import set_nodelay
from ..utils.events import EventEmitter
from ..utils.fsm import FSM
from ..utils.logging import Logger
from .sendplane import SendPlane

METRIC_ZK_CONNECT_LATENCY = 'zookeeper_connect_latency_ms'


@dataclasses.dataclass(frozen=True)
class Backend:
    """One ZooKeeper server address (reference: cueball backend objects)."""

    address: str
    port: int

    @property
    def key(self) -> str:
        return '%s:%d' % (self.address, self.port)


def _finish_span(req, zxid: int | None = None, status: str = 'ok',
                 error: str | None = None) -> None:
    """Close a request's trace span, when the client attached one
    (utils/trace.py — the xid-correlated span is stamped with the
    reply zxid here, where the reply routes back by xid).  Safe on
    every settle path: a span closes once, first outcome wins."""
    span = getattr(req, 'span', None)
    if span is not None:
        span.finish(zxid=zxid, status=status, error=error)


class ZKRequest(EventEmitter):
    """One in-flight request: emits 'reply' (packet) or 'error' (exc)
    exactly once (reference: lib/connection-fsm.js:378-382).  The
    client facade may attach a trace ``span``; the connection's
    reply/error routing closes it."""

    def __init__(self, packet: dict):
        super().__init__()
        self.packet = packet
        #: Optional utils/trace.Span, attached by Client._start_op.
        self.span = None

    def as_future(self) -> asyncio.Future:
        """Adapt to an awaitable resolving to the reply packet.

        Plain ``on`` (not ``once``): reply/error fire at most once per
        request by contract, the ``done()`` guards make a double-settle
        harmless, and skipping the once-wrapper + removal scan matters
        on the per-op hot path."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self.on('reply', lambda pkt: fut.done() or fut.set_result(pkt))
        self.on('error', lambda err, *a: fut.done() or
                fut.set_exception(err))
        return fut


class _SocketProtocol(asyncio.Protocol):
    """Thin adapter: socket callbacks -> connection events."""

    def __init__(self, conn: 'ZKConnection'):
        self._conn = conn

    def connection_made(self, transport) -> None:
        set_nodelay(transport)
        self._conn.transport = transport
        self._conn.emit('sockConnect')

    def data_received(self, data: bytes) -> None:
        self._conn._sock_data(data)

    def eof_received(self) -> bool:
        self._conn.emit('sockEnd')
        return True  # keep half-open, like the reference's allowHalfOpen

    def connection_lost(self, exc) -> None:
        if exc is not None:
            self._conn.emit('sockError', exc)
        else:
            self._conn.emit('sockClose')


class ZKConnection(FSM):
    def __init__(self, client, backend: Backend, spare: bool = False):
        #: A spare parks after the TCP connect instead of handshaking:
        #: the ZK handshake binds the session to one connection, so a
        #: warm standby must stop just short of it.  ``promote()``
        #: resumes the normal lifecycle (pool failover skips the TCP
        #: dial; cueball's target 1 / max 3 warm set,
        #: reference: lib/client.js:108-109).
        self.spare = spare
        #: The owning client; consulted for the session during handshake
        #: (reference: lib/connection-fsm.js:174).
        self.client = client
        self.backend = backend
        # Child logger carrying this connection's address context
        # (reference: lib/connection-fsm.js:93-96); sessionId accretes
        # once connected (reference: lib/connection-fsm.js:209-211).
        self.log = getattr(client, 'log', Logger()).child(
            component='ZKConnectionFSM', zkAddress=backend.address,
            zkPort=backend.port)
        self.codec: PacketCodec | None = None
        self.transport = None
        self.session = None
        #: Optional FleetIngest: when the owning client carries one,
        #: connected-state bytes drain through the batched device
        #: pipeline instead of the per-socket scalar codec.
        self.ingest = getattr(client, 'ingest', None)
        #: Optional FaultInjector (io/faults.py): when the owning
        #: client carries one, dials, received bytes and outbound
        #: frames route through its seeded fault schedule.
        self.faults = getattr(client, 'faults', None)
        self.last_error: Exception | None = None
        self._xid = 0
        #: xid -> ZKRequest for everything awaiting a reply
        #: (reference: zcf_reqs).
        self.reqs: dict[int, ZKRequest] = {}
        self._dial_task: asyncio.Task | None = None
        #: Dial/handshake latency instrumentation: t0 set on entering
        #: 'connecting' (or on promote for a parked spare), observed
        #: into the histogram on reaching 'connected'.
        self._connect_t0: float | None = None
        #: Outbound cork (io/sendplane.py): every encoded frame goes
        #: through it; frames of one event-loop tick leave as a single
        #: transport.write — or, when the client carries a batched
        #: transport tier (io/transport.py), as part of the tick's one
        #: batched submission.  ``client.cork`` forces the cork on/off
        #: (None = process default, see sendplane.cork_default);
        #: ``client.flush_cap`` resizes the early-flush cap.
        collector = getattr(client, 'collector', None)
        self._tx = SendPlane(self._tx_write,
                             enabled=getattr(client, 'cork', None),
                             max_bytes=getattr(client, 'flush_cap',
                                               None),
                             collector=collector, plane='client',
                             tier=getattr(client, 'transport_tier',
                                          None),
                             transport_fn=lambda: self.transport)
        self._connect_latency = None
        if collector is not None:
            self._connect_latency = collector.histogram(
                METRIC_ZK_CONNECT_LATENCY,
                'TCP connect + ZK handshake latency, milliseconds, '
                'by backend')
            self.bind_fsm_metrics(collector, 'ZKConnection')
        super().__init__('init')

    # -- public controls (reference: lib/connection-fsm.js:51-76) --

    def connect(self) -> None:
        assert self.is_in_state('closed') or self.is_in_state('init')
        self.emit('connectAsserted')

    def close(self) -> None:
        if self.is_in_state('closed'):
            return
        self.emit('closeAsserted')

    def destroy(self) -> None:
        if self.is_in_state('closed'):
            return
        self.emit('destroyAsserted')

    def promote(self) -> None:
        """Turn a parked spare into a live connection: run the ZK
        handshake on the already-open socket."""
        assert self.is_in_state('parked'), self.get_state()
        self.spare = False
        # a promoted spare's latency sample measures the handshake
        # only — the TCP dial was paid when it parked
        self._connect_t0 = time.monotonic()
        self.emit('promoteAsserted')

    def next_xid(self) -> int:
        self._xid += 1
        return self._xid

    # -- states --

    def state_init(self, S) -> None:
        S.on(self, 'connectAsserted', lambda: S.goto_state('connecting'))

    def state_connecting(self, S) -> None:
        self.codec = PacketCodec(
            use_native=getattr(self.client, 'use_native_codec', None),
            max_frame=getattr(self.client, 'max_frame', None))
        self.log.debug('attempting new connection')
        self._connect_t0 = time.monotonic()

        async def dial():
            loop = asyncio.get_running_loop()
            try:
                if self.faults is not None:
                    # injected reconnect latency and/or refusal
                    await self.faults.before_connect(self.backend.key)
                await loop.create_connection(
                    lambda: _SocketProtocol(self),
                    self.backend.address, self.backend.port)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.emit('sockError', e)

        self._dial_task = asyncio.get_running_loop().create_task(dial())

        S.on(self, 'sockConnect', lambda: S.goto_state(
            'parked' if self.spare else 'handshaking'))

        def on_error(err):
            self.last_error = err
            S.goto_state('error')
        S.on(self, 'sockError', on_error)
        S.on(self, 'sockClose', lambda: S.goto_state('closed'))
        S.on(self, 'closeAsserted', lambda: S.goto_state('closed'))
        S.on(self, 'destroyAsserted', lambda: S.goto_state('closed'))

    def state_parked(self, S) -> None:
        """Warm spare: TCP is open, no ZK bytes exchanged.  Wakes into
        ``handshaking`` on promote; any socket activity or death tears
        it down (a ZK server must not speak first, so inbound data here
        is a protocol violation)."""
        S.on(self, 'promoteAsserted',
             lambda: S.goto_state('handshaking'))

        def on_data(_data):
            self.last_error = ZKProtocolError('UNEXPECTED_PACKET',
                'Server sent data before the handshake')
            S.goto_state('error')
        S.on(self, 'sockData', on_data)

        def on_error(err):
            self.last_error = err
            S.goto_state('error')
        S.on(self, 'sockError', on_error)

        def on_end():
            self.last_error = ZKProtocolError('CONNECTION_LOSS',
                'Connection closed unexpectedly.')
            S.goto_state('error')
        S.on(self, 'sockEnd', on_end)
        S.on(self, 'sockClose', on_end)
        S.on(self, 'closeAsserted', lambda: S.goto_state('closed'))
        S.on(self, 'destroyAsserted', lambda: S.goto_state('closed'))

    def state_handshaking(self, S) -> None:
        def on_data(data):
            try:
                pkts = self.codec.decode(data)
            except ZKProtocolError as e:
                self.last_error = e
                S.goto_state('error')
                return
            if not pkts:
                return
            # Exactly one packet may arrive during the connect phase
            # (reference: lib/connection-fsm.js:130-140).
            if len(pkts) > 1:
                self.last_error = ZKProtocolError('UNEXPECTED_PACKET',
                    'Received unexpected additional packet during '
                    'connect phase')
                S.goto_state('error')
                return
            pkt = pkts[0]
            if pkt['protocolVersion'] != consts.PROTOCOL_VERSION:
                self.last_error = ZKProtocolError('VERSION_INCOMPAT',
                    'Server version is not compatible')
                S.goto_state('error')
                return
            self.emit('packet', pkt)
        S.on(self, 'sockData', on_data)

        def on_error(err):
            self.last_error = err
            S.goto_state('error')
        S.on(self, 'sockError', on_error)

        def on_end():
            self.last_error = ZKProtocolError('CONNECTION_LOSS',
                'Connection closed unexpectedly.')
            S.goto_state('error')
        S.on(self, 'sockEnd', on_end)
        S.on(self, 'sockClose', on_end)
        S.on(self, 'closeAsserted', lambda: S.goto_state('closed'))
        S.on(self, 'destroyAsserted', lambda: S.goto_state('closed'))

        self.session = self.client.get_session()
        if self.session is None:
            S.goto_state('closed')
            return

        # Guard against a session already attaching to another connection
        # (reference: lib/connection-fsm.js:180-187, the nasty.test.js
        # monitor-mode race).
        if self.session.is_attaching():
            self.log.debug('session in state %s while handshaking',
                           self.session.get_state())
            self.last_error = ZKProtocolError('ATTACH_RACE',
                'ZKSession attaching to another connection')
            S.goto_state('error')
            return

        def on_session_state(st):
            if st == 'attached':
                S.goto_state('connected')
        S.on(self.session, 'stateChanged', on_session_state)

        self.session.attach_and_send_cr(self)

    def state_connected(self, S) -> None:
        # Handshake is over: steady-state request/reply framing from here
        # (the reference flips this per-frame via isInState checks).
        self.codec.handshaking = False
        self.log = self.log.child(
            sessionId=self.session.get_session_id())

        if self._connect_latency is not None and \
                self._connect_t0 is not None:
            self._connect_latency.observe(
                (time.monotonic() - self._connect_t0) * 1000.0,
                {'backend': self.backend.key})
            self._connect_t0 = None

        ping_interval = max(self.session.get_timeout() / 4, 2000)
        S.interval(ping_interval, self.ping)

        def deliver(pkts, err):
            for pkt in pkts:
                self.emit('packet', pkt)
                # Notifications are the session's business
                # (reference: lib/connection-fsm.js:223-224).
                if pkt['opcode'] != 'NOTIFICATION':
                    self.process_reply(pkt)
            if err is not None:
                self.last_error = err
                S.goto_state('error')

        if self.ingest is not None:
            # Fleet drain: in the ingest's BATCH regime bytes go to the
            # batched device pipeline, which routes the decoded packets
            # back through the same deliver path, so semantics cannot
            # diverge from the scalar drain below.  In its pass-through
            # (direct) regime the connection runs the per-socket drain
            # itself — the ingest only gets the byte/frame counts its
            # dispatch policy needs — so the regime where batching does
            # not pay costs one flag check over the no-ingest path.
            self.ingest.register(self)
            S.defer(lambda: self.ingest.unregister(self))

            def on_sock(data):
                ing = self.ingest
                if not ing.direct:
                    ing.feed(self, data)
                    return
                # Deliberately restates FleetIngest._deliver_direct
                # minus its emit hop: calling deliver() directly here
                # skips one event dispatch per segment, which is the
                # point of the pass-through.  Slot residue cannot
                # exist in this regime (register/flip keep it in the
                # codec), so no splice is needed.
                err = None
                try:
                    pkts = self.codec.decode(data)
                except ZKProtocolError as e:
                    pkts = getattr(e, 'packets', [])
                    err = e
                ing.note_direct(len(data), len(pkts))
                deliver(pkts, err)
            S.on(self, 'sockData', on_sock)
            S.on(self, 'ingestDeliver', deliver)
        else:
            def on_data(data):
                err = None
                try:
                    pkts = self.codec.decode(data)
                except ZKProtocolError as e:
                    # Deliver packets decoded before the bad frame first.
                    pkts = getattr(e, 'packets', [])
                    err = e
                deliver(pkts, err)
            S.on(self, 'sockData', on_data)

        def on_error(err):
            self.last_error = err
            S.goto_state('error')
        S.on(self, 'sockError', on_error)

        def on_end():
            self.last_error = ZKProtocolError('CONNECTION_LOSS',
                'Connection closed unexpectedly.')
            S.goto_state('error')
        S.on(self, 'sockEnd', on_end)
        S.on(self, 'sockClose', on_end)

        S.on(self, 'closeAsserted', lambda: S.goto_state('closing'))
        S.on(self, 'destroyAsserted', lambda: S.goto_state('closed'))

        def on_ping_timeout():
            self.last_error = ZKPingTimeoutError()
            S.goto_state('error')
        S.on(self, 'pingTimeout', on_ping_timeout)

        S.immediate(lambda: self.emit('connect'))

    def state_closing(self, S) -> None:
        """Drain outstanding requests, then send CLOSE_SESSION and wait
        for its reply (reference: lib/connection-fsm.js:263-307)."""
        close_xid: list[int | None] = [None]

        def send_close_session():
            if close_xid[0] is not None:
                return
            close_xid[0] = self.next_xid()
            self.log.info('sent CLOSE_SESSION request (xid %d)',
                          close_xid[0])
            self._write({'opcode': 'CLOSE_SESSION', 'xid': close_xid[0]})
            # the EOF must not cut ahead of the corked CLOSE_SESSION —
            # hard: a batched transport tier defers flush_now to the
            # tick submission, which would land after the write_eof
            self._tx.flush_hard()
            try:
                if self.transport and self.transport.can_write_eof():
                    self.transport.write_eof()
            except (OSError, RuntimeError):
                pass

        def on_data(data):
            try:
                pkts = self.codec.decode(data)
            except ZKProtocolError as e:
                self.last_error = e
                S.goto_state('closed')
                return
            for pkt in pkts:
                if pkt['xid'] == close_xid[0]:
                    S.goto_state('closed')
                    return
                self.process_reply(pkt)
                if not self.reqs:
                    send_close_session()
        S.on(self, 'sockData', on_data)

        def on_error(err):
            self.last_error = err
            S.goto_state('closed')
        S.on(self, 'sockError', on_error)
        S.on(self, 'sockEnd', lambda: S.goto_state('closed'))
        S.on(self, 'sockClose', lambda: S.goto_state('closed'))
        S.on(self, 'destroyAsserted', lambda: S.goto_state('closed'))

        if not self.reqs:
            send_close_session()

    def state_error(self, S) -> None:
        self.log.warning('error communicating with ZK: %s',
                         self.last_error)
        reqs, self.reqs = self.reqs, {}
        # Pending ops surface the ZK error taxonomy, never a raw OS
        # exception: a socket-level error becomes CONNECTION_LOSS with
        # the original chained as __cause__ (the clean-close straggler
        # path already spoke ZKProtocolError only).
        req_err = self.last_error
        if not isinstance(req_err, (ZKProtocolError, ZKError)):
            wrapped = ZKProtocolError(
                'CONNECTION_LOSS', 'Connection lost: %s' % (req_err,))
            wrapped.__cause__ = req_err
            req_err = wrapped
        for req in reqs.values():
            _finish_span(req, status='error',
                         error=getattr(req_err, 'code', None)
                         or type(req_err).__name__)
            req.emit('error', req_err)

        # Deliberately not scope-bound: the 'error' event must fire even
        # though we leave this state immediately
        # (reference: lib/connection-fsm.js:317-323).
        err = self.last_error
        asyncio.get_running_loop().call_soon(lambda: self.emit('error', err))

        S.goto_state('closed')

    def state_closed(self, S) -> None:
        if self._dial_task is not None and not self._dial_task.done():
            self._dial_task.cancel()
        self._dial_task = None
        gate = getattr(self, '_fault_rx_gate', None)
        if gate is not None:
            gate.close()
        if self.transport is not None:
            try:
                self.transport.abort()
            except (OSError, RuntimeError):
                pass
        self.transport = None
        # corked frames have nowhere to go once the socket is dead
        self._tx.reset()

        S.on(self, 'connectAsserted', lambda: S.goto_state('connecting'))

        def fail_stragglers():
            self.emit('close')
            # Fail any remaining outstanding requests or they would hang
            # forever (reference: lib/connection-fsm.js:338-350).
            # Their spans settle as 'abandoned': the op was evicted
            # from the pending table without a reply ever routing —
            # distinct from a request that saw a typed error — so the
            # ring can never hold an open span after teardown (the
            # chaos campaigns assert exactly that).
            err = ZKProtocolError('CONNECTION_LOSS', 'Connection closed.')
            reqs, self.reqs = self.reqs, {}
            for req in reqs.values():
                _finish_span(req, status='abandoned', error=err.code)
                req.emit('error', err)
        S.immediate(fail_stragglers)

    # -- request plumbing --

    def _sock_data(self, data: bytes) -> None:
        """Socket bytes -> 'sockData', via the fault schedule when an
        injector is installed (splits/delays/dups/mid-frame resets)."""
        if self.faults is None:
            self.emit('sockData', data)
        else:
            self.faults.rx(self, data)

    def _tx_write(self, data: bytes) -> None:
        """The send plane's sink: one coalesced buffer per flush."""
        if self.transport is not None:
            self.transport.write(data)

    def _write(self, pkt: dict) -> None:
        data = self.codec.encode(pkt)
        if self.faults is not None:
            # Per-frame fault boundary, BEFORE the cork: may truncate
            # the frame and schedule an injected reset.
            out = self.faults.tx(self, data)
            if out is None:
                return
            if out is not data:
                # A fault fired on this frame.  Its scheduled reset
                # lands next tick — deliver everything already corked
                # plus the truncated frame NOW, in stream order, so
                # the reset still targets exactly this frame (hard:
                # the batched transport tier must drain synchronously
                # or the direct write below would overtake it).
                self._tx.flush_hard()
                self._tx_write(out)
                return
        if self.transport is None:
            return
        self._tx.send(data)

    def process_reply(self, pkt: dict) -> None:
        """Route a reply to its pending request
        (reference: lib/connection-fsm.js:353-376)."""
        xid = pkt['xid']
        if xid > 0:
            # One reply settles a normal request; dropping it here
            # (rather than via per-request cleanup listeners) keeps the
            # map tight.  Reserved xids (PING/SET_WATCHES) stay: their
            # handlers manage piggybacking and pop themselves.
            req = self.reqs.pop(xid, None)
        else:
            req = self.reqs.get(xid)
        self.log.trace('server replied to xid %d err %s',
                       xid, pkt['err'])
        if req is None:
            return
        if pkt['err'] == 'OK':
            _finish_span(req, zxid=pkt.get('zxid'))
            req.emit('reply', pkt)
        else:
            _finish_span(req, zxid=pkt.get('zxid'), status='error',
                         error=pkt['err'])
            # the overloaded-member bounce gets its typed class so
            # the client's write path can key its backoff+retry on
            # isinstance instead of string-matching the code
            err = (ZKThrottledError()
                   if pkt['err'] == 'THROTTLED'
                   else ZKError(pkt['err']))
            req.emit('error', err, pkt)

    def request(self, pkt: dict) -> ZKRequest:
        """Send a normal (positive-xid) request
        (reference: lib/connection-fsm.js:384-408)."""
        if not self.is_in_state('connected'):
            raise ZKProtocolError('CONNECTION_LOSS',
                'Client must be connected to send requests')
        req = ZKRequest(pkt)
        pkt['xid'] = self.next_xid()
        self.reqs[pkt['xid']] = req
        self.log.trace('sent request xid %d opcode %s',
                       pkt['xid'], pkt['opcode'])
        self._write(pkt)
        return req

    def send(self, pkt: dict) -> None:
        """Raw send, used by the session for ConnectRequests
        (reference: lib/connection-fsm.js:410-413)."""
        self._write(pkt)

    def ping(self, cb: Callable | None = None) -> None:
        """Keep-alive ping on the reserved xid; concurrent pings
        piggyback on the in-flight one
        (reference: lib/connection-fsm.js:415-463)."""
        if not self.is_in_state('connected'):
            raise ZKProtocolError('CONNECTION_LOSS',
                'Client must be connected to send packets')
        pkt = {'xid': consts.XID_PING, 'opcode': 'PING'}
        existing = self.reqs.get(consts.XID_PING)
        if existing is not None:
            if cb:
                existing.once('reply', lambda _pkt: cb(None, None))
                existing.once('error', lambda err, *a: cb(err, None))
            return
        req = ZKRequest(pkt)
        self.reqs[consts.XID_PING] = req
        timeout_ms = max(self.session.get_timeout() / 8, 2000)
        loop = asyncio.get_running_loop()
        t1 = time.monotonic()

        def on_reply(rpkt):
            self.reqs.pop(consts.XID_PING, None)
            timer.cancel()
            latency = (time.monotonic() - t1) * 1000.0
            self.log.debug('ping ok in %d ms', latency)
            if cb:
                cb(None, latency)

        def on_error(err, *args):
            self.reqs.pop(consts.XID_PING, None)
            timer.cancel()
            if cb:
                cb(err, None)

        def on_timeout():
            req.remove_listener('reply', on_reply)
            self.emit('pingTimeout')

        req.once('reply', on_reply)
        req.once('error', on_error)
        timer = loop.call_later(timeout_ms / 1000.0, on_timeout)
        self._write(pkt)

    def set_watches(self, events: dict, rel_zxid: int,
                    cb: Callable,
                    opcode: str = 'SET_WATCHES') -> None:
        """Send SET_WATCHES on its reserved xid; a second call while one
        is in flight queues behind it
        (reference: lib/connection-fsm.js:465-499).  ``opcode`` selects
        the five-list SET_WATCHES2 variant when the session also
        replays persistent (ADD_WATCH) registrations."""
        if not self.is_in_state('connected'):
            raise ZKProtocolError('CONNECTION_LOSS',
                'Client must be connected to send packets (is in state %s)'
                % (self.get_state(),))
        pkt = {'xid': consts.XID_SET_WATCHES, 'opcode': opcode,
               'relZxid': rel_zxid, 'events': events}
        existing = self.reqs.get(consts.XID_SET_WATCHES)
        if existing is not None:
            existing.once('reply',
                lambda _pkt: self.set_watches(events, rel_zxid, cb,
                                              opcode))
            existing.once('error', lambda err, *a: cb(err))
            return
        req = ZKRequest(pkt)
        self.reqs[consts.XID_SET_WATCHES] = req

        def on_reply(rpkt):
            self.reqs.pop(consts.XID_SET_WATCHES, None)
            cb(None)

        def on_error(err, *args):
            self.reqs.pop(consts.XID_SET_WATCHES, None)
            cb(err)

        req.once('reply', on_reply)
        req.once('error', on_error)
        self._write(pkt)
