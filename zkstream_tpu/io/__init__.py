"""Connection, session, watcher and pool layers (reference layers
L4/L4'/L5: lib/connection-fsm.js, lib/zk-session.js, cueball)."""

from .backoff import Backoff, BackoffPolicy  # noqa: F401
from .connection import Backend, ZKConnection, ZKRequest  # noqa: F401
from .faults import FaultConfig, FaultInjector, FaultPlan  # noqa: F401
from .invariants import History, check_history  # noqa: F401
from .pool import ConnectionPool, RecoveryPolicy  # noqa: F401
from .session import ZKSession  # noqa: F401
from .watcher import LostWakeupError, ZKWatcher, ZKWatchEvent  # noqa: F401
