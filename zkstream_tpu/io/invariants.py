"""History-checked invariants for ensemble-tier chaos campaigns.

The transport-tier campaign (io/faults.py ``run_schedule``) checks a
handful of end-state facts inline.  The ensemble tier — member kills,
restarts, partitions, session migration — needs more: whether an
outcome is a bug depends on *when* it happened relative to failovers
and session lifecycle, so the campaign records every operation,
watch fire, member event and session edge into an append-only
:class:`History`, and :func:`check_history` replays it after the
schedule against the leader's final database.

Invariants (each one a ``check_*`` function, composed by
:func:`check_history`; every violation string stands alone so a
failing seed's report reads without the source):

1. **No acked-write loss across failover** — an acked create (with no
   later acked delete) exists with its exact data; an acked delete
   stays deleted; the newest acked set to the shared counter node is
   <= the final value (a later *unacked* set may have applied:
   at-least-once ambiguity).  An op that died with an outcome-unknown
   error (CONNECTION_LOSS / DEADLINE_EXCEEDED / PING_TIMEOUT after
   the request was sent) is recorded as *ambiguous* and weakens only
   the expectations it could have changed.
2. **Zxid monotonicity per session** — the reply zxids stamped on
   successful *write* completions (CREATE / SET_DATA / DELETE / SYNC)
   never decrease per session, in completion order.  Writes are
   sequenced by the single leader and the serving member catches its
   store up through the write before replying, so a decrease means a
   reply was misrouted or a session resumed against state older than
   it had already observed.
3. **Ephemeral lifetime** — an ephemeral node exists exactly while
   its owning session does: while the session is live it must be
   present (unless acked- or ambiguously deleted); once the leader
   confirms the session expired or closed it must be gone.
4. **Sequential numbering** — acked SEQUENTIAL creates under a parent
   get strictly increasing numbers in ack order, and the total of the
   gaps is covered by the ambiguous sequential creates on that parent
   (an outcome-unknown create may have consumed a number; nothing
   else may).
5. **Watch at-most-once per arm** — no watch event is delivered twice
   for the same change: per (path, kind) no duplicated zxid, and at
   most one 'deleted' per single-deletion path (re-arms over the same
   absence stay silent).
6. **Durable recovery** (:func:`check_durable_recovery`, the
   durability plane's invariant) — after a full-ensemble SIGKILL, the
   database recovered from the write-ahead log (server/persist.py:
   newest valid snapshot + replayed tail, torn final record
   tolerated) holds every unambiguously-acked write, with the same
   ambiguity rules as invariant 1.  Both chaos tiers run it against a
   crash image cut at an injector-chosen fsync window.
7. **Election safety** (:func:`check_election`, the coordination
   plane — server/election.py) — over the recorded election history:
   at most ONE leader is ever elected per epoch, and elected epochs
   strictly increase in history order.  A second winner at an epoch
   means the fencing token was forged or reused; a non-increasing
   epoch means a deposed leader's era could be mistaken for current.
   Invariants 1 and 6 run unchanged across elections — failover must
   not lose an acked write.
8. **Quorum-commit era** (PR 12) — three strengthenings: invariant
   1/6 take a ``quorum_zxid`` floor under which acks are NEVER
   demoted (a majority of mirrors ingested the txn before its ack
   left — server/replication.py QuorumGate);
   :func:`check_session_continuity` asserts a session that stayed
   inside its timeout across a full restart keeps its identity and
   its ephemerals (durable sessions, server/persist.py); and
   :func:`check_multi_atomic` asserts no MULTI batch is ever
   partially visible — in the live tree or across a torn-record
   recovery (one CRC frame per batch).
9. **Per-key linearizability** (analysis/linearize.py, the
   concurrent tier — io/faults.py ``run_concurrent_schedule``) —
   over the TWO-SIDED half of the history (:meth:`History.invoke` /
   :meth:`History.settle` interval records), every key's operations
   admit a Wing&Gong/Lowe-style linearization against the sequential
   znode spec, MULTI batches atomic across their keys, ambiguity
   rules exactly invariant 1's (an outcome-unknown op may linearize
   as applied or be dropped).  Histories with no interval records —
   every pre-concurrent-tier history — pass vacuously; the one-sided
   recorders below stay as the degenerate interval (invocation and
   response at the same history point), so invariants 1-8 run
   unchanged on old and new histories alike.

The history is plain data (a list of dicts) so it can ride a JSON
trace dump next to the span ring; :func:`format_history` renders the
member-event timeline for failure reports (``columns=True`` renders
the per-client interleaving of the interval records instead).
"""

from __future__ import annotations

#: Opcodes whose successful replies must carry monotone zxids per
#: session (leader-sequenced; the member catches up before replying).
WRITE_OPS = frozenset(('CREATE', 'SET_DATA', 'DELETE', 'SYNC'))

#: Error codes that leave a sent write's outcome unknown.
AMBIGUOUS_CODES = frozenset(('CONNECTION_LOSS', 'DEADLINE_EXCEEDED',
                             'PING_TIMEOUT'))


class History:
    """Append-only campaign history.  Every record is a dict with a
    ``kind`` and a monotonically increasing ``t`` (history order —
    completion order for ops, delivery order for watch fires)."""

    def __init__(self) -> None:
        self.records: list[dict] = []
        self._next_call = 0

    def _add(self, kind: str, **fields) -> dict:
        rec = {'kind': kind, 't': len(self.records)}
        rec.update(fields)
        self.records.append(rec)
        return rec

    # -- recorders --

    def invoke(self, op: str, path: str | None, client: int = 0,
               session_id: int = 0, data: bytes | None = None,
               version: int | None = None,
               subs: list | None = None) -> int:
        """Open one two-sided interval: the op is about to be SENT.
        Returns the call id :meth:`settle` closes the interval with.
        ``op`` is one of create/set/delete/get/exists/multi; ``subs``
        (multi only) is ``[(op, path, data, version)]``.  The interval
        pair is what invariant 9 (analysis/linearize.py) searches —
        an invoke with no settle is treated as outcome-unknown."""
        call = self._next_call
        self._next_call += 1
        self._add('invoke', call=call, op=op, path=path,
                  client=client, session_id=session_id, data=data,
                  version=version,
                  subs=list(subs) if subs is not None else None)
        return call

    def settle(self, call: int, status: str,
               zxid: int | None = None, data: bytes | None = None,
               version: int | None = None,
               error: str | None = None) -> dict:
        """Close the interval opened by :meth:`invoke`.  ``status``:
        ``'ok'`` (applied; ``zxid``/``data``/``version`` carry what
        the reply showed — reads record their observed payload here),
        ``'error'`` (a definite spec verdict: NO_NODE / NODE_EXISTS /
        BAD_VERSION — the op linearizes as a no-effect op yielding
        exactly that error), ``'fail'`` (definitely never applied —
        raised before send, or a typed fencing bounce; excluded from
        the search), or ``'unknown'`` (outcome-unknown: may linearize
        as applied or be dropped, invariant 1's ambiguity rule)."""
        return self._add('settle', call=call, status=status,
                         zxid=zxid, data=data, version=version,
                         error=error)

    def op(self, op: str, path: str | None, status: str,
           zxid: int | None = None, session_id: int = 0,
           error: str | None = None) -> dict:
        """One completed client op (every completion path)."""
        return self._add('op', op=op, path=path, status=status,
                         zxid=zxid, session_id=session_id, error=error)

    def acked_create(self, path: str, data: bytes, session_id: int,
                     ephemeral: bool = False,
                     sequential_parent: str | None = None,
                     zxid: int | None = None) -> dict:
        return self._add('ack', op='create', path=path, data=data,
                         session_id=session_id, ephemeral=ephemeral,
                         seq_parent=sequential_parent, zxid=zxid)

    def acked_delete(self, path: str, session_id: int,
                     zxid: int | None = None) -> dict:
        return self._add('ack', op='delete', path=path,
                         session_id=session_id, zxid=zxid)

    def acked_set(self, path: str, index: int,
                  session_id: int, zxid: int | None = None) -> dict:
        return self._add('ack', op='set', path=path, index=index,
                         session_id=session_id, zxid=zxid)

    def multi_batch(self, subs: list, session_id: int = 0,
                    zxid: int | None = None) -> dict:
        """One ATTEMPTED MULTI batch: ``subs`` is ``[(op, path,
        data)]`` (data None where the op carries none), recorded
        whatever the outcome — acked, rejected or outcome-unknown —
        because atomicity binds them all: invariant 8
        (:func:`check_multi_atomic`) demands the batch be visible
        whole or not at all."""
        return self._add('multi', subs=list(subs),
                         session_id=session_id, zxid=zxid)

    def ambiguous(self, op: str, path: str | None,
                  session_id: int = 0,
                  sequential_parent: str | None = None) -> dict:
        """A write whose request was sent but whose outcome is
        unknown (typed CONNECTION_LOSS / deadline / ping timeout)."""
        return self._add('ambig', op=op, path=path,
                         session_id=session_id,
                         seq_parent=sequential_parent)

    def watch_fire(self, path: str, event: str,
                   zxid: int | None) -> dict:
        return self._add('watch', path=path, event=event, zxid=zxid)

    def member_event(self, event: str, member: int | str) -> dict:
        """Ensemble-tier event: kill / restart / partition / heal /
        lag / migrate."""
        return self._add('member', event=event, member=member)

    def election(self, member: int | str, epoch: int) -> dict:
        """A completed leader election (server/election.py): ``member``
        won ``epoch``.  Invariant 7 replays these."""
        return self._add('election', member=member, epoch=epoch)

    def reconfig(self, version: int, phase: str, epoch: int,
                 voters, old_voters=None, observers=()) -> dict:
        """A committed membership-change record (store.py
        ``propose_reconfig``/``commit_reconfig``): config ``version``
        installed under leadership ``epoch``, ``phase`` 'joint'
        (C_old+C_new both govern) or 'final'.  The invariant-7
        extension (:func:`check_reconfig`) replays these."""
        return self._add('reconfig', version=version, phase=phase,
                         epoch=epoch, voters=tuple(voters),
                         old_voters=tuple(old_voters or ()),
                         observers=tuple(observers or ()))

    def session_event(self, event: str, session_id: int) -> dict:
        return self._add('session', event=event,
                         session_id=session_id)

    # -- selectors --

    def of_kind(self, kind: str) -> list[dict]:
        return [r for r in self.records if r['kind'] == kind]

    def member_timeline(self) -> list[dict]:
        return self.of_kind('member')


# ---------------------------------------------------------------------
# Invariant checkers.  Each returns a list of violation strings.
# ---------------------------------------------------------------------


def check_acked_durability(history: History, db,
                           floor_zxid: int | None = None,
                           quorum_zxid: int | None = None) -> list[str]:
    """Invariant 1: no acked write lost.  ``db`` is the leader
    ZKDatabase (reads bypass the wire; faults are stopped).

    ``floor_zxid`` (recovery checks, :func:`check_durable_recovery`):
    acks sequenced past the newest *known-durable* zxid — possible
    only when an fsync failed under them — are demoted to their
    outcome-unknown form instead of enforced; ``None`` enforces every
    ack.

    ``quorum_zxid`` (quorum-commit, server/replication.py
    QuorumGate): the strengthened form — an ack at or under the
    quorum floor is NEVER demoted, whatever the fsync floor says: a
    majority of mirrors ingested the txn before the ack left, so it
    must survive a leader death regardless of the leader's own disk.
    Only meaningful where quorum ack implies a surviving copy (the
    OS-process tier's mirror WALs; the in-process ensemble's replicas
    share the one crash image and keep floor semantics)."""
    from ..server.store import ZKOpError

    out: list[str] = []
    # final acked action per created path, in history order; the
    # ambiguity excuses are ORDERED — an acked op that postdates an
    # ambiguous one proves that ambiguity resolved, so it spends the
    # excuse
    created: dict[str, dict] = {}
    deleted: dict[str, dict] = {}
    ambig_delete: set[str] = set()
    ambig_create: set[str] = set()
    last_set: dict[str, int] = {}
    for r in history.records:
        if r['kind'] == 'ack':
            if floor_zxid is not None and (
                    r.get('zxid') is None or r['zxid'] > floor_zxid) \
                    and not (quorum_zxid is not None
                             and r.get('zxid') is not None
                             and r['zxid'] <= quorum_zxid):
                # past the durable floor: this ack's txn may not have
                # reached disk before the crash — demote, do not
                # enforce (it may legitimately be present OR absent)
                if r['op'] == 'create' and r.get('path'):
                    ambig_create.add(r['path'])
                elif r['op'] == 'delete':
                    ambig_delete.add(r['path'])
                continue
            if r['op'] == 'create':
                created[r['path']] = r
                deleted.pop(r['path'], None)
                ambig_delete.discard(r['path'])
            elif r['op'] == 'delete':
                deleted[r['path']] = r
                created.pop(r['path'], None)
                ambig_delete.discard(r['path'])
                ambig_create.discard(r['path'])
                # sets acked before this delete were deleted with the
                # node; they say nothing about a later re-create
                last_set.pop(r['path'], None)
            elif r['op'] == 'set':
                last_set[r['path']] = max(
                    last_set.get(r['path'], -1), r['index'])
        elif r['kind'] == 'ambig':
            if r['op'] == 'delete':
                ambig_delete.add(r['path'])
            elif r['op'] == 'create' and r.get('path'):
                ambig_create.add(r['path'])
    for path, rec in created.items():
        if path in deleted:
            continue
        try:
            got, _stat = db.get_data(path)
        except ZKOpError:
            if path in ambig_delete:
                continue            # an unacked delete may have landed
            if rec.get('ephemeral'):
                continue            # judged by check_ephemerals
            out.append('acked create %s lost (NO_NODE after campaign)'
                       % (path,))
            continue
        if path in last_set:
            continue                # value judged by the set check
        if rec['data'] is not None and bytes(got) != rec['data']:
            out.append('acked create %s holds %r, expected %r'
                       % (path, bytes(got), rec['data']))
    for path in deleted:
        try:
            db.get_data(path)
        except ZKOpError:
            continue
        if path in ambig_create:
            continue            # an unacked re-create may have landed
        out.append('acked delete %s did not stick' % (path,))
    for path, idx in last_set.items():
        if path in deleted:
            continue
        try:
            got, _stat = db.get_data(path)
            have = int(bytes(got).rsplit(b'v', 1)[1])
        except (ZKOpError, ValueError, IndexError):
            out.append('acked set v%d on %s lost: node unreadable'
                       % (idx, path))
            continue
        if have < idx:
            out.append('acked set v%d on %s lost: final value %r'
                       % (idx, path, bytes(got)))
    return out


def check_durable_recovery(history: History, db,
                           floor_zxid: int | None = None,
                           quorum_zxid: int | None = None) -> list[str]:
    """Invariant 6 (the durability plane, server/persist.py): after a
    full-ensemble SIGKILL, a database recovered from the newest valid
    snapshot plus the replayed WAL tail still holds every
    unambiguously-acked write.  ``db`` is the *recovered* tree (not
    the live leader's); the ambiguity rules are exactly invariant 1's
    — an outcome-unknown write may or may not have reached the log —
    plus the ``floor_zxid`` demotion for acks an fsync error left
    non-durable (``None`` = every ack was fsynced before it left,
    the sync='always'/'tick' barrier contract) and the
    ``quorum_zxid`` strengthening (acks at or under the quorum floor
    are never demoted — invariant 1's docstring says when that is
    sound).  Ephemeral absence is excused as in invariant 1 when the
    owning session died with the crash; a session recovered live
    keeps its ephemerals (:func:`check_session_continuity` asserts
    that side)."""
    out = ['durability: %s' % v
           for v in check_acked_durability(history, db,
                                           floor_zxid=floor_zxid,
                                           quorum_zxid=quorum_zxid)]
    top = 0
    for r in history.of_kind('ack'):
        z = r.get('zxid')
        if z and (floor_zxid is None or z <= floor_zxid
                  or (quorum_zxid is not None and z <= quorum_zxid)):
            top = max(top, z)
    if db.zxid < top:
        out.append('durability: recovered zxid %d is behind the '
                   'newest durable acked zxid %d (log tail lost)'
                   % (db.zxid, top))
    # a multi past the durable floor may legitimately be absent whole
    # — but never partial: the one-CRC-frame record guarantees torn
    # replay is all-or-nothing, and this asserts it
    out.extend('durability: %s' % v
               for v in check_multi_atomic(history, db))
    return out


def check_zxid_monotonic(history: History) -> list[str]:
    """Invariant 2: write-reply zxids never decrease per session."""
    out: list[str] = []
    last: dict[int, tuple[int, str]] = {}
    for r in history.of_kind('op'):
        if r['status'] != 'ok' or r['op'] not in WRITE_OPS:
            continue
        zxid = r.get('zxid')
        sid = r.get('session_id') or 0
        if zxid is None or not sid:
            continue
        prev = last.get(sid)
        if prev is not None and zxid < prev[0]:
            out.append(
                'zxid regression on session %016x: %s %s replied '
                'zxid %d after %s had replied %d'
                % (sid, r['op'], r.get('path'), zxid, prev[1],
                   prev[0]))
        if prev is None or zxid >= prev[0]:
            last[sid] = (zxid, '%s %s' % (r['op'], r.get('path')))
    return out


def check_ephemerals(history: History, db) -> list[str]:
    """Invariant 3: ephemerals live exactly as long as their owning
    session."""
    out: list[str] = []
    acked_del: set[str] = set()
    ambig_del: set[str] = set()
    ephemerals: list[dict] = []
    for r in history.records:
        if r['kind'] == 'ack' and r['op'] == 'create' \
                and r.get('ephemeral'):
            ephemerals.append(r)
        elif r['kind'] == 'ack' and r['op'] == 'delete':
            acked_del.add(r['path'])
        elif r['kind'] == 'ambig' and r['op'] == 'delete':
            ambig_del.add(r['path'])
    for rec in ephemerals:
        path, sid = rec['path'], rec['session_id']
        sess = db.sessions.get(sid)
        alive = (sess is not None and not sess.expired
                 and not sess.closed)
        exists = path in db.nodes
        if not alive and exists:
            out.append(
                'ephemeral %s outlived its session %016x (confirmed '
                '%s)' % (path, sid,
                         'expired' if sess is None or sess.expired
                         else 'closed'))
        elif alive and not exists and path not in acked_del \
                and path not in ambig_del:
            out.append(
                'ephemeral %s vanished while its session %016x is '
                'still live' % (path, sid))
        elif exists and db.nodes[path].ephemeral_owner != sid:
            out.append(
                'ephemeral %s owned by %016x, expected %016x'
                % (path, db.nodes[path].ephemeral_owner, sid))
    return out


def _seq_number(path: str) -> int:
    return int(path[-10:])


def check_sequential(history: History) -> list[str]:
    """Invariant 4: per parent, acked sequential numbers strictly
    increase, and every gap is covered by an ambiguous create
    *recorded before the ack that reveals the gap* — ops complete in
    issue order, so an ambiguous create recorded later could only
    have consumed a higher number and must not excuse an earlier
    loss."""
    out: list[str] = []
    prev: dict[str, int] = {}        # parent -> last acked number
    avail: dict[str, int] = {}       # parent -> unspent ambig creates
    for r in history.records:
        parent = r.get('seq_parent')
        if parent is None:
            continue
        if r['kind'] == 'ambig' and r['op'] == 'create':
            avail[parent] = avail.get(parent, 0) + 1
        elif r['kind'] == 'ack' and r['op'] == 'create':
            num = _seq_number(r['path'])
            last = prev.get(parent)
            if last is not None and num <= last:
                out.append(
                    'sequential numbering under %s not increasing: '
                    '%d acked after %d' % (parent, num, last))
                continue
            gap = num - (last + 1 if last is not None else 0)
            have = avail.get(parent, 0)
            if gap > have:
                out.append(
                    'sequential gap under %s: number(s) %s missing '
                    'before acked %d with only %d prior ambiguous '
                    'create(s) to have consumed them'
                    % (parent,
                       list(range((last + 1 if last is not None
                                   else 0), num)), num, have))
            else:
                avail[parent] = have - gap
            prev[parent] = num
    return out


def check_watch_once(history: History) -> list[str]:
    """Invariant 5: each watch delivers a given change at most once."""
    out: list[str] = []
    seen: dict[tuple[str, str], set[int]] = {}
    deleted_fires: dict[str, int] = {}
    for r in history.of_kind('watch'):
        path, event, zxid = r['path'], r['event'], r.get('zxid')
        if zxid is None:
            if event == 'deleted':
                deleted_fires[path] = deleted_fires.get(path, 0) + 1
            continue
        zset = seen.setdefault((path, event), set())
        if zxid in zset:
            out.append('duplicated %s watch fire for %s at zxid %d'
                       % (event, path, zxid))
        zset.add(zxid)
    for path, n in deleted_fires.items():
        if n > 1:
            out.append('%d deleted fires for %s (deleted at most '
                       'once)' % (n, path))
    return out


def check_session_continuity(live_sessions: dict, db) -> list[str]:
    """Invariant 8a (durable sessions, server/persist.py): a session
    that stayed inside its timeout across a full restart keeps its
    identity AND its ephemerals.  ``live_sessions`` is the
    pre-restart truth, ``{sid: set(ephemeral paths)}`` captured while
    the sessions were live; ``db`` the recovered database."""
    out: list[str] = []
    for sid, paths in live_sessions.items():
        sess = db.sessions.get(sid)
        if sess is None or sess.expired or sess.closed:
            out.append(
                'session %016x did not survive restart inside its '
                'timeout (%s)' % (sid,
                                  'missing' if sess is None else
                                  'expired' if sess.expired
                                  else 'closed'))
            continue
        for path in sorted(paths):
            node = db.nodes.get(path)
            if node is None:
                out.append(
                    'ephemeral %s of surviving session %016x lost '
                    'across restart' % (path, sid))
            elif node.ephemeral_owner != sid:
                out.append(
                    'ephemeral %s re-owned across restart: %016x, '
                    'expected %016x' % (path, node.ephemeral_owner,
                                        sid))
            elif path not in sess.ephemerals:
                out.append(
                    'ephemeral %s missing from recovered session '
                    '%016x ephemeral set' % (path, sid))
    return out


def check_multi_atomic(history: History, db) -> list[str]:
    """Invariant 8b (MULTI, server/store.py ``ZKDatabase.multi``): no
    partial batch is ever visible — for each acked multi, either every
    sub-effect is present in the final tree or none is (a torn multi
    record replays atomically or not at all).  Sub-effects are judged
    by (op, path, data); the caller keeps batch paths unmutated
    outside their batch, as the seeded scenarios do."""
    out: list[str] = []
    for r in history.of_kind('multi'):
        vis: list[bool] = []
        for op, path, data in r['subs']:
            node = db.nodes.get(path)
            if op == 'create':
                vis.append(node is not None and (
                    data is None or bytes(node.data) == data))
            elif op == 'delete':
                vis.append(node is None)
            elif op == 'set_data':
                vis.append(node is not None
                           and bytes(node.data) == data)
        if any(vis) and not all(vis):
            missing = [r['subs'][i][1] for i, v in enumerate(vis)
                       if not v]
            out.append(
                'multi batch (t=%d, %d ops) partially visible: '
                'effect(s) missing at %s — a multi must apply whole '
                'or not at all' % (r['t'], len(vis), missing))
    return out


def check_election(history: History) -> list[str]:
    """Invariant 7: at most one elected leader per epoch, and elected
    epochs strictly increase in history order."""
    out: list[str] = []
    winners: dict[int, object] = {}
    prev: int | None = None
    for r in history.of_kind('election'):
        epoch, member = r['epoch'], r['member']
        if epoch in winners:
            # re-observing a standing leader (a scrape after a
            # restart) is fine; a DIFFERENT winner at the same epoch
            # means the fencing token was reused
            if winners[epoch] != member:
                out.append(
                    'two leaders elected at epoch %d: member %s and '
                    'member %s' % (epoch, winners[epoch], member))
        else:
            winners[epoch] = member
            if prev is not None and epoch <= prev:
                out.append(
                    'elected epoch not increasing: %d won after %d '
                    '(a deposed era could be mistaken for current)'
                    % (epoch, prev))
        prev = epoch if prev is None else max(prev, epoch)
    return out


def check_reconfig(history: History) -> list[str]:
    """Invariant 7 extension (README "Dynamic membership"): config
    versions strictly increase in history order, at most ONE
    voter-set change (joint record) lands per leadership epoch, and
    no joint window opens while another still stands.  The per-epoch
    fence is what makes a reconfig record safe to recover mid-joint:
    a deposed leader's half-finished change can never interleave
    with its successor's in the same epoch."""
    out: list[str] = []
    prev_version: int | None = None
    joint_by_epoch: dict[int, int] = {}
    open_joint: int | None = None
    for r in history.of_kind('reconfig'):
        v = r['version']
        if prev_version is not None and v <= prev_version:
            out.append(
                'config version not increasing: v%d recorded after '
                'v%d' % (v, prev_version))
        prev_version = v
        if r['phase'] == 'joint':
            if open_joint is not None:
                out.append(
                    'joint config v%d proposed while v%d still open '
                    '(two overlapping membership changes)'
                    % (v, open_joint))
            open_joint = v
            e = r['epoch']
            if e in joint_by_epoch:
                out.append(
                    'two voter-set changes in epoch %d: v%d and v%d '
                    '(at-most-one-change-per-epoch fence breached)'
                    % (e, joint_by_epoch[e], v))
            else:
                joint_by_epoch[e] = v
        else:
            open_joint = None
    return out


def check_history(history: History, db) -> list[str]:
    """Run every invariant against the history and the leader's
    final database; returns the combined violation list."""
    from ..analysis.linearize import (
        check_linearizable,
        check_session_reads,
    )

    out: list[str] = []
    out.extend(check_acked_durability(history, db))
    out.extend(check_zxid_monotonic(history))
    out.extend(check_ephemerals(history, db))
    out.extend(check_sequential(history))
    out.extend(check_watch_once(history))
    out.extend(check_election(history))
    out.extend(check_reconfig(history))
    out.extend(check_multi_atomic(history, db))
    # invariant 9: per-key WGL linearizability over the interval
    # records (vacuous on histories that carry none)
    out.extend(check_linearizable(history, db))
    # the session-monotone read rung (the read plane's acceptance,
    # PR 15): a session never observes state older than it has
    # already seen — held by the zxid read gate (server/server.py
    # ReadGate + the client plane's header-zxid validation); the
    # env-gated ungated validator (ZKSTREAM_NO_READ_GATE=1) is what
    # this checker exists to catch
    out.extend(check_session_reads(history))
    return out


def format_history(history: 'History | list[dict]',
                   kinds=('member', 'session', 'election',
                          'reconfig'),
                   limit: int | None = None,
                   columns: bool = False) -> str:
    """Render the member-event (and session-edge) timeline for a
    failure report, oldest first.  Accepts a :class:`History` or a
    plain record list (``ScheduleResult.history``).

    ``columns=True`` renders the per-client interleaving instead:
    one column per client id, invoke (``op>``) and settle (``<st``)
    rows of the interval records in history order, member events in
    a trailing column — the view a linearizability counterexample is
    read against."""
    records = history.records if isinstance(history, History) \
        else history
    if columns:
        return _format_columns(records, limit=limit)
    rows = [r for r in records if r['kind'] in kinds]
    if limit is not None and len(rows) > limit:
        rows = rows[-limit:]
    lines = []
    for r in rows:
        if r['kind'] == 'member':
            lines.append('  t=%-4d member %-8s %s'
                         % (r['t'], r['member'], r['event']))
        elif r['kind'] == 'election':
            lines.append('  t=%-4d member %-8s ELECTED leader '
                         '(epoch %d)'
                         % (r['t'], r['member'], r['epoch']))
        elif r['kind'] == 'reconfig':
            old = (' old=%s' % (','.join(map(str, r['old_voters'])),)
                   if r['old_voters'] else '')
            lines.append('  t=%-4d config v%-7d RECONFIG %s '
                         'voters=%s%s (epoch %d)'
                         % (r['t'], r['version'], r['phase'],
                            ','.join(map(str, r['voters'])), old,
                            r['epoch']))
        else:
            lines.append('  t=%-4d session %016x %s'
                         % (r['t'], r['session_id'], r['event']))
    return '\n'.join(lines)


#: Column width of the per-client interleaving view.
_COL_W = 22


def _format_columns(records: list[dict],
                    limit: int | None = None) -> str:
    """The per-client column view behind ``format_history(...,
    columns=True)``: each interval record renders in its client's
    column (``set /k0 v=-1 >`` opening, ``< ok z=14`` closing,
    correlated by the ``#call`` prefix), member events in a trailing
    column, so concurrent overlap — the thing a linearizability
    counterexample hinges on — is visible by eye."""
    invokes = {r['call']: r for r in records
               if r['kind'] == 'invoke'}
    clients = sorted({r['client'] for r in invokes.values()})
    col = {c: i for i, c in enumerate(clients)}
    rows = [r for r in records
            if r['kind'] in ('invoke', 'settle', 'member')]
    if limit is not None and len(rows) > limit:
        rows = rows[-limit:]
    head = '  %-7s %s| member' \
        % ('t', ''.join(('client %-2s' % (c,)).ljust(_COL_W)
                        for c in clients))
    lines = [head]
    for r in rows:
        cells = [' ' * _COL_W] * len(clients)
        tail = ''
        if r['kind'] == 'member':
            tail = '%s %s' % (r['event'], r['member'])
        else:
            inv = invokes.get(r.get('call'))
            if r['kind'] == 'invoke':
                text = '#%d %s %s >' % (r['call'], r['op'],
                                        r.get('path') or '*')
                c = r['client']
            else:
                c = inv['client'] if inv is not None else None
                text = '< #%d %s' % (r['call'], r['status'])
                if r.get('zxid') is not None:
                    text += ' z=%d' % (r['zxid'],)
                if r.get('error'):
                    text += ' %s' % (r['error'],)
            if c in col:
                cells[col[c]] = text[:_COL_W - 1].ljust(_COL_W)
            else:
                # a settle whose invoke record is missing (e.g.
                # trimmed by ``limit``): never guess a column
                tail = text
        lines.append('  t=%-5d %s| %s'
                     % (r['t'], ''.join(cells), tail))
    return '\n'.join(lines)
