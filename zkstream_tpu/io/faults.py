"""Deterministic, seedable fault injection for the transport stack.

The whole value of this client is surviving failure — session
resumption, watcher re-arm, retry policies — yet hand-rolled failure
tests only ever exercise the failure modes someone thought of.  This
module injects faults *at the byte/socket boundary* on a seeded
schedule, so randomized-but-reproducible campaigns (tests/test_chaos.py,
``python -m zkstream_tpu chaos``) can drive the stack through fault
interleavings nobody hand-wrote:

- **connection refusal** and **added connect latency** (client dial);
- **mid-frame TCP resets** in either direction (a frame's prefix is
  delivered, then the connection dies);
- **partial/slow frame delivery** (byte-level splits with delays);
- **delayed and duplicated segments** (a duplicated stream segment is
  a framing-corruption-class fault: it must surface as a typed
  protocol error and a reconnect, never a hang or a wrong reply);
- **accept-loop refusal** on the server;
- **asymmetric partition** between replication peers (the leader's
  push channel to one follower drops while the follower's control
  channel still flows — server/replication.py);
- member **crash scheduling** helpers (the campaign SIGKILLs / stops
  ensemble members at injector-chosen points).

Determinism: every decision is drawn from a per-category
``random.Random`` seeded from ``(seed, category)`` (string seeding
hashes via SHA-512, stable across processes).  The *schedule* — the
sequence of decisions at each injection point — is therefore a pure
function of the seed and config: the interleaving of categories may
vary with event-loop timing, but each category's Nth decision never
does, and ``schedule_digest()`` captures the whole plan for equality
checks.  Faults stop after ``max_faults`` fires so every campaign
converges to a verifiable steady state.

The hooks are duck-typed: ``ZKConnection`` reads ``client.faults``,
``ZKServer``/``ReplicationService`` carry a ``faults`` slot.  With no
injector installed every hook site is a single ``is None`` check.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import random
import struct

from ..protocol.errors import (
    ZKError,
    ZKNotConnectedError,
    ZKProtocolError,
)
from ..utils.aio import ambient_loop
from .invariants import AMBIGUOUS_CODES

#: Decision streams, one seeded RNG each.  'plan' is reserved for the
#: campaign driver's op/crash scheduling so workload choices never
#: perturb transport-fault draws; 'ingest' drives the FleetIngest
#: batched drain's tick-time faults; 'disk' drives the durability
#: plane (fsync latency/errors, crash-before-fsync vs crash-after-
#: fsync windows — server/persist.py).
#: 'overload' drives the overload plane's pressure bursts (raw
#: connection floods, stalled readers, oversized declared frames —
#: io/overload.py).
CATEGORIES = ('connect', 'rx', 'tx', 'accept', 'server_tx',
              'partition', 'plan', 'ingest', 'disk', 'server_rx',
              'overload')


class InjectedRefusal(ConnectionRefusedError):
    """A dial refused by the fault schedule (client side)."""


@dataclasses.dataclass
class FaultConfig:
    """Probabilities and bounds for one campaign's fault mix.  All
    probabilities are per-decision-point; delays are ms ranges."""

    # client dial
    p_connect_refuse: float = 0.0
    connect_latency_ms: float = 0.0
    # server -> client byte stream (client rx)
    p_rx_reset: float = 0.0
    p_rx_split: float = 0.0
    p_rx_delay: float = 0.0
    p_rx_dup: float = 0.0
    rx_delay_ms: tuple[float, float] = (1.0, 25.0)
    # client -> server byte stream (client tx)
    p_tx_reset: float = 0.0
    # server accept loop
    p_accept_refuse: float = 0.0
    # server reply/notification writes
    p_server_tx_reset: float = 0.0
    p_server_tx_split: float = 0.0
    server_tx_delay_ms: tuple[float, float] = (0.0, 10.0)
    # server receive path (client -> server bytes AT the server):
    # injected at the per-frame boundary BEFORE the ingress drain's
    # decode (io/ingress.py / ServerConnection.feed) — the send
    # plane's before-the-cork rule mirrored on the rx side
    p_server_rx_reset: float = 0.0
    p_server_rx_split: float = 0.0
    server_rx_delay_ms: tuple[float, float] = (0.0, 8.0)
    # replication: leader -> follower push drop (asymmetric partition)
    p_push_drop: float = 0.0
    # FleetIngest batched drain: tick-time faults (io/ingest.py) — a
    # slot suffix withheld across a tick boundary (partial frame into
    # the device scan) or a connection reset at tick time
    p_ingest_hold: float = 0.0
    p_ingest_reset: float = 0.0
    # durability plane (server/persist.py): injected fsync latency
    # (fsync is a blocking syscall; so is its injected delay) and
    # fsync *errors* — a failed fsync leaves acked writes non-durable
    # until the next barrier succeeds, which the recovery invariant's
    # floor demotion accounts for
    p_fsync_delay: float = 0.0
    fsync_delay_ms: tuple[float, float] = (0.2, 5.0)
    p_fsync_error: float = 0.0
    # overload plane (io/overload.py): plan-level pressure bursts —
    # raw connection floods against the admission path, stalled
    # client readers (slow consumers growing the member's tx
    # backlog), and oversized declared frame lengths (the frame cap
    # must refuse BEFORE buffering)
    p_conn_flood: float = 0.0
    flood_conns: int = 12
    p_stall_reader: float = 0.0
    stall_window_ms: tuple[float, float] = (20.0, 120.0)
    p_oversize_frame: float = 0.0
    #: stop firing after this many injected faults (None = unbounded);
    #: the budget is what makes randomized campaigns converge
    max_faults: int | None = 8

    @classmethod
    def randomized(cls, seed: int) -> 'FaultConfig':
        """A randomized-but-reproducible fault mix: which fault classes
        are active, and how hard, is itself drawn from the seed."""
        rng = random.Random('cfg/%d' % (seed,))
        cfg = cls()
        picks = rng.sample([
            ('p_connect_refuse', 0.3), ('p_rx_reset', 0.08),
            ('p_rx_split', 0.5), ('p_rx_delay', 0.4),
            ('p_rx_dup', 0.06), ('p_tx_reset', 0.08),
            ('p_accept_refuse', 0.3), ('p_server_tx_reset', 0.08),
            ('p_server_tx_split', 0.5), ('p_push_drop', 0.3),
        ], k=rng.randint(1, 4))
        for name, ceil in picks:
            setattr(cfg, name, rng.uniform(0.01, ceil))
        cfg.connect_latency_ms = rng.choice([0.0, 0.0, 10.0, 50.0])
        cfg.rx_delay_ms = (0.5, rng.uniform(2.0, 20.0))
        cfg.server_tx_delay_ms = (0.0, rng.uniform(1.0, 8.0))
        cfg.max_faults = rng.randint(1, 5)
        # disk faults ride their own config stream so adding the
        # durability plane never perturbed the transport mixes the
        # existing seeds were tuned on
        drng = random.Random('cfg-disk/%d' % (seed,))
        if drng.random() < 0.4:
            cfg.p_fsync_delay = drng.uniform(0.02, 0.3)
            cfg.fsync_delay_ms = (0.1, drng.uniform(0.5, 4.0))
        if drng.random() < 0.15:
            cfg.p_fsync_error = drng.uniform(0.02, 0.15)
        # server-rx faults likewise ride their own stream (added with
        # the ingress plane, PR 13): existing streams' draws are
        # untouched, the new fault class just joins the mix
        rrng = random.Random('cfg-srx/%d' % (seed,))
        if rrng.random() < 0.35:
            cfg.p_server_rx_split = rrng.uniform(0.02, 0.4)
            cfg.server_rx_delay_ms = (0.1, rrng.uniform(0.5, 6.0))
        if rrng.random() < 0.1:
            cfg.p_server_rx_reset = rrng.uniform(0.01, 0.08)
        # overload faults likewise ride their own stream (PR 18):
        # the transport mixes existing seeds pin stay untouched
        ovrng = random.Random('cfg-overload/%d' % (seed,))
        if ovrng.random() < 0.3:
            cfg.p_conn_flood = ovrng.uniform(0.1, 0.5)
            cfg.flood_conns = ovrng.randint(6, 24)
        if ovrng.random() < 0.3:
            cfg.p_stall_reader = ovrng.uniform(0.1, 0.5)
            cfg.stall_window_ms = (10.0, ovrng.uniform(40.0, 150.0))
        if ovrng.random() < 0.2:
            cfg.p_oversize_frame = ovrng.uniform(0.1, 0.4)
        return cfg

    @classmethod
    def randomized_ensemble(cls, seed: int) -> 'FaultConfig':
        """The ensemble campaign's fault mix: the transport mix of
        :meth:`randomized` (drawn from the same stream, so the two
        tiers' transport schedules stay comparable per seed) plus
        ingest tick faults, drawn from a separate stream so adding
        them never perturbed the transport tier's existing
        schedules."""
        cfg = cls.randomized(seed)
        rng = random.Random('cfg-ens/%d' % (seed,))
        if rng.random() < 0.5:
            cfg.p_ingest_hold = rng.uniform(0.05, 0.6)
        if rng.random() < 0.25:
            cfg.p_ingest_reset = rng.uniform(0.02, 0.10)
        # member kills dominate the ensemble tier; give the byte-level
        # faults a slightly larger budget so both layers keep firing
        cfg.max_faults = rng.randint(2, 8)
        return cfg


class _Gate:
    """Strictly-FIFO delayed delivery of byte segments to a sink.

    TCP never reorders within a stream, so a delayed segment holds
    everything behind it (slow delivery), it does not overtake.  A
    ``reset`` sentinel queued behind segments delivers the prefix
    first, then fires the reset callback — that is what makes injected
    resets genuinely *mid-frame*."""

    _RESET = object()

    def __init__(self, sink, on_reset):
        self._sink = sink
        self._on_reset = on_reset
        self._q: list = []       # (delay_ms, payload) pending delivery
        self._timer = None
        self.dead = False

    @property
    def pending(self) -> bool:
        """True while segments are still queued or a delayed head is
        waiting on its timer — later writes must queue behind them to
        keep the stream FIFO."""
        return bool(self._q) or self._timer is not None

    def push(self, data: bytes, delay_ms: float = 0.0) -> None:
        if self.dead:
            return
        self._q.append((delay_ms, data))
        self._drain()

    def push_reset(self) -> None:
        if self.dead:
            return
        self._q.append((0.0, _Gate._RESET))
        self._drain()

    def _drain(self) -> None:
        if self._timer is not None:
            return                        # a delayed head is pending
        while self._q and not self.dead:
            delay_ms, payload = self._q[0]
            if delay_ms > 0:
                self._q[0] = (0.0, payload)

                def fire():
                    self._timer = None
                    self._drain()
                self._timer = ambient_loop().call_later(
                    delay_ms / 1000.0, fire)
                return
            self._q.pop(0)
            if payload is _Gate._RESET:
                self.dead = True
                self._q.clear()
                self._on_reset()
                return
            self._sink(payload)

    def close(self) -> None:
        self.dead = True
        self._q.clear()
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None


class FaultInjector:
    def __init__(self, seed: int = 0,
                 config: FaultConfig | None = None):
        self.seed = seed
        self.config = config if config is not None else FaultConfig()
        self._streams = {cat: random.Random('%d/%s' % (seed, cat))
                         for cat in CATEGORIES}
        self.active = True
        #: (category, description) of every fault actually fired —
        #: printed on campaign failure next to the seed
        self.fired: list[tuple[str, str]] = []
        self._gates: list[_Gate] = []

    # -- bookkeeping --

    def _take(self, cat: str, p: float, desc: str) -> bool:
        """One decision point: ALWAYS draws from the category stream
        (so the schedule is a pure function of the seed regardless of
        which faults are enabled), fires only while active and under
        the fault budget."""
        r = self._streams[cat].random()
        if not self.active or p <= 0.0 or r >= p:
            return False
        if self.config.max_faults is not None and \
                len(self.fired) >= self.config.max_faults:
            return False
        self.fired.append((cat, desc))
        return True

    def rand(self, cat: str) -> float:
        return self._streams[cat].random()

    def randint(self, cat: str, a: int, b: int) -> int:
        return self._streams[cat].randint(a, b)

    def choice(self, cat: str, seq):
        return self._streams[cat].choice(seq)

    def uniform(self, cat: str, a: float, b: float) -> float:
        return self._streams[cat].uniform(a, b)

    def stop(self) -> None:
        """Stop injecting (verification phase).  Segments already in
        flight through gates still deliver — they are real bytes."""
        self.active = False

    def close(self) -> None:
        self.active = False
        for g in self._gates:
            g.close()
        self._gates.clear()

    def schedule_digest(self, per_category: int = 64) -> str:
        """A digest of the fault plan: config + the first N draws of
        every category stream.  Same seed + config => same digest,
        independent of anything that happened at runtime."""
        h = hashlib.sha256()
        h.update(repr(dataclasses.astuple(self.config)).encode())
        for cat in CATEGORIES:
            rng = random.Random('%d/%s' % (self.seed, cat))
            for _ in range(per_category):
                h.update(struct.pack('<d', rng.random()))
        return h.hexdigest()

    @classmethod
    def randomized(cls, seed: int) -> 'FaultInjector':
        return cls(seed, FaultConfig.randomized(seed))

    # -- client dial --

    async def before_connect(self, backend_key: str) -> None:
        """Called by the connection's dial task before the TCP connect:
        sleeps the injected reconnect latency, then may refuse."""
        refuse = self._take('connect', self.config.p_connect_refuse,
                            'refuse dial to %s' % (backend_key,))
        if self.config.connect_latency_ms > 0:
            await asyncio.sleep(self.config.connect_latency_ms / 1000.0)
        if refuse:
            raise InjectedRefusal(
                'injected connection refusal (%s)' % (backend_key,))

    # -- client rx (server -> client bytes) --

    def rx(self, conn, data: bytes) -> None:
        """Route received bytes through the fault schedule, then on to
        the connection's normal ``sockData`` path, in order."""
        gate = getattr(conn, '_fault_rx_gate', None)
        if gate is None or gate.dead:
            def on_reset(c=conn):
                c.emit('sockError', ConnectionResetError(
                    'injected connection reset (rx)'))
            gate = _Gate(lambda d, c=conn: c.emit('sockData', d),
                         on_reset)
            conn._fault_rx_gate = gate
            self._gates.append(gate)
        cfg = self.config
        if self._take('rx', cfg.p_rx_reset, 'rx mid-frame reset'):
            # deliver a strict prefix, then die: the codec is left
            # holding a half frame when the teardown path runs
            cut = self._streams['rx'].randrange(len(data)) \
                if len(data) > 1 else 0
            if cut:
                gate.push(data[:cut])
            gate.push_reset()
            return
        segments = [data]
        if len(data) > 1 and self._take('rx', cfg.p_rx_split,
                                        'rx split'):
            cut = self._streams['rx'].randrange(1, len(data))
            segments = [data[:cut], data[cut:]]
        if self._take('rx', cfg.p_rx_dup, 'rx duplicate segment'):
            segments.append(segments[self._streams['rx']
                            .randrange(len(segments))])
        lo, hi = cfg.rx_delay_ms
        for seg in segments:
            delay = 0.0
            if self._take('rx', cfg.p_rx_delay, 'rx delay'):
                delay = self._streams['rx'].uniform(lo, hi)
            gate.push(seg, delay)

    # -- client tx (client -> server bytes) --

    def tx(self, conn, data: bytes) -> bytes | None:
        """May truncate an outbound frame and schedule a reset; returns
        the bytes to actually write (None = write nothing)."""
        if self._take('tx', self.config.p_tx_reset,
                      'tx mid-frame reset'):
            cut = self._streams['tx'].randrange(len(data)) \
                if len(data) > 1 else 0

            def die(c=conn):
                c.emit('sockError', ConnectionResetError(
                    'injected connection reset (tx)'))
            ambient_loop().call_soon(die)
            return data[:cut] if cut else None
        return data

    # -- server side --

    def accept_refuse(self) -> bool:
        return self._take('accept', self.config.p_accept_refuse,
                          'refuse accepted client')

    def server_tx(self, server_conn, data: bytes, pre=None) -> bool:
        """Server-side write hook.  Returns True when the injector took
        over delivery (split/delay/reset), False for pass-through.

        ``pre`` (the connection's send-plane ``flush_hard``, or — on
        the watch-table fan-out path — its ``_preflush_fanout``, which
        drains the buffered notifications first) runs before the
        injector's first delivery whenever it takes over: frames
        corked in earlier (un-faulted) writes must hit the wire first
        or the stream would reorder in a way TCP never does.  The hook
        itself stays a per-frame boundary — injection happens before
        the cork (send plane AND shard cork alike), and a faulted
        frame bypasses both.  This holds on every transport backend
        (io/transport.py): ``flush_hard`` drains the batched tier's
        pending submission for the connection synchronously, so the
        gate's direct ``writer.write`` deliveries can never overtake
        bytes the tier still held."""
        cfg = self.config
        wants_reset = self._take('server_tx', cfg.p_server_tx_reset,
                                 'server tx mid-frame reset')
        wants_split = self._take('server_tx', cfg.p_server_tx_split,
                                 'server tx split/delay')
        gate = getattr(server_conn, '_fault_tx_gate', None)
        if not (wants_reset or wants_split):
            if gate is None or gate.dead or not gate.pending:
                return False
            # A delayed segment from an earlier write is still in the
            # gate: this (un-faulted) write must queue behind it, or
            # the stream would reorder in a way TCP never does.
            if pre is not None:
                pre()
            gate.push(data)
            return True
        if pre is not None:
            pre()
        if gate is None or gate.dead:
            def sink(d, c=server_conn):
                if not c.closed:
                    try:
                        c.writer.write(d)
                    except (ConnectionError, RuntimeError):
                        pass

            def on_reset(c=server_conn):
                try:
                    t = c.writer.transport
                    if t is not None:
                        t.abort()
                except (ConnectionError, RuntimeError):
                    pass
                c.close()
            gate = _Gate(sink, on_reset)
            server_conn._fault_tx_gate = gate
            self._gates.append(gate)
        if wants_reset:
            cut = self._streams['server_tx'].randrange(len(data)) \
                if len(data) > 1 else 0
            if cut:
                gate.push(data[:cut])
            gate.push_reset()
            return True
        cut = self._streams['server_tx'].randrange(1, len(data)) \
            if len(data) > 1 else 0
        lo, hi = cfg.server_tx_delay_ms
        delay = self._streams['server_tx'].uniform(lo, hi)
        if cut:
            gate.push(data[:cut])
            gate.push(data[cut:], delay)
        else:
            gate.push(data, delay)
        return True

    def server_rx(self, server_conn, data: bytes) -> bool:
        """Server-side receive hook.  Returns True when the injector
        took over delivery (split/delay/reset), False for
        pass-through.

        Called per connection-chunk BEFORE any decode — by
        ``ServerConnection.feed`` on BOTH receive paths (the
        single-loop validator's read loop and the ingress plane's
        batched drain, io/ingress.py), so injection stays a per-frame
        boundary ahead of the batch: a faulted chunk perturbs one
        connection's stream without reordering it, whichever backend
        drained the bytes.  Delayed segments re-enter through
        ``_feed`` (the injector-free half), never through ``feed`` —
        a faulted chunk is screened exactly once."""
        cfg = self.config
        wants_reset = self._take('server_rx', cfg.p_server_rx_reset,
                                 'server rx mid-frame reset')
        wants_split = self._take('server_rx', cfg.p_server_rx_split,
                                 'server rx split/delay')
        gate = getattr(server_conn, '_fault_srx_gate', None)
        if not (wants_reset or wants_split):
            if gate is None or gate.dead or not gate.pending:
                return False
            # a delayed segment from an earlier chunk is still in the
            # gate: this (un-faulted) chunk must queue behind it, or
            # the server would decode a reordering TCP never delivers
            gate.push(data)
            return True
        if gate is None or gate.dead:
            def sink(d, c=server_conn):
                if not c.closed and not c._feed(d):
                    c.close()

            def on_reset(c=server_conn):
                try:
                    t = c.writer.transport
                    if t is not None:
                        t.abort()
                except (ConnectionError, RuntimeError):
                    pass
                c.close()
            gate = _Gate(sink, on_reset)
            server_conn._fault_srx_gate = gate
            self._gates.append(gate)
        if wants_reset:
            # deliver a strict prefix, then die: the server codec is
            # left holding a half frame when teardown runs
            cut = self._streams['server_rx'].randrange(len(data)) \
                if len(data) > 1 else 0
            if cut:
                gate.push(data[:cut])
            gate.push_reset()
            return True
        cut = self._streams['server_rx'].randrange(1, len(data)) \
            if len(data) > 1 else 0
        lo, hi = cfg.server_rx_delay_ms
        delay = self._streams['server_rx'].uniform(lo, hi)
        if cut:
            gate.push(data[:cut])
            gate.push(data[cut:], delay)
        else:
            gate.push(data, delay)
        return True

    # -- replication partition --

    def drop_push(self, follower_token: str) -> bool:
        """Leader->follower push drop: the asymmetric half-partition
        (the follower's control channel keeps working)."""
        return self._take('partition', self.config.p_push_drop,
                          'drop push to follower %s' % (follower_token,))

    # -- FleetIngest batched drain (tick-time faults) --

    def ingest_reset(self, conn) -> bool:
        """Kill this connection at the tick boundary (teardown while
        other streams of the same batch still route)."""
        return self._take('ingest', self.config.p_ingest_reset,
                          'ingest tick reset')

    def ingest_cut(self, conn, nbytes: int) -> int:
        """How many trailing bytes of a slot to withhold from this
        tick (0 = none): the device scan sees a partial frame at an
        arbitrary cut and must finish it on the follow-up tick."""
        if nbytes < 2:
            return 0
        if not self._take('ingest', self.config.p_ingest_hold,
                          'ingest tick hold'):
            return 0
        return self._streams['ingest'].randrange(1, nbytes)

    # -- durability plane (server/persist.py) --

    def fsync_fault(self) -> tuple[float, bool]:
        """One WAL fsync decision point: returns ``(delay_ms, error)``.
        A delay models a congested device (fsync blocks the loop; so,
        deliberately, does the injected delay); an error models the
        fsync failing outright — the WAL counts it and the acked
        writes under it stay non-durable until the next barrier."""
        delay = 0.0
        if self._take('disk', self.config.p_fsync_delay,
                      'fsync delay'):
            delay = self._streams['disk'].uniform(
                *self.config.fsync_delay_ms)
        err = self._take('disk', self.config.p_fsync_error,
                         'fsync error')
        return delay, err

    def overload_action(self) -> str | None:
        """One per-step overload decision ('overload' stream,
        fault-budget accounted): 'stall' (park a client reader —
        the slow-consumer shape), 'flood' (raw connection burst
        against the admission path), 'oversize' (an absurd declared
        frame length), or None.  The campaign drivers map each to
        the matching pressure action (io/faults.py force_overload)."""
        cfg = self.config
        if self._take('overload', cfg.p_stall_reader,
                      'stalled client reader'):
            return 'stall'
        if self._take('overload', cfg.p_conn_flood,
                      'raw connection flood'):
            return 'flood'
        if self._take('overload', cfg.p_oversize_frame,
                      'oversized declared frame'):
            return 'oversize'
        return None

    def crash_window_before_fsync(self) -> bool:
        """The campaign's SIGKILL placement relative to the pending
        fsync: True = die before it completes (the open segment's
        un-fsynced tail is lost), False = die just after.  A plan
        decision, not a fault — it draws from the 'disk' stream but
        never spends the fault budget."""
        return self._streams['disk'].random() < 0.5


# ---------------------------------------------------------------------
# Campaign driver: one seeded schedule end to end.  Shared by
# tests/test_chaos.py and the ``chaos`` CLI subcommand so the invariant
# checks cannot diverge between them.
# ---------------------------------------------------------------------

#: Per-op deadline for campaign ops, ms.  Generous slack on top of this
#: is what "bounded" is asserted against.
CAMPAIGN_OP_DEADLINE_MS = 400
#: Hard per-op bound: deadline plus scheduling slack.  An op neither
#: completing nor raising inside this window is a violation ("silent
#: hang").
CAMPAIGN_OP_HARD_S = 4.0


async def _bounded_op(res: 'ScheduleResult', coro, what: str,
                      on_ambiguous=None):
    """Run one campaign op under the hard bound; returns
    ``(acked, result)``.  Shared by both campaign tiers so the typed-
    error tally, deadline counting and the silent-hang violation
    cannot drift between them.  ``on_ambiguous`` (ensemble tier) is
    called when the op was sent but its outcome is unknown."""
    try:
        return True, await asyncio.wait_for(coro, CAMPAIGN_OP_HARD_S)
    except ZKNotConnectedError:
        res.typed_errors += 1        # raised before any send: the op
        return False, None           # definitely did not apply
    except (ZKError, ZKProtocolError) as e:
        res.typed_errors += 1
        code = getattr(e, 'code', '')
        if code == 'DEADLINE_EXCEEDED':
            res.deadline_errors += 1
        if on_ambiguous is not None and code in AMBIGUOUS_CODES:
            on_ambiguous()
        return False, None
    except (asyncio.TimeoutError, TimeoutError):
        res.violations.append(
            '%s hung past the %.1fs hard bound (deadline %d ms '
            'never fired)' % (what, CAMPAIGN_OP_HARD_S,
                              CAMPAIGN_OP_DEADLINE_MS))
        if on_ambiguous is not None:
            on_ambiguous()
        return False, None


def record_settle_error(res: 'ScheduleResult', h, call_id: int,
                        exc) -> None:
    """Classify one typed op failure into its interval settle plus
    the shared tallies — ONE ladder for both concurrent tiers
    (io/faults.py ``run_concurrent_schedule`` and the process tier's
    concurrent workload, server/election.py), the ``_bounded_op``
    no-drift discipline applied to two-sided records: a definite
    spec verdict settles ``'error'``, a rejected MULTI likewise
    (whole-batch, no effect), an op that provably never left the
    client (not-connected) or bounced on the epoch fence settles
    ``'fail'`` (excluded from the search), and everything else —
    the outcome-unknown family included — settles ``'unknown'``."""
    from ..analysis.linearize import SPEC_ERRORS
    from ..protocol.errors import ZKMultiError

    res.typed_errors += 1
    code = getattr(exc, 'code', None) or type(exc).__name__
    if code == 'DEADLINE_EXCEEDED':
        res.deadline_errors += 1
    if isinstance(exc, ZKNotConnectedError):
        h.settle(call_id, 'fail', error='NOT_CONNECTED')
    elif isinstance(exc, ZKMultiError):
        h.settle(call_id, 'error', error='MULTI_REJECTED')
    elif code in SPEC_ERRORS:
        h.settle(call_id, 'error', error=code)
    elif code in ('EPOCH_FENCED', 'THROTTLED'):
        # typed bounces that provably never applied: the epoch
        # fence, and the overloaded member's write throttle (README
        # "Overload plane" — the bounce happens BEFORE proposing)
        h.settle(call_id, 'fail', error=code)
    else:
        h.settle(call_id, 'unknown', error=code)


def _note_open_spans(res: 'ScheduleResult', trace) -> None:
    """Teardown invariant shared by both campaign tiers: every span
    must be settled once the client is closed — an op evicted from the
    pending table without a settle is a span-leak bug (abandoned ops
    finish status='abandoned', never stay 'open')."""
    leaked = trace.open_spans()
    if leaked:
        res.violations.append(
            '%d trace span(s) left open after teardown: %s'
            % (len(leaked),
               ', '.join('#%d %s' % (s.span_id, s.op)
                         for s in leaked[:8])))


def _harvest_blackboxes(wal_dir: str) -> dict:
    """Lift every flight-recorder ring out of a schedule's wal_dir
    (utils/blackbox.py) before teardown removes it — the dead
    member's last spans, `merge_timelines`-ready.  Best-effort:
    salvage must never turn a passing schedule into an error."""
    try:
        from ..utils.blackbox import harvest_spans
        return harvest_spans(wal_dir)
    except Exception:
        return {}


@dataclasses.dataclass
class ScheduleResult:
    seed: int
    ops: int = 0
    acked: int = 0
    typed_errors: int = 0
    deadline_errors: int = 0
    faults: int = 0
    watch_fires: int = 0
    violations: list = dataclasses.field(default_factory=list)
    #: The client's xid-correlated span ring (utils/trace.py), dumped
    #: after the schedule: on a violation this is the exact
    #: request/reply/notification interleaving that produced it.
    trace: list = dataclasses.field(default_factory=list)
    #: Every member's server-side span ring ('member:N' -> dump):
    #: merged with the client ring by zxid (utils/trace.
    #: merge_timelines) this is the cross-member causal path of each
    #: write — printed on failure, carried in ``chaos --trace-out``.
    member_rings: dict = dataclasses.field(default_factory=dict)
    #: Which campaign tier produced this result ('transport' or
    #: 'ensemble').
    tier: str = 'transport'
    #: How many concurrent clients drove the schedule (1 = the
    #: classic single-client workload; >1 = the concurrent tier,
    #: ``run_concurrent_schedule`` — part of the rerun key:
    #: ``chaos --tier ensemble --clients N --seed S``).
    clients: int = 1
    #: Ensemble tier only: the member-event timeline (kill / restart /
    #: partition / heal / lag / migrate), in schedule order — printed
    #: next to the seed on failure so the failing interleaving of
    #: member churn is visible without rerunning.
    member_events: list = dataclasses.field(default_factory=list)
    #: Ensemble tier only: the full op/ack/watch/member history the
    #: invariant engine (io/invariants.py) checked, as JSON-ready
    #: dicts.
    history: list = dataclasses.field(default_factory=list)
    #: Ensemble/process tiers: completed leader elections observed
    #: during the schedule (server/election.py; invariant 7 replays
    #: the election records carried in ``history``).
    elections: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations


async def run_schedule(seed: int, ops: int = 6,
                       collector=None) -> ScheduleResult:
    """Run one seeded fault schedule against a fresh in-process server
    and client; returns the invariant-check result.  ``collector``
    (utils/metrics.Collector) is threaded to the client when given, so
    a caller can scrape latency histograms / FSM metrics after the
    schedule; the client's span ring is always dumped into the result.

    Invariants asserted (violations listed in the result, seed
    attached, so any failure is reproducible with the same seed):

    - every client op completes or raises a *typed* error
      (ZKError / ZKProtocolError, ZKDeadlineError included) within the
      hard per-op bound — never a silent hang;
    - no acked write is lost: an acked create (without a later acked
      delete) exists with its data; an acked delete stays deleted; the
      newest acked set is <= the server's final value (a later
      *unacked* set may have applied — at-least-once ambiguity);
    - no duplicated watch fire: no two dataChanged emits carry the
      same mzxid.
    """
    import shutil
    import tempfile

    from ..client import Client
    from ..server.server import ZKServer
    from ..server.store import ZKOpError
    from .backoff import BackoffPolicy

    inj = FaultInjector.randomized(seed)
    res = ScheduleResult(seed=seed)
    # the durability plane rides every schedule: txns are logged to a
    # throwaway WAL dir and the verification phase recovers a SIGKILL
    # crash image from it (sync policy drawn per seed; fsync faults
    # come from the injector's 'disk' category)
    wal_dir = tempfile.mkdtemp(prefix='zkchaos-wal-')
    crash_dir = tempfile.mkdtemp(prefix='zkchaos-crash-')
    durability = 'always' if inj.rand('disk') < 0.25 else 'tick'
    srv = await ZKServer(wal_dir=wal_dir, durability=durability).start()
    srv.faults = inj
    if srv.db.wal is not None:          # ZKSTREAM_NO_WAL honored
        srv.db.wal.faults = inj
    client = Client(
        address='127.0.0.1', port=srv.port, session_timeout=3000,
        seed=seed, faults=inj, op_timeout=CAMPAIGN_OP_DEADLINE_MS,
        collector=collector,
        connect_policy=BackoffPolicy(timeout=400, retries=2,
                                     delay=30, cap=200),
        default_policy=BackoffPolicy(timeout=400, retries=3,
                                     delay=50, cap=400))
    client.start()

    created: dict[str, bytes] = {}     # acked creates, path -> data
    deleted: set[str] = set()          # acked deletes
    ambig_deleted: set[str] = set()    # deletes with unknown outcome
    last_acked_set = -1                # newest acked /w value index
    fires: list[int] = []              # dataChanged mzxids

    # overload slice (README "Overload plane"), its own fresh stream
    # so existing transport seeds stay pinned: ~1 in 4 schedules
    # fires one mid-schedule pressure burst — a raw connection flood
    # against the admission path, or an oversized declared frame the
    # member must refuse with a definite close
    ovrng = random.Random('churn-overload/%d' % (seed,))
    overload_burst = (ovrng.choice(('none', 'none', 'none', 'flood',
                                    'flood', 'oversize'))
                      if ovrng.random() < 0.4 else 'none')

    async def bounded(coro, what):
        """Run one op under the shared hard bound (_bounded_op)."""
        return await _bounded_op(res, coro, what)

    try:
        try:
            await client.wait_connected(timeout=10, fail_fast=False)
        except (asyncio.TimeoutError, TimeoutError):
            res.violations.append(
                'never connected within 10s (fault budget %r should '
                'have exhausted)' % (inj.config.max_faults,))
            return res

        client.watcher('/w').on(
            'dataChanged',
            lambda data, stat: fires.append(stat.mzxid))

        ok, _ = await bounded(client.create('/w', b'v0'), 'create /w')
        if ok:
            created['/w'] = b'v0'

        set_idx = 0
        for i in range(ops):
            if not client.is_connected():
                # A fault killed the connection: give the redial loop a
                # bounded window so later ops exercise the *recovered*
                # path too, not just fail-fast ZKNotConnectedError.
                try:
                    await client.wait_connected(timeout=1.0,
                                                fail_fast=False)
                except (asyncio.TimeoutError, TimeoutError):
                    pass
            res.ops += 1
            if i == ops // 2 and overload_burst != 'none':
                if overload_burst == 'flood':
                    await _overload_flood('127.0.0.1', srv.port,
                                          ovrng.randint(6, 16))
                else:
                    hung = await _overload_oversize('127.0.0.1',
                                                    srv.port)
                    if hung:
                        res.violations.append(
                            'oversized raw frame: no definite close '
                            'within 2s (the frame cap must refuse '
                            'before buffering)')
            kind = inj.choice('plan', ('set', 'create', 'delete',
                                       'get', 'list', 'sync'))
            if kind == 'set':
                set_idx += 1
                ok, _ = await bounded(
                    client.set('/w', b'v%d' % set_idx, version=-1),
                    'set /w v%d' % set_idx)
                if ok:
                    res.acked += 1
                    last_acked_set = set_idx
            elif kind == 'create':
                path, data = '/c%d' % i, b'd%d' % i
                ok, _ = await bounded(client.create(path, data),
                                      'create %s' % path)
                if ok:
                    res.acked += 1
                    created[path] = data
            elif kind == 'delete':
                live = sorted(set(created) - deleted - {'/w'})
                if not live:
                    continue
                path = inj.choice('plan', live)
                # ambiguity-aware, like the ensemble tier: a delete
                # failing with CONNECTION_LOSS etc. may still have
                # applied, which must excuse the acked create's
                # absence below (not count as acked-write loss)
                ok, _ = await _bounded_op(
                    res, client.delete(path, -1), 'delete %s' % path,
                    on_ambiguous=lambda p=path: ambig_deleted.add(p))
                if ok:
                    res.acked += 1
                    deleted.add(path)
            elif kind == 'get':
                await bounded(client.get('/w'), 'get /w')
            elif kind == 'list':
                await bounded(client.list('/'), 'list /')
            else:
                await bounded(client.sync('/w'), 'sync /w')

        # -- verification: faults off, check the server's own tree --
        inj.stop()
        res.faults = len(inj.fired)

        def check_acked_tree(db, prefix=''):
            vs = []
            for path, data in created.items():
                if path in deleted:
                    continue
                try:
                    got, _stat = db.get_data(path)
                except ZKOpError:
                    if path in ambig_deleted:
                        continue  # an unacked delete may have landed
                    vs.append(
                        '%sacked create %s lost (NO_NODE after '
                        'campaign)' % (prefix, path))
                    continue
                if path != '/w' and bytes(got) != data:
                    vs.append(
                        '%sacked create %s holds %r, expected %r'
                        % (prefix, path, bytes(got), data))
            for path in deleted:
                try:
                    db.get_data(path)
                    vs.append('%sacked delete %s did not stick'
                              % (prefix, path))
                except ZKOpError:
                    pass
            if last_acked_set >= 0:
                try:
                    got, _stat = db.get_data('/w')
                    idx = int(bytes(got)[1:])
                    if idx < last_acked_set:
                        vs.append('%sacked set v%d lost: /w holds %r'
                                  % (prefix, last_acked_set,
                                     bytes(got)))
                except (ZKOpError, ValueError):
                    vs.append('%sacked set v%d lost: /w unreadable'
                              % (prefix, last_acked_set))
            return vs

        res.violations.extend(check_acked_tree(srv.db))

        # -- durability: SIGKILL crash image + restart-from-disk ----
        # (invariant 6 — io/invariants.py).  The crash window is
        # injector-chosen: before the pending fsync (the open
        # segment's un-fsynced tail dies with the page cache) or just
        # after.  Acks under sync='always'/'tick' are fsynced before
        # they leave (the send-plane barrier), so the recovered tree
        # must hold every acked write regardless of the window —
        # except past fsync *errors*, whose acks the transport tier
        # cannot zxid-correlate (no per-ack zxids here; the ensemble
        # tier's history can, and does, via the floor demotion).
        wal = srv.db.wal
        if wal is not None and not wal.sync_errors:
            from ..server.persist import recover_state
            from ..server.store import NodeTree

            before = inj.crash_window_before_fsync()
            wal.materialize_crash(crash_dir, before_fsync=before)
            rec = recover_state(crash_dir, trace=client.trace)
            rtree = NodeTree()
            rtree.install({'zxid': rec.zxid, 'nodes': rec.nodes})
            res.violations.extend(check_acked_tree(
                rtree, prefix='durability (crash %s fsync): '
                % ('before' if before else 'after')))

        res.watch_fires = len(fires)
        dupes = [z for z in set(fires) if fires.count(z) > 1]
        if dupes:
            res.violations.append(
                'duplicated watch fires for mzxid(s) %r' % (dupes,))
        return res
    finally:
        try:
            await asyncio.wait_for(client.close(), 5)
        except (asyncio.TimeoutError, TimeoutError):
            client.pool.stop()
            res.violations.append('client.close() hung past 5s')
        await srv.stop()
        if srv.db.wal is not None:
            srv.db.wal.close()
        # black-box harvest before the wal_dir goes: a crash-phase
        # restart may have lost in-memory spans this ring still holds
        salvaged = _harvest_blackboxes(wal_dir)
        shutil.rmtree(wal_dir, ignore_errors=True)
        shutil.rmtree(crash_dir, ignore_errors=True)
        inj.close()
        _note_open_spans(res, client.trace)
        # dump after teardown so close-phase errors are captured too
        res.trace = client.trace.dump()
        if srv.trace is not None:
            res.member_rings = {
                'member:%s' % (srv.member,): srv.trace.dump()}
        for key, spans in salvaged.items():
            res.member_rings.setdefault(key, spans)


async def run_campaign(base_seed: int, schedules: int,
                       ops: int = 6,
                       progress=None) -> list[ScheduleResult]:
    """Run ``schedules`` consecutive seeded schedules starting at
    ``base_seed``.  ``progress(result)`` is called after each one."""
    out = []
    for i in range(schedules):
        r = await run_schedule(base_seed + i, ops=ops)
        out.append(r)
        if progress is not None:
            progress(r)
    return out


# ---------------------------------------------------------------------
# Ensemble tier: deterministic failover campaigns.  One seeded
# FaultPlan schedules member kills/restarts, replication partitions,
# follower lag and forced session migration AROUND a concurrent client
# workload whose every op lands in an append-only history; the
# invariant engine (io/invariants.py) replays the history afterwards.
# Shared by tests/test_chaos_ensemble.py and ``chaos --tier ensemble``.
# ---------------------------------------------------------------------

#: The workload/member-event mix one plan step draws from ('plan'
#: stream; repetition = weight): 13 op entries vs 10 member-churn
#: entries (~60/40), so most schedules see several ops land *between*
#: failures while member events still dominate the fault surface.
PLAN_ACTIONS = (
    'set', 'set', 'set', 'get', 'get', 'list', 'sync',
    'create', 'create', 'create_seq', 'create_seq', 'create_eph',
    'delete',
    'kill_serving', 'kill_follower', 'kill_leader', 'kill_during_op',
    'restart', 'restart',
    'partition', 'partition', 'lag', 'migrate',
)


@dataclasses.dataclass
class FaultPlan:
    """One ensemble schedule's deterministic shape: everything about
    the campaign that is fixed before the first byte flows.  The
    step-by-step decisions (which action, which victim) are drawn at
    runtime from the injector's 'plan' stream, so plan + seed fully
    determine the schedule."""

    seed: int
    config: FaultConfig
    ops: int = 12
    #: client-facing members: 1 leader + (members - 1) replica-store
    #: followers (one shared leader database, killable listeners)
    members: int = 3
    session_timeout: int = 6000
    #: 'none' | 'direct' (pass-through regime) | 'batch' (device
    #: drain, bypass_bytes=0) — which receive path the client runs
    ingest_mode: str = 'none'
    #: decoherence interval, ms (None = production default): small
    #: values force live session migration back toward the leader
    #: mid-schedule
    decoherence_ms: int | None = None
    #: WAL fsync policy for the schedule ('always' | 'tick'; 'never'
    #: forfeits the guarantee the campaign exists to check, so it
    #: stays a bench arm) — server/persist.py
    durability: str = 'tick'
    #: small segments force rotation + fuzzy snapshots mid-schedule
    wal_segment_bytes: int = 1 << 16
    #: forced leader elections (server/election.py): the schedule
    #: kills the CURRENT leader at evenly spaced plan steps —
    #: restarting members first when the survivors would fall under a
    #: quorum — and each kill must produce an elected successor at a
    #: strictly higher epoch within the bounded wait, with invariant
    #: 7 replaying the election records afterwards
    elections: int = 0
    #: forced MULTI batches (store.py ``ZKDatabase.multi``): evenly
    #: spaced steps each fire one all-or-nothing batch over fresh
    #: paths, recorded whatever the outcome — invariant 8
    #: (check_multi_atomic) then demands whole-or-nothing visibility
    #: in the final tree AND across the crash-image recovery
    multis: int = 0
    #: non-voting observer members attached to the ensemble (README
    #: "Read plane"); their lag/partition churn draws come from their
    #: OWN RNG stream, and the schedule's clients run with the
    #: client-side read plane on (reads fan out over the whole
    #: membership, zxid-gated) — the session-monotone read check
    #: (check_session_reads, wired into check_history) is the
    #: invariant under test.  Part of the rerun key:
    #: ``chaos --observers N``.
    observers: int = 0
    #: forced membership changes (README "Dynamic membership"):
    #: evenly spaced plan steps each run one runtime reconfig under
    #: traffic — the FIRST is always a voter REPLACE through a joint
    #: window (the acceptance shape: both majorities must hold the
    #: joint record), later steps draw from the fresh reconfig
    #: stream.  Invariant 7's extension (check_reconfig) replays the
    #: config records.  Part of the rerun key: ``chaos --reconfig N``.
    reconfigs: int = 0
    #: read-plane subset cap for the schedule's clients (the
    #: ``ZKSTREAM_READ_SUBSET`` knob): drawn on the reconfig stream —
    #: a subset-capped plane must rebalance correctly when the
    #: resolver adopts a post-reconfig member list
    read_subset: int | None = None
    #: forced overload bursts (README "Overload plane"): evenly
    #: spaced plan steps each fire one pressure action against a
    #: live member — a raw connection flood (admission caps +
    #: pacer), a stalled client reader (slow-consumer defense), or
    #: an oversized declared frame (the frame cap).  The action mix
    #: draws from a fresh 'churn-overload' stream; part of the
    #: rerun key: ``chaos --overload N``.
    overloads: int = 0
    #: watch-backed client cache (README "Client cache plane",
    #: io/cache.py): the schedule's clients run with ``cache='/'`` —
    #: every read consults the persistent-recursive-watch-backed
    #: local cache first, and the history must still pass
    #: check_session_reads (a cached read can never time-travel:
    #: serve gate + fill gate + invalidation floor).  Part of the
    #: rerun key: ``chaos --cached``.
    cached: bool = False

    @classmethod
    def randomized(cls, seed: int, ops: int = 12) -> 'FaultPlan':
        rng = random.Random('plan/%d' % (seed,))
        plan = cls(
            seed=seed,
            config=FaultConfig.randomized_ensemble(seed),
            ops=ops,
            session_timeout=rng.choice([2000, 4000, 8000]),
            ingest_mode=rng.choice(['none', 'none', 'direct',
                                    'batch']),
            decoherence_ms=rng.choice([None, None, 50, 120]))
        # drawn AFTER the existing fields so the durability plane
        # never perturbed the plan shapes the existing seeds produce
        plan.durability = rng.choice(['tick', 'tick', 'always'])
        plan.wal_segment_bytes = rng.choice([1 << 12, 1 << 14,
                                             1 << 20])
        # its own stream, same rule: adding the election plane must
        # not perturb the transport/plan draws existing seeds pin
        erng = random.Random('plan-elect/%d' % (seed,))
        plan.elections = erng.choice([0, 0, 0, 1, 2])
        # same rule again for the MULTI pillar (PR 12)
        mrng = random.Random('plan-multi/%d' % (seed,))
        plan.multis = mrng.choice([0, 1, 1, 2])
        # and again for the read plane (PR 15): the observer count
        # rides a fresh stream, so every draw existing seeds pinned
        # still produces the same value
        obrng = random.Random('plan-observers/%d' % (seed,))
        plan.observers = obrng.choice([0, 0, 0, 1, 2])
        # and for dynamic membership (PR 16): reconfig count and the
        # read-plane subset cap ride one fresh stream, so every draw
        # existing seeds pinned still produces the same value
        rrng = random.Random('plan-reconfig/%d' % (seed,))
        plan.reconfigs = rrng.choice([0, 0, 0, 1, 2])
        plan.read_subset = rrng.choice([None, None, 2, 3])
        # and for the overload plane (PR 18): the burst count rides
        # a fresh stream, so every draw existing seeds pinned still
        # produces the same value
        ovrng = random.Random('plan-overload/%d' % (seed,))
        plan.overloads = ovrng.choice([0, 0, 0, 1, 2])
        # and for the cache plane (PR 20): the cached-client draw
        # rides a fresh stream, so every draw existing seeds pinned
        # still produces the same value
        carng = random.Random('plan-cache/%d' % (seed,))
        plan.cached = carng.choice([False, False, False, True])
        return plan

    def forced_election_steps(self) -> set[int]:
        """The plan steps that force an election (evenly spaced
        through the schedule, before the drawn action of that step)."""
        if self.elections <= 0:
            return set()
        return {((k + 1) * self.ops) // (self.elections + 1)
                for k in range(self.elections)}

    def forced_multi_steps(self) -> set[int]:
        """The plan steps that fire a MULTI batch (evenly spaced,
        before the drawn action; may share a step with a forced
        election — both then run)."""
        if self.multis <= 0:
            return set()
        return {((2 * k + 1) * self.ops) // (2 * self.multis + 1)
                for k in range(self.multis)}

    def forced_reconfig_steps(self) -> set[int]:
        """The plan steps that run a forced membership change
        (evenly spaced, before the drawn action; the first executed
        is always a voter replace)."""
        if self.reconfigs <= 0:
            return set()
        return {((k + 1) * self.ops) // (self.reconfigs + 1)
                for k in range(self.reconfigs)}

    def forced_overload_steps(self) -> set[int]:
        """The plan steps that fire an overload burst (evenly
        spaced, before the drawn action; offset from the reconfig
        spacing so the two rarely collide)."""
        if self.overloads <= 0:
            return set()
        return {((2 * k + 1) * self.ops) // (2 * self.overloads + 1)
                for k in range(self.overloads)}


class EnsembleUnderTest:
    """The campaign's ensemble: a ``ZKEnsemble`` (member 0 = leader
    endpoint; followers serve from their own ReplicaStore, so they
    genuinely lag when told to) composed — not subclassed, so the
    client-side io package keeps its lazy server imports — with
    dead-member tracking, a ReplicationService, and one
    cross-process-protocol replica: a RemoteLeader mirror over real
    TCP through server/replication.py that the plan partitions and
    heals.  Member lifecycle (start/kill/restart/lag) delegates to
    the ZKEnsemble, so the two harnesses cannot drift.

    The replica does not serve clients: a RemoteLeader forwards writes
    over a *blocking* control socket, and with every member on the one
    campaign event loop that RPC would deadlock against the
    ReplicationService it is calling (the OS-process tier exists
    precisely because of this — tests/process_member_worker.py); the
    SIGKILL acceptance test keeps that tier covered.  Here the replica
    is the partition target, and its convergence with the leader after
    heal + sync barrier is one of the campaign's checks."""

    def __init__(self, members: int = 3, wal_dir: str | None = None,
                 durability: str | None = None,
                 wal_segment_bytes: int | None = None,
                 seed: int | None = None, observers: int = 0):
        from ..server.replication import ReplicationService
        from ..server.server import ZKEnsemble

        #: heartbeat shrunk for campaign pace: leader-loss detection
        #: inside a few plan steps instead of half a second
        self._ens = ZKEnsemble(members, lag=0.0, wal_dir=wal_dir,
                               durability=durability,
                               wal_segment_bytes=wal_segment_bytes,
                               heartbeat_ms=40, seed=seed,
                               observers=observers)
        self.db = self._ens.db
        self.servers = self._ens.servers
        self.coordinator = self._ens.election
        self.svc = ReplicationService(self.db)
        self.dead: set[int] = set()
        #: members a reconfig removed from the ensemble outright
        #: (observer leave): stopped and detached, never restarted
        self.removed: set[int] = set()
        self.remote = None           # RemoteLeader (events/control)
        self.replica = None          # RemoteReplicaStore over it

    @property
    def leader_idx(self) -> int:
        return self._ens.leader_idx

    @property
    def voters(self) -> int:
        """Voting-member count — live through reconfigs (the
        ZKEnsemble re-derives it on every config change)."""
        return self._ens.voters

    def voter_idxs(self) -> list[int]:
        """Current voter member indices, from the installed config
        (after a reconfig they are no longer ``range(voters)``)."""
        if getattr(self.db, 'voter_ids', None) is not None:
            return sorted(self.db.voter_ids)
        return list(range(self._ens.voters))

    def observer_idxs(self) -> list[int]:
        """Current observer member indices, from the installed
        config."""
        if getattr(self.db, 'voter_ids', None) is not None:
            return sorted(self.db.observer_ids)
        return list(range(self._ens.voters, len(self.servers)))

    def config_addresses(self) -> list[tuple[str, int]]:
        """The live config's member addresses (voters + observers) —
        what a client resolver adopts after a membership change."""
        idxs = sorted(set(self.voter_idxs())
                      | set(self.observer_idxs()))
        return [self.servers[i].address for i in idxs
                if i < len(self.servers)]

    async def start(self) -> 'EnsembleUnderTest':
        from ..server.replication import (
            RemoteLeader,
            RemoteReplicaStore,
        )

        await self._ens.start()
        await self.svc.start()
        self.remote = await RemoteLeader('127.0.0.1',
                                         self.svc.port).connect()
        self.replica = RemoteReplicaStore(self.remote, lag=0.0)
        return self

    def install_faults(self, inj: FaultInjector) -> None:
        self._ens.install_faults(inj)
        self.svc.faults = inj
        if self.db.wal is not None:
            self.db.wal.faults = inj

    def addresses(self) -> list[tuple[str, int]]:
        return self._ens.addresses()

    def live(self) -> list[int]:
        return [i for i in range(len(self.servers))
                if i not in self.dead and i not in self.removed]

    async def kill(self, idx: int) -> None:
        await self._ens.kill(idx)
        self.dead.add(idx)

    async def restart(self, idx: int) -> None:
        await self._ens.restart(idx)
        self.dead.discard(idx)

    def set_lag(self, idx: int, lag: float | None) -> None:
        """Delayed follower catch-up: None parks the follower's
        replica until the next write/sync through it; restoring a
        non-positive lag applies the parked backlog immediately."""
        self._ens.set_lag(idx, lag)
        if lag is not None and lag <= 0:
            self.servers[idx].store.catch_up()

    # -- runtime membership changes (delegated to the ZKEnsemble so
    # the two harnesses cannot drift) --

    async def add_observer(self) -> int:
        return await self._ens.add_observer()

    async def remove_observer(self, idx: int) -> None:
        await self._ens.remove_observer(idx)
        self.removed.add(idx)

    async def add_voter(self) -> int:
        return await self._ens.add_voter()

    async def remove_voter(self, idx: int) -> None:
        # the demoted member drains on as an out-of-config observer
        # (still killable, still serving) — not `removed`
        await self._ens.remove_voter(idx)

    async def replace_voter(self, old_idx: int) -> int:
        return await self._ens.replace_voter(old_idx)

    def partition_replica(self) -> bool:
        """Toggle the scheduled asymmetric partition of the TCP
        replica; returns True when now partitioned."""
        token = self.remote.token
        if token in self.svc.partitioned:
            self.svc.partitioned.discard(token)
            return False
        self.svc.partitioned.add(token)
        return True

    def heal(self) -> None:
        self.svc.partitioned.clear()

    async def stop(self) -> None:
        if self.remote is not None:
            self.remote.close()
        await self._ens.stop()
        await self.svc.stop()


#: The forced-reconfig action mix ('churn-reconfig' stream;
#: repetition = weight).  The first executed step of every schedule
#: bypasses the draw: it is always 'replace-voter', the full joint
#: handoff the acceptance criteria pin.
RECONFIG_ACTIONS = ('replace-voter', 'add-observer', 'add-observer',
                    'remove-observer', 'add-voter', 'remove-voter')


def _make_force_reconfig(ens, res, rrng, note_member,
                         force_election, update_resolvers):
    """Build the forced-reconfig step shared by the ensemble
    schedules (single-client and concurrent): one membership change
    under traffic per call.  The db's config-change hook (wrapped by
    the caller) records every config record into the history, so
    invariant 7's extension replays exactly what landed."""
    done = {'k': 0}

    async def force_reconfig() -> None:
        db = ens.db
        if getattr(db, 'voter_ids', None) is None \
                or ens.coordinator is None:
            return
        k, done['k'] = done['k'], done['k'] + 1
        # a joint commit needs majorities of BOTH configs audible:
        # bring dead members back before opening the window
        for back in sorted(ens.dead):
            note_member('restart', back)
            await ens.restart(back)
        act = ('replace-voter' if k == 0
               else rrng.choice(RECONFIG_ACTIONS))
        voter_change = act not in ('add-observer',
                                   'remove-observer')
        if voter_change and db.reconfig_epoch == db.epoch:
            # at most one voter-set change per epoch (invariant 7
            # extension): a second change needs a fresh era — earn
            # it the legitimate way, through an election
            await force_election()
            for back in sorted(ens.dead):
                note_member('restart', back)
                await ens.restart(back)
        try:
            if act == 'add-observer':
                idx = await asyncio.wait_for(ens.add_observer(), 10)
                note_member('reconfig-add-observer', idx)
            elif act == 'remove-observer':
                obs = [i for i in ens.observer_idxs()
                       if i not in ens.dead and i not in ens.removed]
                if not obs:
                    return
                idx = obs[rrng.randrange(len(obs))]
                await asyncio.wait_for(ens.remove_observer(idx), 10)
                note_member('reconfig-remove-observer', idx)
            elif act == 'add-voter':
                idx = await asyncio.wait_for(ens.add_voter(), 10)
                note_member('reconfig-add-voter', idx)
            elif act == 'remove-voter':
                cands = [i for i in ens.voter_idxs()
                         if i != ens.leader_idx]
                if len(ens.voter_idxs()) <= 2 or not cands:
                    return
                idx = cands[rrng.randrange(len(cands))]
                await asyncio.wait_for(ens.remove_voter(idx), 10)
                note_member('reconfig-remove-voter', idx)
            else:
                cands = [i for i in ens.voter_idxs()
                         if i != ens.leader_idx]
                if not cands:
                    return
                old = cands[rrng.randrange(len(cands))]
                idx = await asyncio.wait_for(
                    ens.replace_voter(old), 10)
                note_member('reconfig-replace-voter(%d->%d)'
                            % (old, idx), idx)
        except ValueError as e:
            # a legal refusal (the per-epoch fence, an empty voter
            # set): the fence HOLDING is the invariant — record it
            # in the timeline and move on
            note_member('reconfig-refused(%s)' % (e,), act)
            return
        except (asyncio.TimeoutError, TimeoutError):
            res.violations.append(
                'forced reconfig (%s) hung past 10s: joint quorum '
                'never assembled' % (act,))
            return
        # the elastic client side: resolvers adopt the new member
        # list, subset-capped read planes rebalance onto it
        update_resolvers()
        note_member('resolver-update', '-')

    return force_reconfig


#: The forced-overload action mix ('churn-overload' stream;
#: repetition = weight).  Every action must observe a definite
#: outcome — an oversized raw frame left hanging open is a
#: violation, a shed flood connection is the defense working.
OVERLOAD_ACTIONS = ('flood', 'flood', 'stall', 'stall', 'oversize')


async def _overload_flood(address: str, port: int, n: int,
                          hold_s: float = 0.05) -> None:
    """Open ``n`` raw TCP connections at once and hold them briefly:
    the admission path (per-shard/global caps + handshake pacer,
    io/overload.py) must shed or accept every one with the member
    still serving — never wedge the accept loop.  A refused or
    RST-shed dial IS the defense working, so errors are swallowed."""
    async def one():
        try:
            _r, w = await asyncio.wait_for(
                asyncio.open_connection(address, port), 1.0)
        except (OSError, asyncio.TimeoutError, TimeoutError):
            return
        try:
            await asyncio.sleep(hold_s)
        finally:
            w.close()
    await asyncio.gather(*(one() for _ in range(n)),
                         return_exceptions=True)


async def _overload_oversize(address: str, port: int,
                             declared: int = 1 << 27) -> bool:
    """Declare an absurd frame length on a raw socket: the member
    must refuse it BEFORE buffering (a typed frame-cap eviction,
    io/overload.py) and the socket must observe a definite close.
    Returns True when the socket HUNG open instead — the caller
    records that as a violation."""
    try:
        r, w = await asyncio.wait_for(
            asyncio.open_connection(address, port), 1.0)
    except (OSError, asyncio.TimeoutError, TimeoutError):
        return False
    try:
        w.write(struct.pack('>i', declared) + b'\x00' * 16)
        try:
            await asyncio.wait_for(w.drain(), 1.0)
        except (OSError, asyncio.TimeoutError, TimeoutError):
            pass
        try:
            await asyncio.wait_for(r.read(1 << 16), 2.0)
        except (asyncio.TimeoutError, TimeoutError):
            return True
        except OSError:
            return False
        return False
    finally:
        try:
            w.close()
        except OSError:
            pass


def _make_force_overload(res, ovrng, note_member, live_address,
                         pick_client, cfg=None):
    """Build the overload pressure step shared by the ensemble
    schedules (single-client and concurrent): one burst per call
    against a live member — a forced plan step draws its own action
    (``act=None``), a config-probability firing passes the
    injector's drawn action in.  ``live_address()`` returns a live
    member's ``(host, port)`` or None; ``pick_client()`` returns
    the client whose reader the 'stall' action parks (the
    slow-consumer shape — the member's tx backlog for that session
    grows until the soft watermark starts shedding notifications).
    ``cfg`` (FaultConfig) bounds the flood size and stall window."""
    async def force_overload(act: str | None = None) -> None:
        addr = live_address()
        if addr is None:
            return
        if act is None:
            act = ovrng.choice(OVERLOAD_ACTIONS)
        if act == 'flood':
            n = (ovrng.randint(6, max(7, cfg.flood_conns))
                 if cfg is not None else ovrng.randint(6, 20))
            note_member('overload-flood(%d)' % (n,), '-')
            await _overload_flood(addr[0], addr[1], n)
        elif act == 'stall':
            c = pick_client()
            conn = (c.current_connection()
                    if c is not None else None)
            t = getattr(conn, 'transport', None)
            if t is None:
                return
            lo, hi = (cfg.stall_window_ms if cfg is not None
                      else (20.0, 120.0))
            window = ovrng.uniform(lo, hi) / 1000.0
            note_member('overload-stall(%.0fms)'
                        % (window * 1e3), '-')
            try:
                t.pause_reading()
            except (RuntimeError, OSError):
                return
            await asyncio.sleep(window)
            try:
                t.resume_reading()
            except (RuntimeError, OSError):
                pass
        else:
            note_member('overload-oversize', '-')
            hung = await _overload_oversize(addr[0], addr[1])
            if hung:
                res.violations.append(
                    'oversized raw frame: no definite close within '
                    '2s (the frame cap must refuse before '
                    'buffering)')
    return force_overload


async def run_ensemble_schedule(seed: int, ops: int = 12,
                                collector=None,
                                plan: FaultPlan | None = None,
                                elections: int | None = None,
                                clients: int | None = None,
                                observers: int | None = None,
                                reconfigs: int | None = None,
                                overloads: int | None = None,
                                cached: bool | None = None
                                ) -> ScheduleResult:
    """Run one seeded ensemble-tier schedule: member churn around a
    client workload, every op recorded into an append-only history,
    then the history invariants (io/invariants.py) checked against
    the leader's final database.  ``clients`` > 1 switches to the
    concurrent tier (:func:`run_concurrent_schedule`): N clients
    writing overlapping keys, checked per key for linearizability
    (invariant 9).  ``observers`` overrides the plan's non-voting
    member count (read plane; their churn rides a fresh RNG
    stream).  Any failure is reproducible with ``python -m
    zkstream_tpu chaos --tier ensemble --seed N [--clients N]
    [--observers N]``."""
    if clients is not None and clients > 1:
        return await run_concurrent_schedule(
            seed, ops=ops, clients=clients, collector=collector,
            plan=plan, elections=elections, observers=observers,
            reconfigs=reconfigs, overloads=overloads, cached=cached)
    from ..client import Client
    from ..protocol.consts import CreateFlag
    from .backoff import BackoffPolicy
    from .invariants import History, check_ephemerals, check_history
    from .pool import DEFAULT_DECOHERENCE_INTERVAL

    import shutil
    import tempfile

    if plan is None:
        plan = FaultPlan.randomized(seed, ops=ops)
    if elections is not None:
        # explicit override (chaos --elections N): part of the rerun
        # key — seed + flags reproduce the schedule exactly
        plan.elections = elections
    if observers is not None:
        plan.observers = observers
    if reconfigs is not None:
        plan.reconfigs = reconfigs
    if overloads is not None:
        plan.overloads = overloads
    if cached is not None:
        plan.cached = cached
    #: observer churn draws ride their own stream (fresh per seed):
    #: attaching observers must not shift any draw existing seeds pin
    orng = random.Random('churn-obs/%d' % (seed,))
    #: forced-reconfig draws (victim/action choice) — fresh stream,
    #: same rule
    rrng = random.Random('churn-reconfig/%d' % (seed,))
    #: forced-overload draws (action/size choice) — fresh stream,
    #: same rule
    ovrng = random.Random('churn-overload/%d' % (seed,))
    inj = FaultInjector(seed, plan.config)
    res = ScheduleResult(seed=seed, tier='ensemble')
    h = History()

    wal_dir = tempfile.mkdtemp(prefix='zkchaos-ens-wal-')
    crash_dir = tempfile.mkdtemp(prefix='zkchaos-ens-crash-')
    ens = await EnsembleUnderTest(
        plan.members, wal_dir=wal_dir, durability=plan.durability,
        wal_segment_bytes=plan.wal_segment_bytes, seed=seed,
        observers=plan.observers).start()
    ens.install_faults(inj)

    # every config record — joint and final — lands in the history
    # with the epoch it was appended under; check_reconfig (the
    # invariant-7 extension) replays them.  Chained UNDER the
    # ZKEnsemble's own hook, which re-derives the quorum/ballot sets.
    _prev_cfg_hook = ens.db.on_config_change

    def _on_cfg(phase, entry, _prev=_prev_cfg_hook):
        if _prev is not None:
            _prev(phase, entry)
        h.reconfig(entry[1], entry[2], ens.db.epoch,
                   voters=entry[4], old_voters=entry[3],
                   observers=entry[5])
    ens.db.on_config_change = _on_cfg

    ingest = None
    if plan.ingest_mode != 'none':
        from .ingest import FleetIngest
        ingest = FleetIngest(
            body_mode='host', max_frames=8,
            bypass_bytes=0 if plan.ingest_mode == 'batch' else 16384)
        ingest.faults = inj

    client = Client(
        servers=ens.addresses(), shuffle_backends=False,
        session_timeout=plan.session_timeout, seed=seed, faults=inj,
        op_timeout=CAMPAIGN_OP_DEADLINE_MS, collector=collector,
        ingest=ingest, trace_capacity=512,
        # with observers attached the client-side read plane is on:
        # reads fan out across the whole membership, zxid-gated, and
        # check_session_reads holds the session-monotone rung
        read_distribution=plan.observers > 0,
        read_subset=plan.read_subset,
        # --cached: the watch-backed cache plane rides the whole
        # fault vocabulary; check_session_reads must still hold on
        # every locally-served read (cache=False pins the knob OFF
        # regardless of ZKSTREAM_CACHE, keeping schedules seeded)
        cache='/' if plan.cached else False,
        decoherence_interval=(plan.decoherence_ms
                              if plan.decoherence_ms is not None
                              else DEFAULT_DECOHERENCE_INTERVAL),
        connect_policy=BackoffPolicy(timeout=400, retries=2,
                                     delay=30, cap=200),
        default_policy=BackoffPolicy(timeout=400, retries=3,
                                     delay=50, cap=400))

    def on_op(span):
        h.op(span.op, span.path, status=span.status, zxid=span.zxid,
             session_id=int(span.session_id, 16)
             if span.session_id else 0,
             error=span.error)
    client.on_op = on_op
    client.on('expire', lambda: h.session_event(
        'expired', client.session.session_id
        if client.session is not None else 0))
    client.start()

    def note_member(event: str, member) -> None:
        h.member_event(event, member)
        client.trace.note('MEMBER_' + event.upper(),
                          path='member:%s' % (member,), kind='member')

    if ens.coordinator is None:
        # static-leader validator path (ZKSTREAM_NO_ELECTION=1 /
        # election=False): a drawn election count is meaningless here
        # and must not read as a missed-election violation — and a
        # reconfig's joint handoff has no election to lean on either
        plan.elections = 0
        plan.reconfigs = 0
    else:
        # every completed election lands in the history (invariant 7
        # replays these) AND the client span ring, so a failing seed's
        # timeline shows the failover causally
        def on_elected(member, epoch, dur_ms):
            h.election(member, epoch)
            client.trace.note('ELECTED',
                              path='member:%s' % (member,),
                              kind='member',
                              detail='epoch=%d' % (epoch,),
                              duration_ms=round(dur_ms, 3))
        ens.coordinator.on('elected', on_elected)

    def elections_seen() -> int:
        return sum(1 for r in h.records if r['kind'] == 'election')

    async def force_election() -> None:
        """Kill the CURRENT leader and wait for the coordinator to
        elect a successor — restarting dead members first when the
        survivors would fall under a quorum.  The detection path is
        the real one (heartbeat monitor), not a direct call."""
        if ens.coordinator is None:
            return
        voter_set = set(ens.voter_idxs())
        need = len(voter_set) // 2 + 1
        while ens.dead and \
                len([j for j in ens.live() if j in voter_set]) - 1 \
                < need:
            back = sorted(ens.dead)[0]
            note_member('restart', back)
            await ens.restart(back)
        lead = ens.leader_idx
        before = elections_seen()
        if lead not in ens.dead:
            note_member('kill-leader', lead)
            await ens.kill(lead)
        deadline = 8.0
        step = 0.02
        while elections_seen() <= before and deadline > 0:
            await asyncio.sleep(step)
            deadline -= step
        if elections_seen() <= before:
            res.violations.append(
                'forced election: no successor elected within 8s of '
                'killing leader %d' % (lead,))

    force_reconfig = _make_force_reconfig(
        ens, res, rrng, note_member, force_election,
        lambda: client.update_backends(ens.config_addresses()))

    def _live_address():
        live = ens.live()
        if not live:
            return None
        return ens.servers[live[0]].address

    force_overload = _make_force_overload(
        res, ovrng, note_member, _live_address, lambda: client,
        cfg=plan.config)

    def sid() -> int:
        for r in reversed(h.records):
            if r['kind'] == 'op':
                return r['session_id']
        return 0

    def last_zxid() -> int | None:
        """The reply zxid of the op that just completed (its span
        settles — and lands in the history via on_op — before the op
        future resolves); stamps acks so the recovery invariant can
        demote acks past a failed fsync's durable floor."""
        for r in reversed(h.records):
            if r['kind'] == 'op':
                return r.get('zxid')
        return None

    async def bounded(coro, what, op=None, path=None, seq_parent=None):
        """One op under the shared hard bound (_bounded_op); writes
        with an unknown outcome are recorded as ambiguous."""
        on_amb = None
        if op is not None:
            def on_amb():
                h.ambiguous(op, path, session_id=sid(),
                            sequential_parent=seq_parent)
        return await _bounded_op(res, coro, what, on_amb)

    async def do_create(path, data, flags=0, seq_parent=None):
        ok, made = await bounded(
            client.create(path, data, flags=flags),
            'create %s' % path, op='create', path=path,
            seq_parent=seq_parent)
        if ok:
            res.acked += 1
            h.acked_create(made, data, sid(),
                           ephemeral=bool(CreateFlag(flags)
                                          & CreateFlag.EPHEMERAL),
                           sequential_parent=seq_parent,
                           zxid=last_zxid())
        return ok, made

    async def wait_usable(timeout: float) -> bool:
        if client.is_connected():
            return True
        try:
            await client.wait_connected(timeout=timeout,
                                        fail_fast=False)
            return True
        except (asyncio.TimeoutError, TimeoutError):
            return False

    fires: list = []
    created: list[str] = []          # deletable acked paths
    set_idx = 0
    try:
        if not await wait_usable(10):
            res.violations.append(
                'never connected within 10s (fault budget %r should '
                'have exhausted)' % (inj.config.max_faults,))
            return res

        client.watcher('/w').on(
            'dataChanged',
            lambda data, stat: (fires.append(stat.mzxid),
                                h.watch_fire('/w', 'dataChanged',
                                             stat.mzxid)))
        client.watcher('/').on(
            'childrenChanged',
            lambda ch, stat: h.watch_fire('/', 'childrenChanged',
                                          stat.pzxid))

        # bootstrap nodes the workload mutates; a failed bootstrap is
        # fine — the dependent ops surface typed errors
        ok, _ = await do_create('/w', b'v0')
        if ok:
            h.acked_set('/w', 0, sid(), zxid=last_zxid())
        await do_create('/seq', b'')

        async def do_multi(i: int) -> None:
            """One forced all-or-nothing batch over fresh paths:
            create two nodes and overwrite the first, as ONE txn.
            Recorded whatever the outcome — invariant 8 demands
            whole-or-nothing visibility either way."""
            a, b = '/m%da' % (i,), '/m%db' % (i,)
            za, yb = b'z%d' % (i,), b'y%d' % (i,)
            ops_ = [{'op': 'create', 'path': a, 'data': b'x'},
                    {'op': 'create', 'path': b, 'data': yb},
                    {'op': 'set_data', 'path': a, 'data': za}]
            h.multi_batch([('create', a, za), ('create', b, yb)],
                          session_id=sid())
            ok, _ = await bounded(client.multi(ops_),
                                  'multi %d' % (i,), op='multi')
            if ok:
                res.acked += 1
                h.acked_create(a, za, sid(), zxid=last_zxid())
                h.acked_create(b, yb, sid(), zxid=last_zxid())

        forced_steps = plan.forced_election_steps()
        multi_steps = plan.forced_multi_steps()
        reconfig_steps = plan.forced_reconfig_steps()
        overload_steps = plan.forced_overload_steps()
        for i in range(plan.ops):
            await wait_usable(1.5)
            res.ops += 1
            if i in forced_steps:
                await force_election()
            if i in reconfig_steps:
                await force_reconfig()
            if i in overload_steps:
                await force_overload()
            if i in multi_steps:
                await do_multi(i)
            act = inj.choice('plan', PLAN_ACTIONS)
            if act == 'set':
                set_idx += 1
                ok, _ = await bounded(
                    client.set('/w', b'v%d' % set_idx, version=-1),
                    'set /w v%d' % set_idx, op='set', path='/w')
                if ok:
                    res.acked += 1
                    h.acked_set('/w', set_idx, sid(),
                                zxid=last_zxid())
            elif act == 'create':
                ok, made = await do_create('/c%d' % i, b'd%d' % i)
                if ok:
                    created.append(made)
            elif act == 'create_seq':
                await do_create('/seq/n-', b's%d' % i,
                                flags=CreateFlag.SEQUENTIAL,
                                seq_parent='/seq')
            elif act == 'create_eph':
                await do_create('/e%d' % i, b'e%d' % i,
                                flags=CreateFlag.EPHEMERAL)
            elif act == 'delete':
                if not created:
                    continue
                path = inj.choice('plan', created)
                ok, _ = await bounded(client.delete(path, -1),
                                      'delete %s' % path,
                                      op='delete', path=path)
                if ok:
                    res.acked += 1
                    h.acked_delete(path, sid(), zxid=last_zxid())
                    created.remove(path)
            elif act == 'get':
                await bounded(client.get('/w'), 'get /w')
            elif act == 'list':
                await bounded(client.list('/'), 'list /')
            elif act == 'sync':
                await bounded(client.sync('/w'), 'sync /w',
                              op='sync', path='/w')
            elif act in ('kill_serving', 'kill_during_op'):
                conn = client.current_connection()
                live = ens.live()
                if conn is None or len(live) <= 1:
                    continue
                victim = next((j for j in live
                               if ens.servers[j].port ==
                               conn.backend.port), None)
                if victim is None:
                    continue
                if act == 'kill_during_op':
                    set_idx += 1
                    inflight = asyncio.get_running_loop().create_task(
                        client.set('/w', b'v%d' % set_idx,
                                   version=-1))
                    await asyncio.sleep(0.003)
                    note_member('kill-mid-op', victim)
                    await ens.kill(victim)
                    ok, _ = await bounded(
                        inflight, 'mid-kill set /w v%d' % set_idx,
                        op='set', path='/w')
                    if ok:
                        res.acked += 1
                        h.acked_set('/w', set_idx, sid(),
                                    zxid=last_zxid())
                else:
                    note_member('kill', victim)
                    await ens.kill(victim)
            elif act == 'kill_follower':
                # voters only: observer churn rides its own stream
                # (the CONFIG's voter set — after a reconfig the
                # voters are no longer ``range(voters)``)
                voter_set = set(ens.voter_idxs())
                live = [j for j in ens.live()
                        if j != 0 and j in voter_set]
                if not live or len(ens.live()) <= 1:
                    continue
                victim = inj.choice('plan', live)
                note_member('kill', victim)
                await ens.kill(victim)
            elif act == 'kill_leader':
                # the CURRENT leader: with election on it may be any
                # member (a previous kill already moved leadership)
                lead = ens.leader_idx
                if lead in ens.dead or len(ens.live()) <= 1:
                    continue
                note_member('kill', lead)
                await ens.kill(lead)
            elif act == 'restart':
                if not ens.dead:
                    continue
                back = inj.choice('plan', sorted(ens.dead))
                note_member('restart', back)
                await ens.restart(back)
            elif act == 'partition':
                if ens.partition_replica():
                    note_member('partition', 'replica')
                else:
                    note_member('heal', 'replica')
            elif act == 'lag':
                # non-member-0 voters (same list as range(1, voters)
                # until a reconfig moves the membership; same length
                # either way, so the 'plan' stream stays aligned)
                idx = inj.choice('plan',
                                 [j for j in ens.voter_idxs()
                                  if j != 0])
                lag = inj.choice('plan', (None, 0.05, 0.0))
                note_member('lag=%r' % (lag,), idx)
                ens.set_lag(idx, lag)
            else:
                assert act == 'migrate', act
                note_member('migrate', '-')
                client.pool.rebalance_now()
            if plan.observers:
                # observer fault vocabulary, on its OWN stream: lag
                # windows, a sustained park (the partition shape — a
                # partitioned observer's replica stops applying, so
                # only ITS sessions' reads gate-block or bounce) and
                # heals.  The zxid read gate is the invariant under
                # test: check_session_reads must stay clean.
                oact = orng.choice(('none', 'none', 'lag', 'park',
                                    'heal'))
                if oact != 'none':
                    # the CONFIG's observers (identical to
                    # voters+range(observers) until a reconfig moves
                    # the membership; one draw either way, so the
                    # stream stays aligned)
                    obs = [j for j in ens.observer_idxs()
                           if j not in ens.removed]
                    pick = orng.randrange(max(1, len(obs)))
                    if not obs:
                        continue
                    oidx = obs[pick]
                    if oact == 'lag':
                        olag = orng.choice((0.05, 0.0))
                        note_member('observer-lag=%r' % (olag,), oidx)
                        ens.set_lag(oidx, olag)
                    elif oact == 'park':
                        note_member('observer-partition', oidx)
                        ens.set_lag(oidx, None)
                    else:
                        note_member('observer-heal', oidx)
                        ens.set_lag(oidx, 0.0)
            # config-probability overload firings ('overload'
            # stream, fault-budget accounted) on top of the plan's
            # forced steps
            ov_act = inj.overload_action()
            if ov_act is not None:
                await force_overload(ov_act)

        # -- verification: faults off, ensemble healed --------------
        inj.stop()
        ens.heal()
        for back in sorted(ens.dead):
            note_member('restart', back)
            await ens.restart(back)
        for j in range(1, len(ens.servers)):
            ens.set_lag(j, 0.0)
        if not await wait_usable(10):
            res.violations.append(
                'never reconnected after every member was restarted '
                'and faults stopped')
        else:
            await bounded(client.sync('/w'), 'final sync /w',
                          op='sync', path='/w')
        # the TCP replica must converge once partitions heal: the
        # sync barrier rides the (never-partitioned) control channel
        try:
            await asyncio.wait_for(
                asyncio.get_running_loop().run_in_executor(
                    None, ens.replica.sync_flush), 5)
        except (asyncio.TimeoutError, TimeoutError):
            res.violations.append(
                'replica sync barrier hung after partitions healed')
        else:
            if ens.replica.zxid != ens.db.zxid:
                res.violations.append(
                    'replica did not converge after heal: replica '
                    'zxid %d, leader zxid %d'
                    % (ens.replica.zxid, ens.db.zxid))
            else:
                diverged = [
                    p for p in ens.db.nodes
                    if p not in ens.replica.nodes
                    or bytes(ens.replica.nodes[p].data)
                    != bytes(ens.db.nodes[p].data)]
                extra = [p for p in ens.replica.nodes
                         if p not in ens.db.nodes]
                if diverged or extra:
                    res.violations.append(
                        'replica tree diverged from leader at equal '
                        'zxid %d: missing/stale %r, extra %r'
                        % (ens.db.zxid, sorted(diverged)[:8],
                           sorted(extra)[:8]))

        res.watch_fires = len(fires)
        # compare against the steps actually SCHEDULED: with ops <
        # elections+1 the evenly-spaced steps collide and fewer
        # elections are forced — that is a plan-shape fact, not a
        # missed election
        forced_n = len(plan.forced_election_steps())
        if forced_n and elections_seen() < forced_n:
            res.violations.append(
                'plan forced %d election(s) but only %d completed'
                % (forced_n, elections_seen()))
        # a forced reconfig may legally refuse (the per-epoch fence),
        # but a plan that forces any must land at least one config
        # record — the first step's voter replace has no fence to hit
        if plan.forced_reconfig_steps() and \
                not h.of_kind('reconfig'):
            res.violations.append(
                'plan forced %d reconfig step(s) but no config '
                'record landed' % (plan.reconfigs,))
        res.violations.extend(check_history(h, ens.db))

        # -- durability: full-ensemble SIGKILL + restart-from-disk --
        # (invariant 6).  The crash image is the WAL directory as a
        # SIGKILL would leave it — cut at an injector-chosen fsync
        # window — and the recovered database must hold every
        # unambiguously-acked write.  The floor demotion only engages
        # when an injected fsync error left acks non-durable; under
        # the clean sync barrier every ack is enforced.
        wal = ens.db.wal
        if wal is not None:
            from ..server.persist import recover_state
            from ..server.store import ZKDatabase
            from .invariants import check_durable_recovery

            before = inj.crash_window_before_fsync()
            floor = wal.materialize_crash(crash_dir,
                                          before_fsync=before)
            h.member_event(
                'sigkill-recover(%s-fsync)'
                % ('before' if before else 'after'), 'ensemble')
            rec = recover_state(crash_dir, trace=client.trace)
            rdb = ZKDatabase()
            rdb.nodes = rec.nodes
            rdb.zxid = rec.zxid
            res.violations.extend(check_durable_recovery(
                h, rdb,
                floor_zxid=floor if wal.sync_errors else None))
        return res
    finally:
        # stop injecting on every exit path (the never-connected early
        # return included), and count fired faults only once quiet —
        # the teardown below must not race new faults into the tally
        # or past close()'s 5 s cap.  Each teardown step is guarded
        # individually: a teardown bug is exactly the kind of failure
        # this tier exists to surface, and it must still arrive with
        # its seed, violations, span ring and member timeline — never
        # abort the campaign or leak the ensemble's listeners.
        inj.stop()
        res.faults = len(inj.fired)
        try:
            await asyncio.wait_for(client.close(), 5)
        except (asyncio.TimeoutError, TimeoutError):
            client.pool.stop()
            res.violations.append('client.close() hung past 5s')
        except Exception as e:
            client.pool.stop()
            res.violations.append('client.close() raised: %r' % (e,))
        else:
            # confirmed close/expiry: ephemerals must not outlive it
            # (only NEW findings — the pre-close check_history pass
            # already reported anything visible before close)
            res.violations.extend(
                v for v in check_ephemerals(h, ens.db)
                if v not in res.violations)
        try:
            await ens.stop()
        except Exception as e:
            res.violations.append('ensemble teardown raised: %r'
                                  % (e,))
        inj.close()
        if ingest is not None:
            ingest.close()
        salvaged = _harvest_blackboxes(wal_dir)
        shutil.rmtree(wal_dir, ignore_errors=True)
        shutil.rmtree(crash_dir, ignore_errors=True)
        _note_open_spans(res, client.trace)
        res.trace = client.trace.dump()
        res.member_rings = {
            'member:%s' % (s.member,): s.trace.dump()
            for s in ens.servers if s.trace is not None}
        # harvested black boxes fill only the gaps: a live member's
        # ring dump is fresher than its on-disk frames
        for key, spans in salvaged.items():
            res.member_rings.setdefault(key, spans)
        res.history = list(h.records)
        # derived, never dual-appended: the history's member records
        # ARE the timeline
        res.member_events = h.member_timeline()
        res.elections = sum(1 for r in h.records
                            if r['kind'] == 'election')


async def run_ensemble_campaign(base_seed: int, schedules: int,
                                ops: int = 12, progress=None,
                                elections: int | None = None,
                                clients: int | None = None,
                                observers: int | None = None,
                                reconfigs: int | None = None,
                                overloads: int | None = None,
                                cached: bool | None = None
                                ) -> list[ScheduleResult]:
    """Run ``schedules`` consecutive seeded ensemble schedules
    starting at ``base_seed`` (``clients`` > 1: the concurrent
    tier, every schedule linearizability-checked; ``observers``
    overrides every plan's non-voting member count; ``reconfigs``
    every plan's forced membership-change count; ``overloads``
    every plan's forced overload-burst count; ``cached`` every
    plan's watch-backed client-cache draw)."""
    out = []
    for i in range(schedules):
        r = await run_ensemble_schedule(base_seed + i, ops=ops,
                                        elections=elections,
                                        clients=clients,
                                        observers=observers,
                                        reconfigs=reconfigs,
                                        overloads=overloads,
                                        cached=cached)
        out.append(r)
        if progress is not None:
            progress(r)
    return out


# ---------------------------------------------------------------------
# Concurrent tier: N clients writing OVERLAPPING keys through the
# full fault vocabulary (kills, elections, partitions, disk faults,
# server_rx), every op recorded as a two-sided interval
# (History.invoke/settle), checked per key by the WGL
# linearizability pass (analysis/linearize.py, invariant 9).  Shared
# by tests/test_linearize.py, tests/test_chaos_ensemble.py and
# ``chaos --tier ensemble --clients N``.
# ---------------------------------------------------------------------

#: The shared key set the concurrent workload contends on — small by
#: design: overlap is what exposes lost updates and stale reads.
CONCURRENT_KEYS = ('/k0', '/k1', '/k2')

#: Per-client workload mix (repetition = weight): read-heavy enough
#: that most writes are observed by somebody else's read.
CONCURRENT_ACTIONS = ('set', 'set', 'set', 'get', 'get', 'get',
                      'exists', 'create', 'create', 'delete',
                      'multi')

#: The churn driver's mix (its own RNG stream — per-client streams
#: and churn draws are fresh, so existing single-client seeds are
#: unperturbed).  'pause' keeps churn sparser than ops.
CONCURRENT_CHURN = ('kill_any', 'kill_leader', 'restart', 'restart',
                    'partition', 'lag', 'migrate',
                    'pause', 'pause', 'pause')


async def run_concurrent_schedule(seed: int, ops: int = 12,
                                  clients: int = 3,
                                  collector=None,
                                  plan: FaultPlan | None = None,
                                  elections: int | None = None,
                                  observers: int | None = None,
                                  reconfigs: int | None = None,
                                  overloads: int | None = None,
                                  cached: bool | None = None
                                  ) -> ScheduleResult:
    """One seeded concurrent schedule: ``clients`` Clients driven
    from per-client RNG streams drawn fresh from the FaultPlan, each
    issuing ``ops`` overlapping create/set/delete/get/exists/multi
    ops on :data:`CONCURRENT_KEYS` while a churn driver kills,
    restarts, partitions and lags members (forced elections
    included).  Reads record their observed data/version/mzxid;
    writes their reply zxid; outcome-unknown ops stay ambiguous.
    After the schedule ``check_history`` replays the history — on a
    concurrent history the binding checks are invariants 2 (zxid
    monotone per session), 5 (watch at-most-once), 7 (elections)
    and 9 (per-key WGL linearizability, pinned to the leader's
    final tree: acked-write loss and torn MULTIs on the shared keys
    surface through that pinning, not through the single-client
    tier's ``ack``/``multi`` records, which this tier does not
    emit) — and the crash-image recovery is checked against the
    zxid-ordered replay prefix
    (:func:`~zkstream_tpu.analysis.linearize.check_recovered_prefix`).
    Rerun any failure with ``python -m zkstream_tpu chaos --tier
    ensemble --clients N --seed S``."""
    from ..client import Client
    from .backoff import BackoffPolicy
    from .invariants import History, check_ephemerals, check_history
    from .pool import DEFAULT_DECOHERENCE_INTERVAL

    import shutil
    import tempfile

    if plan is None:
        plan = FaultPlan.randomized(seed, ops=ops)
    if elections is not None:
        plan.elections = elections
    if observers is not None:
        plan.observers = observers
    if reconfigs is not None:
        plan.reconfigs = reconfigs
    if overloads is not None:
        plan.overloads = overloads
    if cached is not None:
        plan.cached = cached
    inj = FaultInjector(seed, plan.config)
    res = ScheduleResult(seed=seed, tier='ensemble',
                         clients=clients)
    h = History()
    rngs = [random.Random('client/%d/%d' % (seed, ci))
            for ci in range(clients)]
    crng = random.Random('churn/%d' % (seed,))
    #: observer churn rides its own stream — attaching observers
    #: must not shift the per-client or churn draws existing seeds pin
    orng = random.Random('churn-obs/%d' % (seed,))
    #: forced-reconfig draws — fresh stream, same rule
    rrng = random.Random('churn-reconfig/%d' % (seed,))
    #: forced-overload draws — fresh stream, same rule
    ovrng = random.Random('churn-overload/%d' % (seed,))

    wal_dir = tempfile.mkdtemp(prefix='zkchaos-conc-wal-')
    crash_dir = tempfile.mkdtemp(prefix='zkchaos-conc-crash-')
    ens = await EnsembleUnderTest(
        plan.members, wal_dir=wal_dir, durability=plan.durability,
        wal_segment_bytes=plan.wal_segment_bytes, seed=seed,
        observers=plan.observers).start()
    ens.install_faults(inj)

    # config records land in the history with their epoch (the
    # invariant-7 extension replays them) — chained under the
    # ZKEnsemble's own quorum/ballot re-derivation hook
    _prev_cfg_hook = ens.db.on_config_change

    def _on_cfg(phase, entry, _prev=_prev_cfg_hook):
        if _prev is not None:
            _prev(phase, entry)
        h.reconfig(entry[1], entry[2], ens.db.epoch,
                   voters=entry[4], old_voters=entry[3],
                   observers=entry[5])
    ens.db.on_config_change = _on_cfg

    ingest = None
    if plan.ingest_mode != 'none':
        from .ingest import FleetIngest
        # ONE shared ingest across all N clients — shared batched
        # drains are the plane's deployment shape
        ingest = FleetIngest(
            body_mode='host', max_frames=8,
            bypass_bytes=0 if plan.ingest_mode == 'batch' else 16384)
        ingest.faults = inj

    spans: list = [None] * clients
    cls: list = []
    for ci in range(clients):
        c = Client(
            servers=ens.addresses(), shuffle_backends=False,
            session_timeout=plan.session_timeout,
            seed=seed * 131 + ci, faults=inj,
            op_timeout=CAMPAIGN_OP_DEADLINE_MS, collector=collector,
            ingest=ingest, trace_capacity=512,
            # the read plane rides along whenever observers are
            # attached: distributed reads are zxid-gated and the
            # history must still pass check_session_reads
            read_distribution=plan.observers > 0,
            read_subset=plan.read_subset,
            # --cached: every client consults the watch-backed
            # cache first; contended keys make the invalidation
            # stream do real work and check_session_reads holds
            # the no-time-travel rung on every local serve
            cache='/' if plan.cached else False,
            decoherence_interval=(plan.decoherence_ms
                                  if plan.decoherence_ms is not None
                                  else DEFAULT_DECOHERENCE_INTERVAL),
            connect_policy=BackoffPolicy(timeout=400, retries=2,
                                         delay=30, cap=200),
            default_policy=BackoffPolicy(timeout=400, retries=3,
                                         delay=50, cap=400))

        def on_op(span, ci=ci):
            spans[ci] = span
            h.op(span.op, span.path, status=span.status,
                 zxid=span.zxid,
                 session_id=int(span.session_id, 16)
                 if span.session_id else 0,
                 error=span.error)
        c.on_op = on_op
        c.on('expire', lambda c=c: h.session_event(
            'expired', c.session.session_id
            if c.session is not None else 0))
        cls.append(c)

    def note_member(event: str, member) -> None:
        h.member_event(event, member)
        cls[0].trace.note('MEMBER_' + event.upper(),
                          path='member:%s' % (member,),
                          kind='member')

    if ens.coordinator is None:
        plan.elections = 0
        plan.reconfigs = 0
    else:
        def on_elected(member, epoch, dur_ms):
            h.election(member, epoch)
            cls[0].trace.note('ELECTED',
                              path='member:%s' % (member,),
                              kind='member',
                              detail='epoch=%d' % (epoch,),
                              duration_ms=round(dur_ms, 3))
        ens.coordinator.on('elected', on_elected)

    def elections_seen() -> int:
        return sum(1 for r in h.records if r['kind'] == 'election')

    async def force_election() -> None:
        if ens.coordinator is None:
            return
        voter_set = set(ens.voter_idxs())
        need = len(voter_set) // 2 + 1
        while ens.dead and \
                len([j for j in ens.live() if j in voter_set]) - 1 \
                < need:
            back = sorted(ens.dead)[0]
            note_member('restart', back)
            await ens.restart(back)
        lead = ens.leader_idx
        before = elections_seen()
        if lead not in ens.dead:
            note_member('kill-leader', lead)
            await ens.kill(lead)
        deadline = 8.0
        step = 0.02
        while elections_seen() <= before and deadline > 0:
            await asyncio.sleep(step)
            deadline -= step
        if elections_seen() <= before:
            res.violations.append(
                'forced election: no successor elected within 8s '
                'of killing leader %d' % (lead,))

    def _update_resolvers() -> None:
        addrs = ens.config_addresses()
        for c in cls:
            c.update_backends(addrs)

    force_reconfig = _make_force_reconfig(
        ens, res, rrng, note_member, force_election,
        _update_resolvers)

    def _live_address():
        live = ens.live()
        if not live:
            return None
        return ens.servers[live[0]].address

    force_overload = _make_force_overload(
        res, ovrng, note_member, _live_address,
        lambda: cls[ovrng.randrange(len(cls))], cfg=plan.config)

    async def usable(c, timeout: float) -> bool:
        if c.is_connected():
            return True
        try:
            await c.wait_connected(timeout=timeout, fail_fast=False)
            return True
        except (asyncio.TimeoutError, TimeoutError):
            return False

    async def call(ci: int, op: str, path: str | None, factory,
                   data: bytes | None = None,
                   version: int | None = None,
                   subs: list | None = None):
        """One interval-recorded op: invoke before the send, settle
        on every completion path with the observed payload.  Returns
        the op result on ack, None otherwise."""
        call_id = h.invoke(op, path, client=ci, data=data,
                           version=version, subs=subs)
        try:
            out = await asyncio.wait_for(factory(),
                                         CAMPAIGN_OP_HARD_S)
        except (ZKError, ZKProtocolError) as e:
            record_settle_error(res, h, call_id, e)
            return None
        except (asyncio.TimeoutError, TimeoutError):
            res.violations.append(
                'client %d: %s %s hung past the %.1fs hard bound '
                '(deadline %d ms never fired)'
                % (ci, op, path, CAMPAIGN_OP_HARD_S,
                   CAMPAIGN_OP_DEADLINE_MS))
            h.settle(call_id, 'unknown', error='HARD_BOUND')
            return None
        span = spans[ci]
        zxid = span.zxid if span is not None else None
        if op == 'set':
            h.settle(call_id, 'ok', zxid=out.mzxid,
                     version=out.version)
        elif op == 'get':
            got, stat = out
            h.settle(call_id, 'ok', zxid=stat.mzxid,
                     data=bytes(got), version=stat.version)
        elif op == 'exists':
            h.settle(call_id, 'ok', zxid=out.mzxid,
                     version=out.version)
        else:                        # create / delete / multi
            h.settle(call_id, 'ok', zxid=zxid)
        if op not in ('get', 'exists'):
            res.acked += 1
        return out

    fires: list = []
    obs_ver: list[dict] = [{} for _ in range(clients)]

    def pick_version(ci: int, key: str, rng) -> int:
        """Mostly unconditional; 1-in-4 pins the last version this
        client observed — BAD_VERSION under interleaving is a
        definite spec verdict the checker must explain."""
        if rng.random() < 0.25 and key in obs_ver[ci]:
            return obs_ver[ci][key]
        return -1

    async def worker(ci: int) -> None:
        c, rng = cls[ci], rngs[ci]
        if not await usable(c, 10):
            res.violations.append(
                'client %d never connected within 10s (fault '
                'budget %r should have exhausted)'
                % (ci, inj.config.max_faults))
            return
        for step in range(ops):
            await usable(c, 1.5)
            res.ops += 1
            act = rng.choice(CONCURRENT_ACTIONS)
            key = rng.choice(CONCURRENT_KEYS)
            tag = b'c%d-%d' % (ci, step)
            if act == 'create':
                await call(ci, 'create', key,
                           lambda: c.create(key, tag), data=tag)
            elif act == 'set':
                ver = pick_version(ci, key, rng)
                out = await call(
                    ci, 'set', key,
                    lambda: c.set(key, tag, version=ver),
                    data=tag, version=ver)
                if out is not None:
                    obs_ver[ci][key] = out.version
            elif act == 'delete':
                ver = pick_version(ci, key, rng)
                await call(ci, 'delete', key,
                           lambda: c.delete(key, ver),
                           version=ver)
                # whatever the outcome, the cached version is stale
                obs_ver[ci].pop(key, None)
            elif act == 'get':
                out = await call(ci, 'get', key,
                                 lambda: c.get(key))
                if out is not None:
                    obs_ver[ci][key] = out[1].version
            elif act == 'exists':
                out = await call(ci, 'exists', key,
                                 lambda: c.stat(key))
                if out is not None:
                    obs_ver[ci][key] = out.version
            else:                     # multi: atomic across 2 keys
                ka, kb = rng.sample(CONCURRENT_KEYS, 2)
                da, db_ = tag + b'a', tag + b'b'
                if rng.random() < 0.5:
                    subs = [('set_data', ka, da, -1),
                            ('set_data', kb, db_, -1)]
                    mops = [{'op': 'set_data', 'path': ka,
                             'data': da},
                            {'op': 'set_data', 'path': kb,
                             'data': db_}]
                else:
                    subs = [('create', ka, da, None),
                            ('set_data', kb, db_, -1)]
                    mops = [{'op': 'create', 'path': ka,
                             'data': da},
                            {'op': 'set_data', 'path': kb,
                             'data': db_}]
                await call(ci, 'multi', None,
                           lambda: c.multi(mops), subs=subs)

    async def churn() -> None:
        forced = plan.forced_election_steps()
        reconfig_steps = plan.forced_reconfig_steps()
        overload_steps = plan.forced_overload_steps()
        for i in range(ops):
            if i in forced:
                await force_election()
            if i in reconfig_steps:
                await force_reconfig()
            if i in overload_steps:
                await force_overload()
            act = crng.choice(CONCURRENT_CHURN)
            if act == 'kill_any':
                voter_set = set(ens.voter_idxs())
                live = [j for j in ens.live() if j in voter_set]
                if len(live) > 1:
                    victim = crng.choice(live)
                    note_member('kill', victim)
                    await ens.kill(victim)
            elif act == 'kill_leader':
                lead = ens.leader_idx
                if lead not in ens.dead and len(ens.live()) > 1:
                    note_member('kill', lead)
                    await ens.kill(lead)
            elif act == 'restart':
                if ens.dead:
                    back = crng.choice(sorted(ens.dead))
                    note_member('restart', back)
                    await ens.restart(back)
            elif act == 'partition':
                if ens.partition_replica():
                    note_member('partition', 'replica')
                else:
                    note_member('heal', 'replica')
            elif act == 'lag':
                idx = crng.choice([j for j in ens.voter_idxs()
                                   if j != 0])
                lag = crng.choice((None, 0.05, 0.0))
                note_member('lag=%r' % (lag,), idx)
                ens.set_lag(idx, lag)
            elif act == 'migrate':
                note_member('migrate', '-')
                for c in cls:
                    c.pool.rebalance_now()
            if plan.observers:
                # observer lag/partition vocabulary on its own
                # stream (same shape as the single-client tier)
                oact = orng.choice(('none', 'none', 'lag', 'park',
                                    'heal'))
                if oact != 'none':
                    # the CONFIG's observers (one draw either way,
                    # so the stream stays aligned through reconfigs)
                    obs = [j for j in ens.observer_idxs()
                           if j not in ens.removed]
                    pick = orng.randrange(max(1, len(obs)))
                    oidx = obs[pick] if obs else None
                    if oidx is None:
                        pass
                    elif oact == 'lag':
                        olag = orng.choice((0.05, 0.0))
                        note_member('observer-lag=%r' % (olag,),
                                    oidx)
                        ens.set_lag(oidx, olag)
                    elif oact == 'park':
                        note_member('observer-partition', oidx)
                        ens.set_lag(oidx, None)
                    else:
                        note_member('observer-heal', oidx)
                        ens.set_lag(oidx, 0.0)
            # config-probability overload firings ('overload'
            # stream, fault-budget accounted)
            ov_act = inj.overload_action()
            if ov_act is not None:
                await force_overload(ov_act)
            await asyncio.sleep(crng.uniform(0.005, 0.04))

    try:
        for c in cls:
            c.start()
        if not await usable(cls[0], 10):
            res.violations.append(
                'client 0 never connected within 10s (fault budget '
                '%r should have exhausted)'
                % (inj.config.max_faults,))
            return res

        cls[0].watcher(CONCURRENT_KEYS[0]).on(
            'dataChanged',
            lambda data, stat: (fires.append(stat.mzxid),
                                h.watch_fire(CONCURRENT_KEYS[0],
                                             'dataChanged',
                                             stat.mzxid)))

        await asyncio.gather(churn(),
                             *(worker(ci) for ci in range(clients)))

        # -- verification: faults off, ensemble healed --------------
        inj.stop()
        ens.heal()
        for back in sorted(ens.dead):
            note_member('restart', back)
            await ens.restart(back)
        for j in range(1, len(ens.servers)):
            ens.set_lag(j, 0.0)
        if not await usable(cls[0], 10):
            res.violations.append(
                'never reconnected after every member was restarted '
                'and faults stopped')
        else:
            try:
                await asyncio.wait_for(
                    cls[0].sync(CONCURRENT_KEYS[0]),
                    CAMPAIGN_OP_HARD_S)
            except (ZKError, ZKProtocolError,
                    asyncio.TimeoutError, TimeoutError):
                pass                  # sync is a barrier, not an op
        res.watch_fires = len(fires)
        forced_n = len(plan.forced_election_steps())
        if forced_n and elections_seen() < forced_n:
            res.violations.append(
                'plan forced %d election(s) but only %d completed'
                % (forced_n, elections_seen()))
        if plan.forced_reconfig_steps() and \
                not h.of_kind('reconfig'):
            res.violations.append(
                'plan forced %d reconfig step(s) but no config '
                'record landed' % (plan.reconfigs,))
        # the full invariant engine, invariant 9 (per-key WGL
        # linearizability pinned to the final tree) included
        res.violations.extend(check_history(h, ens.db))

        # -- durability: SIGKILL crash image + zxid-ordered replay --
        wal = ens.db.wal
        if wal is not None:
            from ..analysis.linearize import check_recovered_prefix
            from ..server.persist import recover_state
            from ..server.store import ZKDatabase

            before = inj.crash_window_before_fsync()
            wal.materialize_crash(crash_dir, before_fsync=before)
            h.member_event(
                'sigkill-recover(%s-fsync)'
                % ('before' if before else 'after'), 'ensemble')
            rec = recover_state(crash_dir, trace=cls[0].trace)
            rdb = ZKDatabase()
            rdb.nodes = rec.nodes
            rdb.zxid = rec.zxid
            res.violations.extend(check_recovered_prefix(h, rdb))
        return res
    finally:
        inj.stop()
        res.faults = len(inj.fired)
        for ci, c in enumerate(cls):
            try:
                await asyncio.wait_for(c.close(), 5)
            except (asyncio.TimeoutError, TimeoutError):
                c.pool.stop()
                res.violations.append(
                    'client %d close() hung past 5s' % (ci,))
            except Exception as e:
                c.pool.stop()
                res.violations.append(
                    'client %d close() raised: %r' % (ci, e))
        res.violations.extend(
            v for v in check_ephemerals(h, ens.db)
            if v not in res.violations)
        try:
            await ens.stop()
        except Exception as e:
            res.violations.append('ensemble teardown raised: %r'
                                  % (e,))
        inj.close()
        if ingest is not None:
            ingest.close()
        salvaged = _harvest_blackboxes(wal_dir)
        shutil.rmtree(wal_dir, ignore_errors=True)
        shutil.rmtree(crash_dir, ignore_errors=True)
        for c in cls:
            _note_open_spans(res, c.trace)
        res.trace = cls[0].trace.dump()
        res.member_rings = {
            'member:%s' % (s.member,): s.trace.dump()
            for s in ens.servers if s.trace is not None}
        for key, spans in salvaged.items():
            res.member_rings.setdefault(key, spans)
        res.history = list(h.records)
        res.member_events = h.member_timeline()
        res.elections = sum(1 for r in h.records
                            if r['kind'] == 'election')
