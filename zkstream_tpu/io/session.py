"""The durable session layer.

A ``ZKSession`` outlives any one TCP connection: it holds the protocol
state that makes a session resumable — sessionId, password, and the last
zxid seen — and attaches to whichever ``ZKConnection`` is currently live,
re-sending those credentials in the ConnectRequest so the server resumes
rather than recreates the session (reference: lib/zk-session.js:38-480).
That triple *is* the checkpoint/resume mechanism of this system; nothing
touches disk.

States: ``detached / attaching / attached / reattaching / closing /
expired / closed``.  ``reattaching`` implements live-session migration to
a more-preferred backend with revert-on-failure
(reference: lib/zk-session.js:265-339).
"""

from __future__ import annotations

import asyncio
import time

from ..protocol import consts
from ..protocol.errors import ZKProtocolError
from ..utils.events import EventEmitter
from ..utils.fsm import FSM
from ..utils.logging import Logger
from ..utils.metrics import Collector
from .backoff import BackoffPolicy
from .watcher import ZKPersistentWatcher, ZKWatcher

METRIC_ZK_NOTIFICATION_COUNTER = 'zookeeper_notifications'

#: NOTIFICATION wire type -> user-facing watcher event name.
_NOTIFICATION_EVENTS = {
    'CREATED': 'created',
    'DELETED': 'deleted',
    'DATA_CHANGED': 'dataChanged',
    'CHILDREN_CHANGED': 'childrenChanged',
}


class ZKSession(FSM):
    def __init__(self, timeout: int, collector: Collector | None = None,
                 log: Logger | None = None,
                 retry_policy: BackoffPolicy | None = None,
                 seed: int | None = None,
                 trace=None):
        # Child logger; sessionId accretes once the server assigns one
        # (reference: lib/zk-session.js:42-44,179-181).
        self.log = Logger(log).child(component='ZKSession')
        self.conn = None
        self.old_conn = None
        #: Wall-clock ms of the last packet; liveness = a packet within
        #: the session timeout (reference: lib/zk-session.js:77-87).
        self.last_pkt: float | None = None
        self.expiry_timer = EventEmitter()
        self._expiry_handle: asyncio.TimerHandle | None = None
        self._expiry_deadline = 0.0
        self._expiry_at = 0.0      # when the pending handle will fire
        self.watchers: dict[str, ZKWatcher] = {}
        #: Persistent (ADD_WATCH) registrations: path ->
        #: ZKPersistentWatcher.  Unlike the one-shot map above these
        #: carry no re-arm FSMs — the server-side subscription
        #: survives fires — but they ride the same reconnect replay,
        #: upgraded to SET_WATCHES2 (io/connection.py set_watches).
        self.persistent_watchers: dict[str, ZKPersistentWatcher] = {}
        #: The newest zxid any NOTIFICATION stamped (reply zxids live
        #: in ``last_zxid``).  The watch-backed cache's coherence
        #: position (io/cache.py) is the max of the two: the server
        #: never lets a reply overtake an earlier notification on one
        #: connection (server/watchtable.py ordering contract), so
        #: everything at or below that max has already been fanned to
        #: this session's watchers.
        self.notif_zxid = 0
        self.timeout = timeout
        self.last_attach = 0.0
        self.collector = collector if collector is not None else Collector()
        self.collector.counter(METRIC_ZK_NOTIFICATION_COUNTER,
            'Notifications received from ZooKeeper')
        #: Optional TraceRing (utils/trace.py) shared with the owning
        #: client: notification deliveries are recorded into it so a
        #: span dump interleaves requests and watch events.
        self.trace = trace

        #: The session triple that makes resumption possible
        #: (reference: lib/zk-session.js:57-59).
        self.last_zxid = 0
        self.session_id = 0
        self.passwd = b'\x00' * 16

        #: Zxid floor observed OUTSIDE this session's own connection —
        #: the client's read plane (io/pool.py ReadPlane) bumps it
        #: with every distributed read it accepts, and the previous
        #: session's floor carries into it on replacement.  Presented
        #: at every handshake (max with ``last_zxid``) so the
        #: server-side zxid read gate covers what the CLIENT has seen,
        #: not just this connection; kept separate from ``last_zxid``
        #: because that one is also the SET_WATCHES relZxid — raising
        #: it for state observed via OTHER sessions could suppress
        #: catch-up notifications this connection still owes.
        self.gate_floor = 0

        #: Optional override for crash-on-bug escalation (see
        #: :meth:`fatal_error`); None = loud default (loop exception
        #: handler after teardown).
        self.fatal_handler = None

        #: SET_WATCHES re-arm retry backoff: the same jittered policy
        #: object the pool redials under (shared via the client), so
        #: reattach-time churn retries decorrelate the same way.
        self._rearm_backoff = (retry_policy if retry_policy is not None
                               else BackoffPolicy(delay=50,
                                                  cap=2000)).backoff(seed)
        self._rearm_handle: asyncio.TimerHandle | None = None

        self.bind_fsm_metrics(self.collector, 'ZKSession')
        super().__init__('detached')

    def _trace_edge(self, what: str, session_id: int) -> None:
        """Record a session lifecycle edge into the shared span ring
        (when one is attached), so a campaign's trace dump interleaves
        session create/resume/expiry with ops and member events."""
        if self.trace is not None:
            self.trace.note(what, kind='session',
                            session_id='%016x' % (session_id,))

    # -- public accessors --

    def is_attaching(self) -> bool:
        return (self.is_in_state('attaching') or
                self.is_in_state('reattaching'))

    def is_alive(self) -> bool:
        if self.last_pkt is None:
            return False
        delta = time.monotonic() * 1000.0 - self.last_pkt
        return delta < self.timeout

    def attach_and_send_cr(self, conn) -> None:
        """Called by a connection mid-handshake to bind this session to
        it (reference: lib/zk-session.js:89-97)."""
        if not self.is_in_state('detached') and \
           not self.is_in_state('attached'):
            raise RuntimeError('ZKSession.attach_and_send_cr may only be '
                'called in state "attached" or "detached" (is in %s)'
                % (self.get_state(),))
        self.emit('assertAttach', conn)

    def reset_expiry_timer(self) -> None:
        """Push the expiry deadline out by one session timeout.

        Called on every received packet, so it must be cheap: the
        deadline is just a number, and ONE lazy timer chases it — when
        the timer fires early (deadline moved while it slept) it
        reschedules for the remainder instead of expiring.  Avoids a
        cancel + heap insertion per packet (this showed up in the e2e
        runtime profile)."""
        now = time.monotonic()
        self.last_pkt = now * 1000.0
        self._expiry_deadline = now + self.timeout / 1000.0
        if self._expiry_handle is None:
            self._schedule_expiry(self.timeout / 1000.0)
        elif self._expiry_deadline < self._expiry_at:
            # The deadline moved EARLIER (server renegotiated the
            # session timeout down on reattach) — the lazy timer must
            # not fire late, so this rare case does reschedule.
            self._expiry_handle.cancel()
            self._schedule_expiry(self.timeout / 1000.0)

    def _schedule_expiry(self, delay: float) -> None:
        loop = asyncio.get_running_loop()

        def fire():
            self._expiry_handle = None
            remaining = self._expiry_deadline - time.monotonic()
            if remaining > 0:          # deadline moved while sleeping
                self._schedule_expiry(remaining)
            else:
                self.expiry_timer.emit('timeout')
        self._expiry_at = time.monotonic() + delay
        self._expiry_handle = loop.call_later(delay, fire)

    def _cancel_expiry_timer(self) -> None:
        if self._expiry_handle is not None:
            self._expiry_handle.cancel()
            self._expiry_handle = None

    def get_timeout(self) -> int:
        return self.timeout

    def get_connection(self):
        if not self.is_in_state('attached'):
            return None
        return self.conn

    def get_session_id(self) -> str:
        return '%016x' % (self.session_id,)

    def close(self) -> None:
        self.emit('closeAsserted')

    def fatal_error(self, exc: BaseException) -> None:
        """Crash-on-bug escalation for self-check failures (missed
        wakeups, unmatchable notifications).  The reference throws to
        kill the process (lib/zk-session.js:916-919); here the loud
        default is: log critical, tear the session down through the
        terminal ``expired`` path (connection destroyed, ``expire``/
        ``failed`` surfaced to the client), and hand the exception to
        the event loop's exception handler so an unconfigured process
        prints a traceback.  Installing a ``fatalError`` listener makes
        the policy configurable — teardown still happens, but the loop
        handler is not invoked."""
        self.log.fatal('fatal self-check failure: %s', exc)
        self.emit('fatalError', exc)
        if not (self.is_in_state('expired') or
                self.is_in_state('closed')):
            self._transition('expired')
        if self.fatal_handler is not None:
            self.fatal_handler(exc)
        else:
            asyncio.get_running_loop().call_exception_handler({
                'message': 'zkstream fatal self-check failure '
                           '(crash-on-bug)',
                'exception': exc,
            })

    # -- states --

    def state_detached(self, S) -> None:
        if self.conn is not None:
            self.conn.destroy()
        self.conn = None

        def on_attach(conn):
            self.conn = conn
            S.goto_state('attaching')
        S.on(self, 'assertAttach', on_attach)
        S.on(self, 'closeAsserted', lambda: S.goto_state('closed'))
        S.on(self.expiry_timer, 'timeout', lambda: S.goto_state('expired'))
        self.watchers_disconnected()

    def state_attaching(self, S) -> None:
        def on_conn_dead(*args):
            # The connect attempt died.  A live session keeps trying; a
            # session that had an id and ran out the clock is expired
            # (reference: lib/zk-session.js:150-159).
            if self.is_alive():
                S.goto_state('detached')
            elif self.session_id != 0:
                S.goto_state('expired')
            else:
                S.goto_state('detached')
        S.on(self.conn, 'error', on_conn_dead)
        S.on(self.conn, 'close', on_conn_dead)

        def on_packet(pkt):
            if pkt['sessionId'] == 0:
                # The server zeroed the id: our session is gone
                # (reference: lib/zk-session.js:170-173).
                S.goto_state('expired')
                return
            verb = 'resumed' if self.session_id != 0 else 'created'
            self.log = self.log.child(
                sessionId='%016x' % (pkt['sessionId'],))
            self.log.info('%s zookeeper session with timeout %d ms',
                          verb, pkt['timeOut'])
            self._trace_edge('SESSION_' + verb.upper(),
                             pkt['sessionId'])
            self.timeout = pkt['timeOut']
            self.session_id = pkt['sessionId']
            self.passwd = pkt['passwd']
            self.reset_expiry_timer()
            S.goto_state('attached')
        S.on(self.conn, 'packet', on_packet)

        S.on(self.expiry_timer, 'timeout', lambda: S.goto_state('expired'))
        S.on(self, 'closeAsserted', lambda: S.goto_state('closing'))

        self.conn.send({
            'protocolVersion': consts.PROTOCOL_VERSION,
            'lastZxidSeen': max(self.last_zxid, self.gate_floor),
            'timeOut': self.timeout,
            'sessionId': self.session_id,
            'passwd': self.passwd,
        })

    def state_attached(self, S) -> None:
        self.last_attach = time.monotonic()

        def on_conn_dead(*args):
            if self.is_alive():
                S.goto_state('detached')
            else:
                S.goto_state('expired')
        S.on(self.conn, 'close', on_conn_dead)
        S.on(self.conn, 'error', on_conn_dead)

        def on_packet(pkt):
            self.reset_expiry_timer()
            if pkt['opcode'] != 'NOTIFICATION':
                # Track the max zxid seen: it anchors both session
                # resumption and watch catch-up
                # (reference: lib/zk-session.js:229-235).
                if pkt['zxid'] > self.last_zxid:
                    self.last_zxid = pkt['zxid']
                return
            self.process_notification(pkt)
        S.on(self.conn, 'packet', on_packet)

        S.on(self.expiry_timer, 'timeout', lambda: S.goto_state('expired'))
        S.on(self, 'closeAsserted', lambda: S.goto_state('closing'))

        def on_conn_state(st):
            if st == 'connected':
                if self.old_conn is not None:
                    self.old_conn.destroy()
                    self.old_conn = None
                self.resume_watches()
        S.on(self.conn, 'stateChanged', on_conn_state)

        def on_attach(conn):
            self.old_conn = self.conn
            self.conn = conn
            S.goto_state('reattaching')
        S.on(self, 'assertAttach', on_attach)

    def state_reattaching(self, S) -> None:
        """Move a live session to a more-preferred backend, reverting to
        the old connection on failure (reference:
        lib/zk-session.js:265-339)."""
        assert self.old_conn is not None, 'reattaching requires old_conn'

        def on_packet(pkt):
            if pkt['sessionId'] == 0:
                revert()
                return
            self.log.info('moved zookeeper session to more preferred '
                          'backend (%s) with timeout %d ms',
                          self.conn.backend.key, pkt['timeOut'])
            self._trace_edge('SESSION_MIGRATED', pkt['sessionId'])
            self.timeout = pkt['timeOut']
            self.session_id = pkt['sessionId']
            self.passwd = pkt['passwd']
            self.reset_expiry_timer()
            self.watchers_disconnected()
            S.goto_state('attached')
        S.on(self.conn, 'packet', on_packet)

        def revert(*args):
            if self.is_alive() and self.old_conn.is_in_state('connected'):
                self.log.warning('reverted move of session to new '
                                 'backend (%s)', self.conn.backend.key)
                self.conn = self.old_conn
                self.old_conn = None
                S.goto_state('attached')
            elif self.is_alive():
                self.old_conn.destroy()
                self.old_conn = None
                S.goto_state('detached')
            else:
                self.old_conn.close()
                self.old_conn = None
                S.goto_state('expired')
        S.on(self.conn, 'error', revert)
        S.on(self.conn, 'close', revert)
        S.on(self.expiry_timer, 'timeout', revert)

        def on_close_asserted():
            self.old_conn.close()
            self.old_conn = None
            S.goto_state('closing')
        S.on(self, 'closeAsserted', on_close_asserted)

        self.log.debug('attempting to move zookeeper session from %s '
                       'to %s', self.old_conn.backend.key,
                       self.conn.backend.key)

        self.conn.send({
            'protocolVersion': consts.PROTOCOL_VERSION,
            'lastZxidSeen': max(self.last_zxid, self.gate_floor),
            'timeOut': self.timeout,
            'sessionId': self.session_id,
            'passwd': self.passwd,
        })

    def state_closing(self, S) -> None:
        S.on(self.conn, 'error', lambda *a: S.goto_state('closed'))
        S.on(self.conn, 'close', lambda: S.goto_state('closed'))
        S.on(self.expiry_timer, 'timeout', lambda: S.goto_state('closed'))
        self.conn.close()

    def state_expired(self, S) -> None:
        if self.conn is not None:
            self.conn.destroy()
        self.conn = None
        self._cancel_expiry_timer()
        self._cancel_rearm_retry()
        self._trace_edge('SESSION_EXPIRED', self.session_id)
        self._drop_persistent()
        self.log.warning('ZK session expired')

    def state_closed(self, S) -> None:
        if self.conn is not None:
            self.conn.destroy()
        self.conn = None
        self._cancel_expiry_timer()
        self._cancel_rearm_retry()
        self._drop_persistent()
        self.log.info('ZK session closed')

    # -- watcher plumbing --

    def _drop_persistent(self) -> None:
        """Terminal teardown (expired/closed): the server-side
        registrations die with the session — surface the loss so
        subscribers re-create them on the replacement session."""
        pers = self.persistent_watchers
        if not pers:
            return
        self.persistent_watchers = {}
        for pw in pers.values():
            pw._lost()

    def watchers_disconnected(self) -> None:
        """Tell every armed watch event it is on the auto-resume list
        (reference: lib/zk-session.js:377-387)."""
        for w in list(self.watchers.values()):
            for event in w.events():
                event.disconnected()

    def process_notification(self, pkt: dict) -> None:
        """Dispatch a NOTIFICATION to the right path's watcher
        (reference: lib/zk-session.js:389-419)."""
        if pkt['state'] != 'SYNC_CONNECTED':
            self.log.warning('received notification with bad state %s',
                             pkt['state'])
            return
        evt = _NOTIFICATION_EVENTS[pkt['type']]
        self.log.trace('notification %s for %s', evt, pkt['path'])
        self.collector.get_collector(
            METRIC_ZK_NOTIFICATION_COUNTER).increment({'event': evt})
        if self.trace is not None:
            self.trace.note('NOTIFICATION', pkt['path'],
                            zxid=self.last_zxid, kind='notification',
                            session_id=self.get_session_id())
        watcher = self.watchers.get(pkt['path'])
        if watcher is not None:
            watcher.notify(evt)
        if self.persistent_watchers:
            zxid = pkt.get('zxid', 0)
            if zxid > self.notif_zxid:
                self.notif_zxid = zxid
            self._dispatch_persistent(evt, pkt['path'], zxid)

    def _dispatch_persistent(self, evt: str, path: str,
                             zxid: int) -> None:
        """Fan one notification to the persistent registrations it
        matches: the exact node, plus — for everything except
        childrenChanged — every recursive registration on an ancestor
        (mirrors the server's ancestor-prefix walk,
        server/watchtable.py _persistent_subs)."""
        pers = self.persistent_watchers
        w = pers.get(path)
        if w is not None:
            if evt != 'childrenChanged':
                w._notify(evt, path, zxid)
            elif not w.recursive:
                # recursive subscribers never get childrenChanged:
                # they see the child's own created/deleted instead
                w._notify(evt, path, zxid)
        if evt == 'childrenChanged':
            return
        p = path
        while len(p) > 1:
            i = p.rfind('/')
            p = p[:i] if i > 0 else '/'
            w = pers.get(p)
            if w is not None and w.recursive:
                w._notify(evt, path, zxid)

    def resume_watches(self) -> None:
        """After reconnect, batch every watch event in 'resuming' into
        one SET_WATCHES anchored at the last zxid seen, then release them
        (reference: lib/zk-session.js:421-471)."""
        events = {'dataChanged': [], 'createdOrDestroyed': [],
                  'childrenChanged': []}
        all_evts = []
        count = 0
        for path, w in self.watchers.items():
            cod = False
            for event in w.events():
                if not event.is_in_state('resuming'):
                    continue
                evt = event.get_event()
                if evt == 'createdOrDeleted':
                    if cod:
                        continue
                    events['createdOrDestroyed'].append(path)
                    count += 1
                    cod = True
                elif evt == 'dataChanged':
                    events['dataChanged'].append(path)
                    count += 1
                elif evt == 'childrenChanged':
                    events['childrenChanged'].append(path)
                    count += 1
                else:
                    raise AssertionError('unknown event: %s' % (evt,))
                all_evts.append(event)
        opcode = 'SET_WATCHES'
        pers_list: list[ZKPersistentWatcher] = []
        if self.persistent_watchers:
            # persistent registrations always replay — arming is
            # unconditional (nothing to consume server-side), and a
            # registration made while disconnected arms here for the
            # first time
            opcode = 'SET_WATCHES2'
            events['persistent'] = []
            events['persistentRecursive'] = []
            for path, pw in self.persistent_watchers.items():
                events['persistentRecursive' if pw.recursive
                       else 'persistent'].append(path)
                pers_list.append(pw)
                count += 1
        if count < 1:
            return
        zxid = self.last_zxid
        self.log.info('re-arming %d node watchers at zxid %x', count, zxid)

        def done(err):
            if err is not None:
                # Injected/real churn killed the SET_WATCHES round trip.
                # The events stay in 'resuming' (they re-batch on the
                # next reconnect), and — when the failure was transient
                # and this connection is still serving — a jittered
                # retry re-arms them without waiting for another
                # disconnect.  Without this, watches could stay dark
                # until the next unrelated reconnect: a dropped-event
                # window.
                self.log.warning('SET_WATCHES failed during watch '
                                 'resumption: %s', err)
                self._schedule_rearm_retry()
                return
            self._rearm_backoff.reset()
            for event in all_evts:
                event.resume()
            for pw in pers_list:
                # the gap is closed server-side; derived state
                # (io/cache.py) resyncs on this edge
                pw._resumed()
        try:
            self.conn.set_watches(events, zxid, done, opcode)
        except ZKProtocolError as e:
            # The connection died between 'connected' and this call
            # (reattach churn): not a bug, the events stay 'resuming'
            # and the retry path below re-arms them.
            self.log.warning('connection lost before SET_WATCHES '
                             'could be sent: %s', e)
            self._schedule_rearm_retry()

    def _schedule_rearm_retry(self) -> None:
        """Retry :meth:`resume_watches` after a jittered backoff delay,
        if the session is still attached over a usable connection by
        then.  One timer at a time; re-arm churn cannot stack timers."""
        if self._rearm_handle is not None:
            return
        delay_s = self._rearm_backoff.next_delay() / 1000.0
        loop = asyncio.get_running_loop()

        def fire():
            self._rearm_handle = None
            if not self.is_in_state('attached'):
                return
            conn = self.conn
            if conn is None or not conn.is_in_state('connected'):
                return
            self.resume_watches()
        self._rearm_handle = loop.call_later(delay_s, fire)

    def _cancel_rearm_retry(self) -> None:
        if self._rearm_handle is not None:
            self._rearm_handle.cancel()
            self._rearm_handle = None

    def watcher(self, path: str) -> ZKWatcher:
        """One cached ZKWatcher per path
        (reference: lib/zk-session.js:473-480)."""
        w = self.watchers.get(path)
        if w is None:
            w = ZKWatcher(self, path)
            self.watchers[path] = w
        return w

    def persistent_watcher(self, path: str,
                           recursive: bool) -> ZKPersistentWatcher:
        """One persistent registration per path.  Registering here
        alone does NOT arm the server side — the caller sends
        ADD_WATCH (Client.add_watch) — but once registered the path
        rides every reconnect's SET_WATCHES2 replay, so a
        registration that raced a disconnect still arms.  Asking for
        the same path under a different mode re-homes it (last mode
        wins, matching the server's re-arm semantics)."""
        w = self.persistent_watchers.get(path)
        if w is None:
            w = ZKPersistentWatcher(self, path, recursive)
            self.persistent_watchers[path] = w
        elif w.recursive is not recursive:
            w.recursive = recursive
        return w

    def drop_persistent_watcher(self, path: str) -> None:
        self.persistent_watchers.pop(path, None)
