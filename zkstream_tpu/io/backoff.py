"""Retry backoff: capped exponential delay with full jitter.

The reference's cueball recovery objects carry fixed ``{timeout,
retries, delay}`` numbers (reference: lib/client.js:96-107).  Fixed
delays are exactly wrong at fleet scale: when an ensemble member dies
under heavy traffic, every client that was attached to it redials on
the same fixed cadence — a correlated reconnect storm that lands on the
survivors in synchronized waves.  This module upgrades the policy to
capped exponential backoff with *full jitter* (each delay drawn
uniformly from ``[0, min(cap, delay * factor**attempt)]``), which is
the standard storm-decorrelation scheme, while keeping the reference's
field names so existing callers (and tests) construct policies
unchanged.

Two classes, deliberately split:

- :class:`BackoffPolicy` — the immutable description (dataclass).  The
  old ``RecoveryPolicy`` name is kept as an alias in ``io/pool.py``.
- :class:`Backoff` — one retry sequence's mutable state (attempt
  counter + RNG).  ``next_delay()`` advances it, ``reset()`` is called
  on success.  It never sleeps itself: callers own their sleeps, which
  is what makes the policy unit-testable against a fake clock with no
  real delays (tests/test_backoff.py).

Seeding: a ``Backoff`` built with a seed is fully deterministic — the
chaos harness (io/faults.py) relies on this to make fault campaigns
reproducible from a single integer.
"""

from __future__ import annotations

import dataclasses
import random


@dataclasses.dataclass
class BackoffPolicy:
    """Connect/retry policy (reference: lib/client.js:96-107, plus the
    cap/factor/jitter upgrade).

    ``timeout`` is the per-attempt budget in ms; ``retries`` the number
    of attempts under the *initial* policy before a pool reports
    ``failed``; ``delay`` the base delay (the attempt-0 ceiling) in ms.
    ``cap`` bounds the exponential growth; ``jitter=False`` restores
    the reference's fixed-delay behavior (useful for tests that assert
    exact timing)."""

    timeout: int = 5000
    retries: int = 3
    delay: int = 1000
    cap: int = 30000
    factor: float = 2.0
    jitter: bool = True

    def ceiling(self, attempt: int) -> float:
        """The delay ceiling for ``attempt`` (0-based), in ms."""
        if attempt < 0:
            raise ValueError('attempt must be >= 0')
        # Cap the exponent too: delay * factor**attempt overflows to
        # inf for large attempt counts long after the cap has won.
        ceil = float(self.delay)
        for _ in range(attempt):
            ceil *= self.factor
            if ceil >= self.cap:
                return float(self.cap)
        return min(ceil, float(self.cap))

    def backoff(self, seed: int | None = None) -> 'Backoff':
        """A fresh retry sequence under this policy."""
        return Backoff(self, seed=seed)


class Backoff:
    """One retry sequence: attempt counter + jitter RNG.

    ``next_delay()`` returns the next delay in **ms** and advances the
    attempt counter; ``reset()`` rewinds to attempt 0 (call it when the
    guarded operation succeeds).  With ``policy.jitter`` the delay is
    drawn uniformly from ``[0, ceiling(attempt)]`` (full jitter);
    without, it is exactly the ceiling (the legacy fixed schedule when
    ``factor`` is 1)."""

    def __init__(self, policy: BackoffPolicy, seed: int | None = None):
        self.policy = policy
        self.attempt = 0
        self._rng = random.Random(seed)

    def next_delay(self) -> float:
        ceil = self.policy.ceiling(self.attempt)
        self.attempt += 1
        if not self.policy.jitter:
            return ceil
        return self._rng.uniform(0.0, ceil)

    def peek_ceiling(self) -> float:
        """The ceiling the *next* ``next_delay()`` will draw under."""
        return self.policy.ceiling(self.attempt)

    def reset(self) -> None:
        self.attempt = 0
