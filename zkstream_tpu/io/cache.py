"""The client cache plane: a watch-backed read cache in the
Curator-cache shape, built on the persistent-recursive watch family
(ADD_WATCH, opcode 106).

One ``CachePlane`` subscribes each configured subtree root ONCE with a
PERSISTENT_RECURSIVE watch and then fills read-through: every server
read the client performs under a subscribed root deposits its reply
(data / stat / children), and every later read of the same path is
served locally — single-digit microseconds, zero server round trips —
until the notification stream invalidates it.  In a read-mostly fleet
the server's read QPS collapses to the invalidation rate.

Coherence contract (README "Client cache plane")
------------------------------------------------

A cached read must satisfy the same session-view rules as a server
read — ``check_session_reads`` (analysis/linearize.py) and invariant 9
apply to it verbatim.  Three mechanisms make that hold:

1. **Ordering.**  The server never lets a reply overtake an earlier
   notification on one connection (server/watchtable.py's ordering
   contract), so by the time the session has seen a reply stamped
   ``zxid Z``, every invalidation at or below ``Z`` for this
   connection has already been applied to the cache (notifications
   are processed synchronously, in arrival order, before any awaiting
   read coroutine resumes).  The cache's coherence position is
   therefore ``pos = max(last notification zxid, session.last_zxid)``.

2. **The serve gate.**  A cached read is served only while
   ``pos >= Client.last_seen_zxid()``.  The client floor can outrun
   the watch stream only through the read plane's distributed replies
   (other connections); when it does, cached reads fall through to
   real server reads — which the zxid read gate already covers —
   until the watch stream catches up.  A served entry also notes its
   fill zxid into the client floor, exactly like a server read.

3. **The fill gate.**  A reply deposits into the cache only if its
   zxid is at or above the last notification position: a distributed
   read off a lagging member must not resurrect a value the
   notification stream already invalidated.

Gaps are never silent.  A disconnect marks every subtree stale (reads
fall through); reconnect replays the registrations via SET_WATCHES2
and the ``'resumed'`` edge drops the subtree's entries — anything may
have changed while dark, so the cache refetches rather than trusts.
A session that dies outright (``'lost'``) drops everything and
re-subscribes on the replacement session.  The server holds the same
line: an overloaded member EVICTS a persistent-watch subscriber
rather than dropping its notification (io/overload.py
``allow_persistent_notification``), so a surviving connection implies
an unbroken invalidation stream.

Knobs: ``Client(cache=...)`` beats ``ZKSTREAM_CACHE`` (a subtree
root, ``:``-separated for several, or ``1`` for ``/``);
``ZKSTREAM_NO_CACHE=1`` is the kill switch.

Observability: ``zookeeper_cache_hits`` / ``_misses`` (by op),
``zookeeper_cache_invalidations`` (by event), and
``zookeeper_cache_staleness_ms`` — the age of each served entry.
"""

from __future__ import annotations

import asyncio
import os
import time

from ..utils.aio import ambient_loop

METRIC_CACHE_HITS = 'zookeeper_cache_hits'
METRIC_CACHE_MISSES = 'zookeeper_cache_misses'
METRIC_CACHE_INVALIDATIONS = 'zookeeper_cache_invalidations'
METRIC_CACHE_STALENESS = 'zookeeper_cache_staleness_ms'

#: Entry-age buckets (ms): the interesting band is whether read-mostly
#: entries live long enough to amortize their one fill round trip.
STALENESS_BUCKETS = (0.1, 1.0, 10.0, 100.0, 1000.0, 10000.0,
                     60000.0, 600000.0)

#: Opcodes the plane serves and fills.  GET_ACL stays uncached (ACL
#: changes carry no notification type to invalidate on).
_CACHED_OPS = frozenset(('GET_DATA', 'EXISTS', 'GET_CHILDREN2'))


def cache_roots_default() -> list[str] | None:
    """Process-wide default subtree roots (env resolution): None when
    the plane is off."""
    if os.environ.get('ZKSTREAM_NO_CACHE') == '1':
        return None
    raw = os.environ.get('ZKSTREAM_CACHE', '')
    if not raw:
        return None
    if raw == '1':
        return ['/']
    roots = [r for r in raw.split(':') if r.startswith('/')]
    return roots or None


def _parent(path: str) -> str:
    i = path.rfind('/')
    return path[:i] if i > 0 else '/'


class _Root:
    """One subscribed subtree root's replication state."""

    __slots__ = ('path', 'armed', 'stale', 'arming')

    def __init__(self, path: str) -> None:
        self.path = path
        #: True while a server-side PERSISTENT_RECURSIVE registration
        #: is live for this root on the current session.
        self.armed = False
        #: True while the invalidation stream has a known gap
        #: (disconnected); serving stops until the resync edge.
        self.stale = False
        #: An arm round trip is in flight (dedup for the connect
        #: retrigger).
        self.arming = False


class CachePlane:
    """The client-owned watch-backed read cache.  Constructed by
    :class:`~.client.Client` when a cache root is configured; consult
    via :meth:`lookup`, deposit via :meth:`fill` — both called from
    ``Client._read_request`` so every read path shares one contract.
    """

    def __init__(self, client, roots: list[str],
                 collector=None) -> None:
        self.client = client
        self.roots: dict[str, _Root] = {
            r: _Root(r) for r in roots}
        #: Per-kind entry maps: path -> (payload..., zxid, fill time).
        self._data: dict[str, tuple] = {}
        self._stats: dict[str, tuple] = {}
        self._children: dict[str, tuple] = {}
        #: The newest zxid any invalidation stamped — the notification
        #: half of the coherence position (the reply half is the live
        #: session's ``last_zxid``).
        self._pos = 0
        #: Plain counters for bench/campaign summaries (the metric
        #: series below carry the labelled breakdown).
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self._hits_c = None
        self._miss_c = None
        self._inval_c = None
        self._staleness = None
        if collector is not None:
            self._hits_c = collector.counter(
                METRIC_CACHE_HITS,
                'Reads served from the client cache, by opcode')
            self._miss_c = collector.counter(
                METRIC_CACHE_MISSES,
                'Cache-eligible reads that fell through to the '
                'server, by opcode')
            self._inval_c = collector.counter(
                METRIC_CACHE_INVALIDATIONS,
                'Cache entries dropped by watch notifications, '
                'by event')
            self._staleness = collector.histogram(
                METRIC_CACHE_STALENESS,
                'Age of served cache entries, milliseconds',
                buckets=STALENESS_BUCKETS)
        self._started = False
        self._closed = False
        self._tasks: set = set()

    # -- lifecycle --

    def start(self) -> None:
        """Hook the client's connectivity edges and arm on the first
        connect.  Separate from __init__ for the same reason
        Client.start is: the caller picks the running loop."""
        if self._started:
            return
        self._started = True
        self.client.on('connect', self._on_connect)
        self.client.on('disconnect', self._on_disconnect)

    def close(self) -> None:
        self._closed = True
        for t in list(self._tasks):
            t.cancel()

    # -- connectivity edges --

    def _on_connect(self) -> None:
        if self._closed:
            return
        for root in self.roots.values():
            if not root.armed and not root.arming:
                root.arming = True
                t = ambient_loop().create_task(self._arm(root))
                self._tasks.add(t)
                t.add_done_callback(self._tasks.discard)

    def _on_disconnect(self) -> None:
        # the invalidation stream has a gap from here until the
        # replay's 'resumed' edge: stop serving, keep the entries
        # (the resync drops them — cheaper than dropping twice when
        # the reconnect never comes before close)
        for root in self.roots.values():
            root.stale = True

    async def _arm(self, root: _Root) -> None:
        """One arm round trip: register + ADD_WATCH the root.  On
        failure the registration (if it landed) still rides the next
        reconnect's SET_WATCHES2 replay, and the next 'connect' edge
        retries the round trip."""
        try:
            w = await self.client.add_watch(root.path, recursive=True)
        except asyncio.CancelledError:
            raise
        except Exception:
            sess = self.client.session
            w = (None if sess is None
                 else sess.persistent_watchers.get(root.path))
            if w is None:
                root.arming = False
                return
            # registered but the round trip failed: the replay will
            # arm it — hook the emitter now and wait for 'resumed'
            self._hook(w, root)
            root.arming = False
            return
        finally:
            root.arming = False
        self._hook(w, root)
        self._resync(root)

    def _hook(self, w, root: _Root) -> None:
        """Attach this plane to one session-owned watcher emitter.
        A fresh emitter exists per session, so re-hooking after
        session replacement cannot double-subscribe."""
        w.on('created', lambda p, z: self._invalidate('created', p, z))
        w.on('deleted', lambda p, z: self._invalidate('deleted', p, z))
        w.on('dataChanged',
             lambda p, z: self._invalidate('dataChanged', p, z))
        w.on('resumed', lambda: self._resync(root))
        w.on('lost', lambda: self._lost(root))

    # -- the invalidation stream --

    def _invalidate(self, evt: str, path: str, zxid: int) -> None:
        if zxid > self._pos:
            self._pos = zxid
        # invariant-9: the notification IS an observation of member
        # state at ``zxid`` — raise the client floor so no later
        # server read (distributed or primary) can show older state
        self.client._note_read_floor(zxid)
        n = 0
        if self._data.pop(path, None) is not None:
            n += 1
        if self._stats.pop(path, None) is not None:
            n += 1
        if self._children.pop(path, None) is not None:
            n += 1
        if evt != 'dataChanged':
            # membership changed: the parent's child list AND its
            # stat (pzxid/cversion/numChildren) are both stale
            parent = _parent(path)
            if self._children.pop(parent, None) is not None:
                n += 1
            if self._stats.pop(parent, None) is not None:
                n += 1
        if n:
            self.invalidations += n
            if self._inval_c is not None:
                self._inval_c.increment({'event': evt}, n)

    def _resync(self, root: _Root) -> None:
        """The registration is live again after a gap (reconnect
        replay, or a fresh arm): anything cached under the root may
        have changed while the stream was dark — drop it all and
        refill read-through.  Never silent staleness."""
        self._drop_subtree(root.path)
        sess = self.client.session
        if sess is not None and sess.last_zxid > self._pos:
            # entries filled from here on are newer than anything the
            # dark window could have invalidated
            self._pos = sess.last_zxid
        root.armed = True
        root.stale = False

    def _lost(self, root: _Root) -> None:
        """The owning session died terminally: the server-side
        registration is gone.  Drop state; the client 'connect' edge
        on the replacement session re-subscribes."""
        root.armed = False
        root.stale = True
        self._drop_subtree(root.path)

    def _drop_subtree(self, rootpath: str) -> None:
        for m in (self._data, self._stats, self._children):
            if rootpath == '/':
                m.clear()
                continue
            prefix = rootpath + '/'
            for p in [p for p in m
                      if p == rootpath or p.startswith(prefix)]:
                del m[p]

    # -- the read path (Client._read_request calls in) --

    def _covering_root(self, path: str) -> _Root | None:
        for root in self.roots.values():
            if root.path == '/' or path == root.path \
                    or path.startswith(root.path + '/'):
                return root
        return None

    def _coherent(self) -> bool:
        sess = self.client.session
        if sess is None:
            return False
        pos = self._pos
        if sess.last_zxid > pos:
            pos = sess.last_zxid
        return pos >= self.client.last_seen_zxid()

    def lookup(self, opcode: str, path: str) -> dict | None:
        """Serve one read locally, or None to fall through.  The
        returned dict is shaped exactly like the server reply the
        caller would otherwise get (plus ``'cached': True``)."""
        if opcode not in _CACHED_OPS:
            return None
        root = self._covering_root(path)
        if root is None:
            return None
        if not root.armed or root.stale or not self._coherent():
            self._miss(opcode)
            return None
        if opcode == 'GET_DATA':
            e = self._data.get(path)
            if e is None:
                self._miss(opcode)
                return None
            data, stat, zxid, t0 = e
            out = {'opcode': opcode, 'data': data, 'stat': stat,
                   'zxid': zxid, 'cached': True}
        elif opcode == 'EXISTS':
            e = self._stats.get(path)
            if e is None:
                # a data entry carries the same stat
                d = self._data.get(path)
                if d is None:
                    self._miss(opcode)
                    return None
                e = (d[1], d[2], d[3])
            stat, zxid, t0 = e
            out = {'opcode': opcode, 'stat': stat, 'zxid': zxid,
                   'cached': True}
        else:                              # GET_CHILDREN2
            e = self._children.get(path)
            if e is None:
                self._miss(opcode)
                return None
            children, stat, zxid, t0 = e
            out = {'opcode': opcode, 'children': list(children),
                   'stat': stat, 'zxid': zxid, 'cached': True}
        # a cached read is an observation like any other: it anchors
        # the session floor at its fill zxid (<= coherence position,
        # so serving stays enabled)
        self.client._note_read_floor(zxid)
        self.hits += 1
        if self._hits_c is not None:
            self._hits_c.increment({'op': opcode})
        if self._staleness is not None:
            self._staleness.observe(
                (time.monotonic() - t0) * 1000.0)
        return out

    def _miss(self, opcode: str) -> None:
        self.misses += 1
        if self._miss_c is not None:
            self._miss_c.increment({'op': opcode})

    def fill(self, opcode: str, path: str, pkt: dict) -> None:
        """Deposit one server reply.  Gated on the notification
        position: a reply off a member behind an invalidation this
        plane already applied must not resurrect the dead value."""
        if opcode not in _CACHED_OPS:
            return
        root = self._covering_root(path)
        if root is None or not root.armed or root.stale:
            return
        zxid = pkt.get('zxid', 0)
        if zxid < self._pos:
            return
        now = time.monotonic()
        if opcode == 'GET_DATA':
            self._data[path] = (pkt['data'], pkt['stat'], zxid, now)
        elif opcode == 'EXISTS':
            self._stats[path] = (pkt['stat'], zxid, now)
        else:                              # GET_CHILDREN2
            self._children[path] = (list(pkt['children']),
                                    pkt['stat'], zxid, now)

    # -- warm-up --

    async def prime(self, root: str | None = None,
                    max_nodes: int = 100000) -> int:
        """Walk a subscribed subtree once through the normal read
        path, depositing every node's children and data — after this
        a read-mostly workload starts at its steady-state hit ratio
        instead of paying one fill miss per path.  Returns the number
        of nodes visited; bounded by ``max_nodes``."""
        from ..protocol.errors import ZKError
        targets = ([root] if root is not None
                   else list(self.roots))
        seen = 0
        for r in targets:
            stack = [r]
            while stack and seen < max_nodes:
                p = stack.pop()
                try:
                    children, _stat = await self.client.list(p)
                    await self.client.get(p)
                except ZKError:
                    continue          # raced a delete: fine
                seen += 1
                base = p if p != '/' else ''
                stack.extend(base + '/' + c for c in children)
        return seen

    def stats(self) -> dict:
        """Plane summary for bench/campaign reporting."""
        return {'hits': self.hits, 'misses': self.misses,
                'invalidations': self.invalidations,
                'entries': (len(self._data) + len(self._stats)
                            + len(self._children)),
                'armed': sum(1 for r in self.roots.values()
                             if r.armed and not r.stale)}
