"""``python -m zkstream_tpu`` entry point (see cli.py)."""

import sys

from .cli import main

sys.exit(main())
