"""Error classes for the zkstream_tpu client.

Mirrors the reference's four error classes (reference: lib/errors.js:9-54):
transport/framing problems, ping timeouts, not-connected, and server-side
operation errors.
"""

from __future__ import annotations

from .consts import ERR_TEXT, ErrCode


class ZKProtocolError(Exception):
    """A transport- or framing-level protocol problem (bad length prefix,
    undecodable packet, version mismatch...).  ``code`` is a short
    machine-readable string such as ``'BAD_LENGTH'`` or ``'BAD_DECODE'``
    (reference: lib/errors.js:19-28)."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class ZKPingTimeoutError(ZKProtocolError):
    """The server failed to answer a keep-alive ping in time
    (reference: lib/errors.js:30-35)."""

    def __init__(self) -> None:
        super().__init__('PING_TIMEOUT', 'Timed out while waiting for ping '
            'reply from ZK server')


class ZKDeadlineError(ZKProtocolError):
    """A client operation exceeded its per-request deadline.  Typed so
    callers can distinguish "the connection is wedged / the server is
    not answering" (retryable, outcome unknown) from a definite server
    verdict; ``code`` is ``'DEADLINE_EXCEEDED'``."""

    def __init__(self, opcode: str, path: str | None = None,
                 deadline_ms: float | None = None):
        where = ' %s' % (path,) if path else ''
        after = '' if deadline_ms is None else ' after %d ms' \
            % (deadline_ms,)
        super().__init__('DEADLINE_EXCEEDED',
            'Deadline exceeded%s waiting for %s%s reply'
            % (after, opcode, where))
        self.opcode = opcode
        self.path = path
        self.deadline_ms = deadline_ms


class ZKFrameTooLargeError(ZKProtocolError):
    """An inbound length prefix exceeded the frame-size cap
    (``ZKSTREAM_MAX_FRAME``, the ``jute.maxbuffer`` analogue).  Typed
    so both directions can reject the frame BEFORE buffering it — a
    corrupt or hostile 4-byte prefix must never make a peer try to
    allocate gigabytes; ``code`` is ``'FRAME_TOO_LARGE'``."""

    def __init__(self, length: int, cap: int):
        super().__init__('FRAME_TOO_LARGE',
            'Inbound ZK frame of %d bytes exceeds the %d-byte cap'
            % (length, cap))
        self.length = length
        self.cap = cap


class ZKNotConnectedError(ZKProtocolError):
    """An operation was attempted while no usable connection exists
    (reference: lib/errors.js:37-42)."""

    def __init__(self) -> None:
        super().__init__('CONNECTION_LOSS',
            'Not connected to a ZooKeeper server')


class ZKError(Exception):
    """A server-side operation error: the reply header carried a non-OK
    error code (reference: lib/errors.js:44-54).  ``code`` is the error
    name (e.g. ``'NO_NODE'``); ``errno`` the numeric protocol code."""

    def __init__(self, code: str, message: str | None = None):
        if message is None:
            message = ERR_TEXT.get(code) or code
        super().__init__(message)
        self.code = code
        self.message = message
        try:
            self.errno: int | None = int(ErrCode[code])
        except KeyError:
            self.errno = None


class ZKThrottledError(ZKError):
    """The serving member bounced a write at its global memory
    watermark (io/overload.py): a definite, typed failure — the write
    was NOT applied.  Reads keep flowing on the same connection; the
    client's write path backs off (capped exponential, the session's
    retry policy) and re-issues."""

    def __init__(self, message: str | None = None):
        super().__init__('THROTTLED', message)


class ZKMultiError(ZKError):
    """A MULTI transaction was rejected: no sub-op was applied
    (all-or-nothing, server/store.py ``ZKDatabase.multi``).  ``code``
    is the first failing sub-op's error; ``results`` holds the per-op
    outcome dicts exactly as the wire carried them (failed ops as
    ``{'op': 'error', 'err': <code>}``), and ``index`` names the first
    failing position."""

    def __init__(self, results: list):
        self.results = results
        self.index = next(
            (i for i, r in enumerate(results) if r.get('op') == 'error'
             and r.get('err') not in (None, 'OK',
                                      'RUNTIME_INCONSISTENCY')),
            next((i for i, r in enumerate(results)
                  if r.get('op') == 'error'), 0))
        code = (results[self.index].get('err', 'API_ERROR')
                if results else 'API_ERROR')
        super().__init__(code, 'multi rejected at op %d: %s (no sub-op '
                               'was applied)' % (self.index, code))
