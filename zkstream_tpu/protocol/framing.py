"""Length-prefixed framing and the stateful packet codec.

Every ZooKeeper message travels as a 4-byte big-endian length prefix
followed by that many body bytes.  ``FrameDecoder`` is an incremental
accumulator that slices complete frames out of an arbitrary byte stream
and rejects insane lengths (negative, or over the 16 MiB cap)
(reference: lib/zk-streams.js:39-64, cap at :23).

``PacketCodec`` layers the message codec on top: it tracks whether the
link is still handshaking (connect req/resp framing differs from the
steady-state request/reply framing) and keeps the xid -> opcode map the
reply decoder needs.  Like the reference's streams it is symmetric —
``server=True`` flips the direction so the same codec drives an
in-process ZooKeeper *server* for tests
(reference: lib/zk-streams.js:28,70-71,84-85,128-129).
"""

from __future__ import annotations

import os
import struct

from . import records
from .consts import MAX_PACKET
from .errors import ZKFrameTooLargeError, ZKProtocolError
from .fastencode import FastEncoder
from .jute import JuteReader, JuteWriter

_LEN = struct.Struct('>i')

MAX_FRAME_ENV = 'ZKSTREAM_MAX_FRAME'


def frame_cap_default() -> int:
    """The process-wide inbound frame-size cap (the ``jute.maxbuffer``
    analogue): ``ZKSTREAM_MAX_FRAME`` bytes, clamped to the 16 MiB
    protocol ceiling — a knob can only TIGHTEN the cap, never loosen
    the decoder's sanity bound."""
    raw = os.environ.get(MAX_FRAME_ENV)
    if raw:
        try:
            v = int(raw)
        except ValueError:
            return MAX_PACKET
        if v > 0:
            return min(v, MAX_PACKET)
    return MAX_PACKET


def resolve_frame_cap(arg: int | None) -> int:
    """Resolve an explicit constructor knob against the protocol
    ceiling (None = process default)."""
    if arg is None:
        return frame_cap_default()
    return min(int(arg), MAX_PACKET) if arg > 0 else MAX_PACKET


class FrameDecoder:
    """Incremental splitter of a byte stream into length-prefixed frames.

    When the native host codec is available (native/zkwire.cpp, loaded
    via utils/native.py) the scan runs in C++; the pure-Python loop is
    the always-present fallback and the semantic spec — the two are
    A/B-tested equivalent in tests/test_native.py.  ``use_native=None``
    auto-detects; True/False force a path (tests, benchmarks).
    """

    __slots__ = ('_buf', '_scanner', '_max_frame')

    def __init__(self, use_native: bool | None = None,
                 max_frame: int | None = None) -> None:
        self._buf = bytearray()
        #: Inbound frame cap, checked against the 4-byte prefix BEFORE
        #: any body byte is buffered — an oversized prefix raises the
        #: typed :class:`ZKFrameTooLargeError` instead of making the
        #: peer accumulate up to the prefix's claim.
        self._max_frame = resolve_frame_cap(max_frame)
        self._scanner = None
        if use_native is not False:
            from ..utils import native
            # auto mode (None) must never block the event loop: it
            # binds only an already-built artifact (the build proceeds
            # on a background thread for later connections).  Forced
            # mode (True, tests/tools) builds synchronously.
            lib = native.ensure_lib() if use_native else native.get_lib()
            if lib is not None:
                self._scanner = native.NativeFrameScanner(lib)
            elif use_native is True:
                raise RuntimeError('native codec unavailable')

    def feed(self, chunk: bytes) -> list[bytes]:
        """Absorb ``chunk``; return every complete frame body now
        available.  Raises ZKProtocolError('BAD_LENGTH') on a negative or
        oversized length prefix (reference: lib/zk-streams.js:47-53)."""
        self._buf += chunk
        if self._scanner is not None:
            return self._feed_native()
        frames: list[bytes] = []
        off = 0
        try:
            while len(self._buf) - off >= 4:
                (ln,) = _LEN.unpack_from(self._buf, off)
                if ln < 0:
                    raise ZKProtocolError('BAD_LENGTH',
                        'Invalid ZK packet length %d' % (ln,))
                if ln > self._max_frame:
                    raise ZKFrameTooLargeError(ln, self._max_frame)
                if len(self._buf) - off < 4 + ln:
                    break
                frames.append(bytes(self._buf[off + 4:off + 4 + ln]))
                off += 4 + ln
        finally:
            if off:
                del self._buf[:off]
        return frames

    def _feed_native(self) -> list[bytes]:
        """Native scan over the accumulated buffer (zero-copy: the
        scanner reads the bytearray in place).  Matches the Python loop
        exactly, including the BAD_LENGTH contract: complete frames
        before an invalid prefix are consumed-and-discarded and the
        buffer is left positioned at the offending prefix."""
        spans, resid, bad_at = self._scanner.scan(self._buf,
                                                  self._max_frame)
        if bad_at is not None:
            if bad_at:
                del self._buf[:bad_at]
            (ln,) = _LEN.unpack_from(self._buf, 0)
            if ln < 0:
                raise ZKProtocolError('BAD_LENGTH',
                    'Invalid ZK packet length %d' % (ln,))
            raise ZKFrameTooLargeError(ln, self._max_frame)
        frames = [bytes(self._buf[s:s + z]) for s, z in spans]
        if resid:
            del self._buf[:resid]
        return frames

    def pending(self) -> int:
        """Bytes buffered but not yet sliced into a frame."""
        return len(self._buf)

    def take_pending(self) -> bytes:
        """Hand off the undecoded residue (a partial frame) and clear
        it — used when an external drain (the fleet ingest) takes over
        this stream mid-flight."""
        out = bytes(self._buf)
        self._buf.clear()
        return out

    def restore_pending(self, data: bytes) -> None:
        """Give residue back (the external drain returned the stream)."""
        self._buf[:0] = data


def frame(body: bytes) -> bytes:
    """Wrap an encoded message body in its length prefix."""
    return _LEN.pack(len(body)) + body


class PacketCodec:
    """Stateful bytes <-> packet-dict codec for one TCP connection.

    ``handshaking`` starts True; the connection layer flips it to False
    once the connect exchange completes, switching both directions to the
    request/reply formats (reference: lib/zk-streams.js:68,126).
    """

    def __init__(self, server: bool = False,
                 use_native: bool | None = None,
                 max_frame: int | None = None):
        self._decoder = FrameDecoder(use_native=use_native,
                                     max_frame=max_frame)
        #: The resolved inbound frame cap: one value drives all three
        #: decode tiers (scalar loop, native scanner, C-extension
        #: batch decode), so the rejection boundary cannot fork.
        self._max_frame = self._decoder._max_frame
        self._server = server
        self.handshaking = True
        #: xid -> opcode for replies in flight
        #: (reference: lib/zk-streams.js:145, connection-fsm.js:74).
        self.xid_map: dict[int, str] = {}
        # The C-extension decoder covers both steady-state receive
        # directions — replies (client) and requests (server); only the
        # handshake exchange stays in Python.  Best-effort: absent
        # extension degrades to the scalar path.
        self._ext = None
        if use_native is not False:
            from ..utils import native
            self._ext = (native.ensure_ext() if use_native
                         else native.get_ext())
        # Middle encode tier: single-pass struct-batched Python
        # (protocol/fastencode.py).  Runs when the C encoder is absent
        # or declines a shape; the JuteWriter walk below stays the
        # spec and the last resort.
        self._fast = (None if os.environ.get('ZKSTREAM_NO_FASTENC')
                      == '1' else FastEncoder())

    @property
    def ext(self):
        """The bound C-extension decoder (or None) — exposed for the
        fleet ingest's zero-copy slice-decode fast path, which must
        honor this connection's codec selection (``--codec``)."""
        return self._ext

    def encode(self, pkt: dict) -> bytes:
        """Encode one outgoing packet to framed wire bytes."""
        if self._ext is not None and not self.handshaking:
            # best-effort C encode: None means "shape the C side does
            # not handle" (rare opcodes, out-of-range fields) — the
            # Python encoder below is the spec and raises its own
            # validation errors; byte equality is A/B-tested.
            data = (self._ext.encode_response(pkt) if self._server
                    else self._ext.encode_request(pkt))
            if data is not None:
                if not self._server:
                    self.xid_map[pkt['xid']] = pkt['opcode']
                return data
        if self._fast is not None and not self.handshaking:
            # single-pass Python tier: same None-means-fall-back
            # contract as the C encoder, same A/B-tested equivalence
            data = (self._fast.encode_response(pkt) if self._server
                    else self._fast.encode_request(pkt))
            if data is not None:
                if not self._server:
                    self.xid_map[pkt['xid']] = pkt['opcode']
                return data
        w = JuteWriter()
        if self.handshaking:
            if self._server:
                records.write_connect_response(w, pkt)
            else:
                records.write_connect_request(w, pkt)
        elif self._server:
            records.write_response(w, pkt)
        else:
            records.write_request(w, pkt)
            self.xid_map[pkt['xid']] = pkt['opcode']
        return frame(w.to_bytes())

    def take_pending(self) -> bytes:
        """See :meth:`FrameDecoder.take_pending`."""
        return self._decoder.take_pending()

    def restore_pending(self, data: bytes) -> None:
        """See :meth:`FrameDecoder.restore_pending`."""
        self._decoder.restore_pending(data)

    def decode(self, chunk: bytes) -> list[dict]:
        """Absorb incoming bytes; return the packets completed by them.

        Framing errors raise ZKProtocolError('BAD_LENGTH'); undecodable
        frame bodies raise ZKProtocolError('BAD_DECODE')
        (reference: lib/zk-streams.js:49-51,74-79,90-95).  When a later
        frame in the chunk fails, packets decoded before it are attached
        to the error as ``err.packets`` so the caller can still deliver
        them (e.g. a watch notification sharing a TCP segment with a
        corrupt frame must not be lost — ZK will never refire it).
        """
        if self._ext is not None and not self.handshaking:
            return self._decode_ext(chunk)
        return self._decode_scalar(chunk, [])

    def _decode_scalar(self, chunk: bytes,
                       pkts: list[dict]) -> list[dict]:
        """The pure-Python decode loop, appending into ``pkts`` (the
        spec tier; also the continuation the extension path hands the
        buffer to when it punts an opcode it carries no layout for)."""
        for body in self._decoder.feed(chunk):
            r = JuteReader(body)
            try:
                if self.handshaking:
                    if self._server:
                        pkt = records.read_connect_request(r)
                    else:
                        pkt = records.read_connect_response(r)
                elif self._server:
                    pkt = records.read_request(r)
                else:
                    pkt = records.read_response(r, self.xid_map)
            except Exception as e:
                if isinstance(e, ZKProtocolError):
                    err = e
                else:
                    what = ('ConnectRequest' if self._server else
                            'ConnectResponse') if self.handshaking else (
                            'Request' if self._server else 'Response')
                    err = ZKProtocolError('BAD_DECODE',
                        'Failed to decode %s: %s: %s' % (
                            what, type(e).__name__, e))
                    err.__cause__ = e
                err.packets = pkts
                raise err
            pkts.append(pkt)
        return pkts

    def _decode_ext(self, chunk: bytes) -> list[dict]:
        """Steady-state client receive via the C extension: framing +
        reply decode in one native pass over the accumulation buffer.
        Shares the FrameDecoder's buffer so handing a connection between
        paths (handshake -> steady state, ingest take/restore_pending)
        stays seamless; error semantics mirror the Python path
        (A/B-tested in tests/test_native_ext.py)."""
        buf = self._decoder._buf
        buf += chunk
        try:
            if self._server:
                pkts, consumed, kind, msg = self._ext.decode_requests(
                    buf, self._max_frame)
            else:
                pkts, consumed, kind, msg = self._ext.decode_responses(
                    buf, self.xid_map, self._max_frame)
        except Exception as e:
            # Parity with the scalar path: ANY decode-side exception
            # (e.g. MemoryError) surfaces as connection-fatal
            # BAD_DECODE, never as a raw exception the connection FSM
            # would not catch.
            err = ZKProtocolError('BAD_DECODE',
                'Failed to decode %s: %s: %s'
                % ('Request' if self._server else 'Response',
                   type(e).__name__, e))
            err.__cause__ = e
            err.packets = []
            raise err
        if consumed:
            del buf[:consumed]
        if kind == 'UNSUPPORTED':
            # the head of the buffer is a complete frame whose opcode
            # the C tier carries no layout for (MULTI): the spec tier
            # takes over from here — it decodes the frame (or raises
            # the spec's own precise error) and everything behind it
            # in this chunk, with the scalar path's exact buffer and
            # error semantics; the next chunk re-enters the C tier
            return self._decode_scalar(b'', pkts)
        if kind is not None:
            if kind == 'BAD_LENGTH' and len(buf) >= 4:
                # scalar parity: the buffer is positioned at the
                # offending prefix — a non-negative over-cap length is
                # the typed frame-size rejection, not a corrupt prefix
                (ln,) = _LEN.unpack_from(buf, 0)
                if ln > self._max_frame and ln >= 0:
                    err = ZKFrameTooLargeError(ln, self._max_frame)
                    err.packets = pkts
                    raise err
            err = ZKProtocolError(kind, msg)
            err.packets = pkts
            raise err
        return pkts
