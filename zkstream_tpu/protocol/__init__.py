"""Wire-protocol layers: constants, errors, jute primitives, message
records, framing (reference layers L0-L3, lib/zk-consts.js through
lib/zk-streams.js)."""

from . import consts, errors, framing, jute, records  # noqa: F401
from .consts import (  # noqa: F401
    MAX_PACKET,
    PROTOCOL_VERSION,
    CreateFlag,
    ErrCode,
    KeeperState,
    NotificationType,
    OpCode,
    Perm,
)
from .errors import (  # noqa: F401
    ZKError,
    ZKNotConnectedError,
    ZKPingTimeoutError,
    ZKProtocolError,
)
from .framing import FrameDecoder, PacketCodec, frame  # noqa: F401
from .jute import JuteReader, JuteWriter  # noqa: F401
from .records import ACL, OPEN_ACL_UNSAFE, Id, Stat  # noqa: F401
