"""ZooKeeper message-body codec.

Encodes and decodes every message the client speaks: the connect
handshake, the request bodies, and the reply bodies, plus the shared
Stat / ACL / notification records (reference: lib/zk-buffer.js:22-443).

Packets are plain dicts (mirroring the reference's packet objects) keyed
by ``opcode`` name strings; ``Stat``, ``ACL`` and ``Id`` are dataclasses.
64-bit protocol fields (zxid, sessionId, ephemeralOwner, times) are plain
Python ints.

Unlike the reference — whose server mode cannot encode replies (its
zk-streams.js:140 calls a ``writeResponse`` that lib/zk-buffer.js never
defines) — this codec is fully symmetric: ``encode_response`` /
``decode_request`` make an in-process ZooKeeper server possible.
"""

from __future__ import annotations

import dataclasses
import struct
import typing

from .consts import (
    SPECIAL_XIDS,
    CreateFlag,
    ErrCode,
    KeeperState,
    NotificationType,
    OpCode,
    Perm,
    err_name,
)
from .jute import JuteReader, JuteWriter


@dataclasses.dataclass(frozen=True)
class Id:
    """An ACL identity (reference: lib/zk-buffer.js:416-426)."""

    scheme: str
    id: str


@dataclasses.dataclass(frozen=True)
class ACL:
    """One ACL entry: a permission mask and who it applies to
    (reference: lib/zk-buffer.js:372-414)."""

    perms: Perm
    id: Id


#: world:anyone with all permissions — the default ACL for new nodes.
OPEN_ACL_UNSAFE = (ACL(Perm.ALL, Id('world', 'anyone')),)


class Stat(typing.NamedTuple):
    """The 11-field znode stat record (reference: lib/zk-buffer.js:428-442).
    ``ctime``/``mtime`` are milliseconds since the epoch.

    A NamedTuple, not a dataclass: immutable and field-named either way,
    but tuple construction happens in C — the decode hot path builds one
    per stat-bearing reply, and a frozen dataclass pays ~11 Python-level
    ``object.__setattr__`` calls each (see tools/profile_hotpath.py)."""

    czxid: int = 0
    mzxid: int = 0
    ctime: int = 0
    mtime: int = 0
    version: int = 0
    cversion: int = 0
    aversion: int = 0
    ephemeralOwner: int = 0
    dataLength: int = 0
    numChildren: int = 0
    pzxid: int = 0


#: The Stat record's fixed 68-byte layout, decoded in one call — field
#: order matches the Stat dataclass exactly.
_STAT_STRUCT = struct.Struct('>qqqqiiiqiiq')


def read_stat(r: JuteReader) -> Stat:
    return Stat(*r.read_struct(_STAT_STRUCT))


def write_stat(w: JuteWriter, s: Stat) -> None:
    # one 68-byte pack; field order is the Stat tuple order
    w.write_struct(_STAT_STRUCT, *s)


def read_acl(r: JuteReader) -> list[ACL]:
    count = r.read_int()
    out = []
    for _ in range(count):
        perms = Perm(r.read_int())
        scheme = r.read_ustring()
        ident = r.read_ustring()
        out.append(ACL(perms, Id(scheme, ident)))
    return out


def write_acl(w: JuteWriter, acl) -> None:
    w.write_int(len(acl))
    for entry in acl:
        w.write_int(int(entry.perms))
        w.write_ustring(entry.id.scheme)
        w.write_ustring(entry.id.id)


# -- Connect handshake (reference: lib/zk-buffer.js:22-56) --

def write_connect_request(w: JuteWriter, pkt: dict) -> None:
    w.write_int(pkt['protocolVersion'])
    w.write_long(pkt['lastZxidSeen'])
    w.write_int(pkt['timeOut'])
    w.write_long(pkt['sessionId'])
    w.write_buffer(pkt['passwd'])


def read_connect_request(r: JuteReader) -> dict:
    return {
        'protocolVersion': r.read_int(),
        'lastZxidSeen': r.read_long(),
        'timeOut': r.read_int(),
        'sessionId': r.read_long(),
        'passwd': r.read_buffer(),
    }


def write_connect_response(w: JuteWriter, pkt: dict) -> None:
    w.write_int(pkt['protocolVersion'])
    w.write_int(pkt['timeOut'])
    w.write_long(pkt['sessionId'])
    w.write_buffer(pkt['passwd'])


def read_connect_response(r: JuteReader) -> dict:
    return {
        'protocolVersion': r.read_int(),
        'timeOut': r.read_int(),
        'sessionId': r.read_long(),
        'passwd': r.read_buffer(),
    }


# -- Requests (reference: lib/zk-buffer.js:58-273) --

def _write_path(w: JuteWriter, pkt: dict) -> None:
    w.write_ustring(pkt['path'])


def _write_path_watch(w: JuteWriter, pkt: dict) -> None:
    w.write_ustring(pkt['path'])
    w.write_bool(pkt['watch'])


def _read_path(r: JuteReader, pkt: dict) -> None:
    pkt['path'] = r.read_ustring()


def _read_path_watch(r: JuteReader, pkt: dict) -> None:
    pkt['path'] = r.read_ustring()
    pkt['watch'] = r.read_bool()


def _write_create(w: JuteWriter, pkt: dict) -> None:
    w.write_ustring(pkt['path'])
    w.write_buffer(pkt['data'])
    write_acl(w, pkt['acl'])
    w.write_int(int(CreateFlag(pkt.get('flags', 0))))


def _read_create(r: JuteReader, pkt: dict) -> None:
    pkt['path'] = r.read_ustring()
    pkt['data'] = r.read_buffer()
    pkt['acl'] = read_acl(r)
    pkt['flags'] = CreateFlag(r.read_int())


def _write_delete(w: JuteWriter, pkt: dict) -> None:
    w.write_ustring(pkt['path'])
    w.write_int(pkt['version'])


def _read_delete(r: JuteReader, pkt: dict) -> None:
    pkt['path'] = r.read_ustring()
    pkt['version'] = r.read_int()


def _write_set_data(w: JuteWriter, pkt: dict) -> None:
    w.write_ustring(pkt['path'])
    w.write_buffer(pkt['data'])
    w.write_int(pkt['version'])


def _read_set_data(r: JuteReader, pkt: dict) -> None:
    pkt['path'] = r.read_ustring()
    pkt['data'] = r.read_buffer()
    pkt['version'] = r.read_int()


def _write_check(w: JuteWriter, pkt: dict) -> None:
    w.write_ustring(pkt['path'])
    w.write_int(pkt['version'])


def _read_check(r: JuteReader, pkt: dict) -> None:
    pkt['path'] = r.read_ustring()
    pkt['version'] = r.read_int()


# -- MULTI (opcode 14): all-or-nothing transactions --------------------
#
# The jute MultiHeader framing (upstream ZooKeeper MultiTransactionRecord
# / MultiResponse; the reference client never implemented opcode 14 —
# its consts table stops at naming it): each sub-op travels as
# ``int type | bool done | int err`` followed by the op body, terminated
# by a header with ``type == -1, done == True``.  Request sub-op bodies
# reuse the single-op request shapes (create / delete / setData /
# check); response results carry the single-op reply bodies for OK
# results and an ``int err`` body (type -1) for error results.  The
# whole batch is ONE frame, ONE server transaction, ONE WAL record
# (server/store.py ``ZKDatabase.multi``).

#: Sub-ops a MULTI may carry, by wire type number.
MULTI_OPS = {
    'create': int(OpCode.CREATE),
    'delete': int(OpCode.DELETE),
    'set_data': int(OpCode.SET_DATA),
    'check': int(OpCode.CHECK),
}
_MULTI_OP_NAMES = {v: k for k, v in MULTI_OPS.items()}

_MULTI_SUB_WRITERS = {
    'create': _write_create,
    'delete': _write_delete,
    'set_data': _write_set_data,
    'check': _write_check,
}
_MULTI_SUB_READERS = {
    'create': _read_create,
    'delete': _read_delete,
    'set_data': _read_set_data,
    'check': _read_check,
}


def _write_multi_header(w: JuteWriter, type_: int, done: bool,
                        err: int) -> None:
    w.write_int(type_)
    w.write_bool(done)
    w.write_int(err)


def _write_multi(w: JuteWriter, pkt: dict) -> None:
    for op in pkt['ops']:
        name = op['op']
        if name not in MULTI_OPS:
            raise ValueError('unsupported multi sub-op %r' % (name,))
        _write_multi_header(w, MULTI_OPS[name], False, -1)
        _MULTI_SUB_WRITERS[name](w, op)
    _write_multi_header(w, -1, True, -1)


def _read_multi(r: JuteReader, pkt: dict) -> None:
    ops: list[dict] = []
    while True:
        type_ = r.read_int()
        done = r.read_bool()
        r.read_int()                  # err: always -1 in requests
        if done:
            if type_ != -1:
                raise ValueError(
                    'multi terminator carries type %d' % (type_,))
            break
        name = _MULTI_OP_NAMES.get(type_)
        if name is None:
            raise ValueError('unsupported multi sub-op type %d'
                             % (type_,))
        sub: dict = {'op': name}
        _MULTI_SUB_READERS[name](r, sub)
        ops.append(sub)
    pkt['ops'] = ops


def _read_multi_resp(r: JuteReader, pkt: dict) -> None:
    results: list[dict] = []
    while True:
        type_ = r.read_int()
        done = r.read_bool()
        err = r.read_int()
        if done:
            break
        if type_ == -1:
            # ErrorResult: the body repeats the error code as an int
            r.read_int()
            results.append({'op': 'error', 'err': err_name(err)})
            continue
        name = _MULTI_OP_NAMES.get(type_)
        if name is None:
            raise ValueError('unsupported multi result type %d'
                             % (type_,))
        res: dict = {'op': name}
        if name == 'create':
            res['path'] = r.read_ustring()
        elif name == 'set_data':
            res['stat'] = read_stat(r)
        results.append(res)           # delete / check: header only
    pkt['results'] = results


def _write_multi_resp(w: JuteWriter, pkt: dict) -> None:
    for res in pkt['results']:
        name = res['op']
        if name == 'error':
            code = int(ErrCode[res['err']])
            _write_multi_header(w, -1, False, code)
            w.write_int(code)
            continue
        if name not in MULTI_OPS:
            raise ValueError('unsupported multi result %r' % (name,))
        _write_multi_header(w, MULTI_OPS[name], False, 0)
        if name == 'create':
            w.write_ustring(res['path'])
        elif name == 'set_data':
            write_stat(w, res['stat'])
    _write_multi_header(w, -1, True, -1)


#: The three watch lists in a SET_WATCHES body, in wire order
#: (reference: lib/zk-buffer.js:233-273).
SET_WATCHES_KINDS = ('dataChanged', 'createdOrDestroyed', 'childrenChanged')

#: SET_WATCHES2 (opcode 107, upstream ZooKeeper SetWatches2): the
#: legacy three lists followed by the two persistent-watch lists.
SET_WATCHES2_KINDS = SET_WATCHES_KINDS + ('persistent',
                                          'persistentRecursive')


def _write_watch_lists(w: JuteWriter, pkt: dict, kinds) -> None:
    w.write_long(pkt['relZxid'])
    events = pkt.get('events', {})
    for kind in kinds:
        paths = events.get(kind, ())
        w.write_int(len(paths))
        for p in paths:
            w.write_ustring(p)


def _read_watch_lists(r: JuteReader, pkt: dict, kinds) -> None:
    pkt['relZxid'] = r.read_long()
    pkt['events'] = {}
    for kind in kinds:
        count = r.read_int()
        pkt['events'][kind] = [r.read_ustring() for _ in range(count)]


def _write_set_watches(w: JuteWriter, pkt: dict) -> None:
    _write_watch_lists(w, pkt, SET_WATCHES_KINDS)


def _read_set_watches(r: JuteReader, pkt: dict) -> None:
    _read_watch_lists(r, pkt, SET_WATCHES_KINDS)


def _write_set_watches2(w: JuteWriter, pkt: dict) -> None:
    _write_watch_lists(w, pkt, SET_WATCHES2_KINDS)


def _read_set_watches2(r: JuteReader, pkt: dict) -> None:
    _read_watch_lists(r, pkt, SET_WATCHES2_KINDS)


def _write_add_watch(w: JuteWriter, pkt: dict) -> None:
    # AddWatchRequest: path ustring + mode int (AddWatchMode)
    w.write_ustring(pkt['path'])
    w.write_int(pkt['mode'])


def _read_add_watch(r: JuteReader, pkt: dict) -> None:
    pkt['path'] = r.read_ustring()
    pkt['mode'] = r.read_int()


_REQ_WRITERS = {
    'GET_CHILDREN': _write_path_watch,
    'GET_CHILDREN2': _write_path_watch,
    'GET_DATA': _write_path_watch,
    'EXISTS': _write_path_watch,
    'CREATE': _write_create,
    'DELETE': _write_delete,
    'GET_ACL': _write_path,
    'SET_DATA': _write_set_data,
    'SYNC': _write_path,
    'SET_WATCHES': _write_set_watches,
    'SET_WATCHES2': _write_set_watches2,
    'ADD_WATCH': _write_add_watch,
    'MULTI': _write_multi,
    # Header-only requests (reference: lib/zk-buffer.js:129-132):
    'CLOSE_SESSION': None,
    'PING': None,
}

_REQ_READERS = {
    'GET_CHILDREN': _read_path_watch,
    'GET_CHILDREN2': _read_path_watch,
    'GET_DATA': _read_path_watch,
    'EXISTS': _read_path_watch,
    'CREATE': _read_create,
    'DELETE': _read_delete,
    'GET_ACL': _read_path,
    'SET_DATA': _read_set_data,
    'SYNC': _read_path,
    'SET_WATCHES': _read_set_watches,
    'SET_WATCHES2': _read_set_watches2,
    'ADD_WATCH': _read_add_watch,
    'MULTI': _read_multi,
    'CLOSE_SESSION': None,
    'PING': None,
}


def write_request(w: JuteWriter, pkt: dict) -> None:
    """Encode a request: 8-byte header (xid, opcode) then the body
    (reference: lib/zk-buffer.js:97-136)."""
    opcode = pkt['opcode']
    if opcode not in _REQ_WRITERS:
        raise ValueError('unsupported opcode %r' % (opcode,))
    w.write_int(pkt['xid'])
    w.write_int(int(OpCode[opcode]))
    body = _REQ_WRITERS[opcode]
    if body is not None:
        body(w, pkt)


def read_request(r: JuteReader) -> dict:
    """Decode a request (server direction)
    (reference: lib/zk-buffer.js:58-95)."""
    pkt: dict = {}
    pkt['xid'] = r.read_int()
    pkt['opcode'] = OpCode(r.read_int()).name
    if pkt['opcode'] not in _REQ_READERS:
        raise ValueError('unsupported opcode %r' % (pkt['opcode'],))
    body = _REQ_READERS[pkt['opcode']]
    if body is not None:
        body(r, pkt)
    return pkt


# -- Responses (reference: lib/zk-buffer.js:275-370) --

def _read_get_children_resp(r: JuteReader, pkt: dict) -> None:
    count = r.read_int()
    pkt['children'] = [r.read_ustring() for _ in range(count)]
    if pkt['opcode'] == 'GET_CHILDREN2':
        pkt['stat'] = read_stat(r)


def _read_create_resp(r: JuteReader, pkt: dict) -> None:
    pkt['path'] = r.read_ustring()


def _read_stat_only_resp(r: JuteReader, pkt: dict) -> None:
    pkt['stat'] = read_stat(r)


def _read_get_acl_resp(r: JuteReader, pkt: dict) -> None:
    pkt['acl'] = read_acl(r)
    pkt['stat'] = read_stat(r)


def _read_get_data_resp(r: JuteReader, pkt: dict) -> None:
    pkt['data'] = r.read_buffer()
    pkt['stat'] = read_stat(r)


def _read_notification(r: JuteReader, pkt: dict) -> None:
    pkt['type'] = NotificationType(r.read_int()).name
    pkt['state'] = KeeperState(r.read_int()).name
    pkt['path'] = r.read_ustring()


#: Reply opcodes whose body is empty — the header error code alone carries
#: the result (reference: lib/zk-buffer.js:316-325).
_EMPTY_RESPONSES = frozenset(
    ('SET_WATCHES', 'SET_WATCHES2', 'ADD_WATCH', 'PING', 'SYNC',
     'DELETE', 'CLOSE_SESSION', 'AUTH'))

_RESP_READERS = {
    'GET_CHILDREN': _read_get_children_resp,
    'GET_CHILDREN2': _read_get_children_resp,
    'CREATE': _read_create_resp,
    'GET_ACL': _read_get_acl_resp,
    'GET_DATA': _read_get_data_resp,
    'NOTIFICATION': _read_notification,
    'EXISTS': _read_stat_only_resp,
    'SET_DATA': _read_stat_only_resp,
    'MULTI': _read_multi_resp,
}


#: The 16-byte reply header (xid:int32, zxid:int64, err:int32), decoded
#: in one call (reference: lib/zk-buffer.js:281-289).
_REPLY_HDR_STRUCT = struct.Struct('>iqi')


def read_response(r: JuteReader, xid_map: dict[int, str]) -> dict:
    """Decode a reply.  The opcode comes from the special-xid table for
    reserved xids, otherwise from the caller's xid -> opcode map recorded
    at encode time (reference: lib/zk-buffer.js:281-331)."""
    xid, zxid, errc = r.read_struct(_REPLY_HDR_STRUCT)
    pkt: dict = {'xid': xid, 'zxid': zxid, 'err': err_name(errc)}
    opcode = SPECIAL_XIDS.get(pkt['xid'])
    if opcode is None:
        # One reply per xid: pop so the map cannot grow without bound
        # over a long-lived connection.
        opcode = xid_map.pop(pkt['xid'], None)
    if opcode is None:
        raise ValueError('reply xid %d matches no request' % (pkt['xid'],))
    pkt['opcode'] = opcode
    if pkt['err'] != 'OK':
        return pkt
    if opcode in _EMPTY_RESPONSES:
        return pkt
    body = _RESP_READERS.get(opcode)
    if body is None:
        raise ValueError('unsupported reply opcode %r' % (opcode,))
    body(r, pkt)
    return pkt


# -- Server-direction response encoding (no reference equivalent: the
#    reference's zk-streams.js:140 calls an undefined writeResponse) --

def _write_get_children_resp(w: JuteWriter, pkt: dict) -> None:
    children = pkt['children']
    w.write_int(len(children))
    for c in children:
        w.write_ustring(c)
    if pkt['opcode'] == 'GET_CHILDREN2':
        write_stat(w, pkt['stat'])


def _write_create_resp(w: JuteWriter, pkt: dict) -> None:
    w.write_ustring(pkt['path'])


def _write_stat_only_resp(w: JuteWriter, pkt: dict) -> None:
    write_stat(w, pkt['stat'])


def _write_get_acl_resp(w: JuteWriter, pkt: dict) -> None:
    write_acl(w, pkt['acl'])
    write_stat(w, pkt['stat'])


def _write_get_data_resp(w: JuteWriter, pkt: dict) -> None:
    w.write_buffer(pkt['data'])
    write_stat(w, pkt['stat'])


def _write_notification(w: JuteWriter, pkt: dict) -> None:
    w.write_int(int(NotificationType[pkt['type']]))
    w.write_int(int(KeeperState[pkt['state']]))
    w.write_ustring(pkt['path'])


_RESP_WRITERS = {
    'GET_CHILDREN': _write_get_children_resp,
    'GET_CHILDREN2': _write_get_children_resp,
    'CREATE': _write_create_resp,
    'GET_ACL': _write_get_acl_resp,
    'GET_DATA': _write_get_data_resp,
    'NOTIFICATION': _write_notification,
    'EXISTS': _write_stat_only_resp,
    'SET_DATA': _write_stat_only_resp,
    'MULTI': _write_multi_resp,
}


def write_response(w: JuteWriter, pkt: dict) -> None:
    """Encode a reply (server direction): 16-byte header (xid, zxid, err)
    then the body if the error is OK and the opcode has one."""
    err = pkt.get('err', 'OK')
    w.write_struct(_REPLY_HDR_STRUCT, pkt['xid'], pkt['zxid'],
                   int(ErrCode[err]))
    if err != 'OK':
        return
    opcode = pkt['opcode']
    if opcode in _EMPTY_RESPONSES:
        return
    body = _RESP_WRITERS.get(opcode)
    if body is None:
        raise ValueError('unsupported reply opcode %r' % (opcode,))
    body(w, pkt)
