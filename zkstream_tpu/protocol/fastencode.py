"""Single-pass steady-state encoders — the send-side twin of the
struct-batched decode (PROFILE.md).

``records.write_request`` / ``write_response`` walk a ``JuteWriter``
one primitive at a time: ~10-15 Python-level calls and one
``struct.pack`` per *field* for a GET_DATA reply.  The decode profile
condemned exactly that shape on the receive side, and the cure is the
same here: per-opcode precompiled encoders.  Every variable length is
known before any byte is written, so the frame's length prefix, the
8/16-byte header and every adjacent fixed-width field go out in ONE
``struct.pack`` (no reserve-and-backfill pass), the variable bytes are
spliced with a single ``join``, and the 68-byte Stat is one pack — the
exact twin of ``records.read_stat``.  An EXISTS/SET_DATA reply is one
``struct.pack`` for the entire frame, prefix to pzxid.  (A reusable
scratch buffer with ``pack_into`` + in-place length patching was
measured ~2x SLOWER than pack-and-join: the final ``bytes()`` copy out
of the scratch costs more than the join saves.)

``JuteWriter`` + ``records`` remain the semantic spec and the
fallback: every encoder here returns ``None`` for any shape, type or
range it does not handle bit-exactly, and ``PacketCodec.encode``
re-runs the spec encoder, which raises its own precise validation
errors.  Byte-for-byte equivalence over the full opcode corpus is
asserted in tests/test_fastencode.py (and against the C encoders in
native/zkwire_ext.c when the extension is present — the three tiers
must agree or the fast ones lose).

``ZKSTREAM_NO_FASTENC=1`` disables this tier (A/B tests, the encode
profile's per-field baseline).
"""

from __future__ import annotations

import struct

from . import records
from .consts import ErrCode, KeeperState, NotificationType, OpCode
from .jute import JuteWriter

#: The Stat record's fixed 68-byte layout in one pack
#: (field order is the records.Stat tuple order).
_STAT = records._STAT_STRUCT

_INT = struct.Struct('>i')
#: len + xid + opnum — a header-only request (PING, CLOSE_SESSION).
_REQ_HDR = struct.Struct('>iii')
#: len + xid + opnum + first-string length, one pack.
_REQ_PATH_HDR = struct.Struct('>iiii')
#: len + xid + zxid + err — the framed 16-byte reply header.
_RESP_HDR = struct.Struct('>iiqi')
#: reply header + one buffer length (GET_DATA data, CREATE path).
_RESP_BUF_HDR = struct.Struct('>iiqii')
#: reply header + the WHOLE 68-byte Stat: an EXISTS/SET_DATA reply is
#: one pack, start to finish.
_RESP_STAT = struct.Struct('>iiqiqqqqiiiqiiq')
#: reply header + notification type + state + path length.
_NOTIF_HDR = struct.Struct('>iiqiiii')
#: one jute MultiHeader: int type | bool done | int err.
_MULTI_HDR = struct.Struct('>ibi')
_MULTI_END = _MULTI_HDR.pack(-1, 1, -1)

_ERRNUM = {e.name: int(e) for e in ErrCode}
_NOTIFNUM = {t.name: int(t) for t in NotificationType}
_STATENUM = {s.name: int(s) for s in KeeperState}

_EMPTY_RESPONSES = records._EMPTY_RESPONSES

#: The default ACL every create() issues, pre-encoded once via the
#: spec writer so equivalence is by construction.
_w = JuteWriter()
records.write_acl(_w, records.OPEN_ACL_UNSAFE)
_OPEN_ACL_BYTES = _w.to_bytes()
del _w

#: Exceptions that mean "this shape is the spec encoder's business":
#: the fallback re-raises them with its own precise messages.
_FALLBACK_ERRORS = (KeyError, TypeError, AttributeError, ValueError,
                    UnicodeError, struct.error)


def _acl_bytes(acl):
    """Encode a non-default ACL list via the spec writer (rare path —
    the OPEN_ACL_UNSAFE identity hit above covers steady state);
    None on anything the spec would reject."""
    try:
        w = JuteWriter()
        records.write_acl(w, acl)
        return w.to_bytes()
    except Exception:
        return None


class FastEncoder:
    """Per-codec single-pass encoder (stateless; the class keeps the
    tier's dispatch tables and the codec-facing API in one place)."""

    __slots__ = ()

    # -- requests (client direction) --

    def encode_request(self, pkt: dict) -> bytes | None:
        """Framed wire bytes for one request, or None to fall back."""
        try:
            fn, opnum = _REQ_FAST[pkt['opcode']]
            return fn(self, pkt, opnum)
        except _FALLBACK_ERRORS:
            return None

    def _rq_bare(self, pkt, opnum):
        return _REQ_HDR.pack(8, pkt['xid'], opnum)

    def _rq_path(self, pkt, opnum):
        p = pkt['path']
        if type(p) is not str:
            return None
        pb = p.encode('utf-8')
        n = len(pb)
        return _REQ_PATH_HDR.pack(12 + n, pkt['xid'], opnum,
                                  n if n else -1) + pb

    def _rq_path_watch(self, pkt, opnum):
        p = pkt['path']
        wt = pkt['watch']
        if type(p) is not str or type(wt) is not bool:
            return None
        pb = p.encode('utf-8')
        n = len(pb)
        return b''.join((
            _REQ_PATH_HDR.pack(13 + n, pkt['xid'], opnum,
                               n if n else -1),
            pb, b'\x01' if wt else b'\x00'))

    def _rq_delete(self, pkt, opnum):
        p = pkt['path']
        if type(p) is not str:
            return None
        pb = p.encode('utf-8')
        n = len(pb)
        return b''.join((
            _REQ_PATH_HDR.pack(16 + n, pkt['xid'], opnum,
                               n if n else -1),
            pb, _INT.pack(pkt['version'])))

    def _rq_add_watch(self, pkt, opnum):
        # path + mode int — the DELETE shape with AddWatchMode in the
        # trailing int slot
        p = pkt['path']
        m = pkt['mode']
        if type(p) is not str or not isinstance(m, int) \
                or not 0 <= m <= 1:
            return None
        pb = p.encode('utf-8')
        n = len(pb)
        return b''.join((
            _REQ_PATH_HDR.pack(16 + n, pkt['xid'], opnum,
                               n if n else -1),
            pb, _INT.pack(int(m))))

    def _rq_set_data(self, pkt, opnum):
        p = pkt['path']
        d = pkt['data']
        if type(p) is not str:
            return None
        pb = p.encode('utf-8')
        n = len(pb)
        dn = len(d)
        return b''.join((
            _REQ_PATH_HDR.pack(20 + n + dn, pkt['xid'], opnum,
                               n if n else -1),
            pb, _INT.pack(dn if dn else -1), d,
            _INT.pack(pkt['version'])))

    def _rq_create(self, pkt, opnum):
        p = pkt['path']
        d = pkt['data']
        acl = pkt['acl']
        fl = pkt.get('flags', 0)
        # CreateFlag NORMALIZES out-of-range flags (e.g. -1 -> 3); only
        # already-normal values are safe to write verbatim.
        if type(p) is not str or not isinstance(fl, int) \
                or not 0 <= fl <= 3:
            return None
        if acl is records.OPEN_ACL_UNSAFE:
            ab = _OPEN_ACL_BYTES
        else:
            ab = _acl_bytes(acl)
            if ab is None:
                return None
        pb = p.encode('utf-8')
        n = len(pb)
        dn = len(d)
        return b''.join((
            _REQ_PATH_HDR.pack(20 + n + dn + len(ab), pkt['xid'],
                               opnum, n if n else -1),
            pb, _INT.pack(dn if dn else -1), d, ab,
            _INT.pack(int(fl))))

    def _multi_sub_body(self, op: dict) -> bytes | None:
        """One MULTI sub-op request body (no header), single pass;
        None for any shape the spec tier must judge."""
        name = op['op']
        p = op['path']
        if type(p) is not str:
            return None
        pb = p.encode('utf-8')
        n = len(pb)
        if name in ('delete', 'check'):
            return b''.join((_INT.pack(n if n else -1), pb,
                             _INT.pack(op['version'])))
        if name == 'set_data':
            d = op['data']
            dn = len(d)
            return b''.join((_INT.pack(n if n else -1), pb,
                             _INT.pack(dn if dn else -1), d,
                             _INT.pack(op['version'])))
        if name == 'create':
            d = op['data']
            fl = op.get('flags', 0)
            if not isinstance(fl, int) or not 0 <= fl <= 3:
                return None
            acl = op['acl']
            if acl is records.OPEN_ACL_UNSAFE:
                ab = _OPEN_ACL_BYTES
            else:
                ab = _acl_bytes(acl)
                if ab is None:
                    return None
            dn = len(d)
            return b''.join((_INT.pack(n if n else -1), pb,
                             _INT.pack(dn if dn else -1), d, ab,
                             _INT.pack(int(fl))))
        return None

    def _rq_multi(self, pkt, opnum):
        parts = [b'']                 # [0] holds the framed header
        size = 8
        for op in pkt['ops']:
            t = records.MULTI_OPS.get(op['op'])
            if t is None:
                return None
            body = self._multi_sub_body(op)
            if body is None:
                return None
            parts.append(_MULTI_HDR.pack(t, 0, -1))
            parts.append(body)
            size += 9 + len(body)
        parts.append(_MULTI_END)
        size += 9
        parts[0] = _REQ_HDR.pack(size, pkt['xid'], opnum)
        return b''.join(parts)

    # -- responses (server direction) --

    def encode_response(self, pkt: dict) -> bytes | None:
        """Framed wire bytes for one reply, or None to fall back."""
        try:
            err = pkt.get('err', 'OK')
            if err == 'OK':
                fn = _RESP_FAST.get(pkt['opcode'])
                if fn is not None:
                    return fn(self, pkt)
                if pkt['opcode'] in _EMPTY_RESPONSES:
                    return _RESP_HDR.pack(16, pkt['xid'],
                                          pkt['zxid'], 0)
                return None
            return _RESP_HDR.pack(16, pkt['xid'], pkt['zxid'],
                                  _ERRNUM[err])
        except _FALLBACK_ERRORS:
            return None

    def _rs_stat_only(self, pkt):
        st = pkt['stat']
        if len(st) != 11:
            return None
        return _RESP_STAT.pack(84, pkt['xid'], pkt['zxid'], 0, *st)

    def _rs_get_data(self, pkt):
        d = pkt['data']
        st = pkt['stat']
        if len(st) != 11:
            return None
        dn = len(d)
        return b''.join((
            _RESP_BUF_HDR.pack(88 + dn, pkt['xid'], pkt['zxid'], 0,
                               dn if dn else -1),
            d, _STAT.pack(*st)))

    def _rs_create(self, pkt):
        p = pkt['path']
        if type(p) is not str:
            return None
        pb = p.encode('utf-8')
        n = len(pb)
        return _RESP_BUF_HDR.pack(20 + n, pkt['xid'], pkt['zxid'], 0,
                                  n if n else -1) + pb

    def _rs_notification(self, pkt):
        t = _NOTIFNUM[pkt['type']]
        s = _STATENUM[pkt['state']]
        p = pkt['path']
        if type(p) is not str:
            return None
        pb = p.encode('utf-8')
        n = len(pb)
        return _NOTIF_HDR.pack(28 + n, pkt['xid'], pkt['zxid'], 0,
                               t, s, n if n else -1) + pb

    def _rs_children(self, pkt):
        return self._children(pkt, with_stat=False)

    def _rs_children2(self, pkt):
        return self._children(pkt, with_stat=True)

    def _children(self, pkt, with_stat):
        kids = pkt['children']
        parts = [b'', _INT.pack(len(kids))]      # [0] holds the header
        size = 4
        for c in kids:
            cb = c.encode('utf-8')
            n = len(cb)
            parts.append(_INT.pack(n if n else -1))
            parts.append(cb)
            size += 4 + n
        if with_stat:
            st = pkt['stat']
            if len(st) != 11:
                return None
            parts.append(_STAT.pack(*st))
            size += 68
        parts[0] = _RESP_HDR.pack(16 + size, pkt['xid'],
                                  pkt['zxid'], 0)
        return b''.join(parts)

    def _rs_multi(self, pkt):
        parts = [b'']                 # [0] holds the reply header
        size = 0
        for res in pkt['results']:
            name = res['op']
            if name == 'error':
                code = _ERRNUM[res['err']]
                parts.append(_MULTI_HDR.pack(-1, 0, code))
                parts.append(_INT.pack(code))
                size += 13
                continue
            t = records.MULTI_OPS.get(name)
            if t is None:
                return None
            parts.append(_MULTI_HDR.pack(t, 0, 0))
            size += 9
            if name == 'create':
                p = res['path']
                if type(p) is not str:
                    return None
                pb = p.encode('utf-8')
                n = len(pb)
                parts.append(_INT.pack(n if n else -1))
                parts.append(pb)
                size += 4 + n
            elif name == 'set_data':
                st = res['stat']
                if len(st) != 11:
                    return None
                parts.append(_STAT.pack(*st))
                size += 68
        parts.append(_MULTI_END)
        size += 9
        parts[0] = _RESP_HDR.pack(16 + size, pkt['xid'],
                                  pkt['zxid'], 0)
        return b''.join(parts)

    def _rs_get_acl(self, pkt):
        acl = pkt['acl']
        ab = (_OPEN_ACL_BYTES if acl is records.OPEN_ACL_UNSAFE
              else _acl_bytes(acl))
        st = pkt['stat']
        if ab is None or len(st) != 11:
            return None
        return b''.join((
            _RESP_HDR.pack(84 + len(ab), pkt['xid'], pkt['zxid'], 0),
            ab, _STAT.pack(*st)))


#: opcode -> (encoder, wire opcode number); keep the COVERAGE in sync
#: with records._REQ_WRITERS (SET_WATCHES / SET_WATCHES2 are
#: resume-time-rare and stay on the spec path, like the C encoder).
_REQ_FAST = {
    'GET_CHILDREN': (FastEncoder._rq_path_watch,
                     int(OpCode.GET_CHILDREN)),
    'GET_CHILDREN2': (FastEncoder._rq_path_watch,
                      int(OpCode.GET_CHILDREN2)),
    'GET_DATA': (FastEncoder._rq_path_watch, int(OpCode.GET_DATA)),
    'EXISTS': (FastEncoder._rq_path_watch, int(OpCode.EXISTS)),
    'CREATE': (FastEncoder._rq_create, int(OpCode.CREATE)),
    'DELETE': (FastEncoder._rq_delete, int(OpCode.DELETE)),
    'GET_ACL': (FastEncoder._rq_path, int(OpCode.GET_ACL)),
    'SET_DATA': (FastEncoder._rq_set_data, int(OpCode.SET_DATA)),
    'SYNC': (FastEncoder._rq_path, int(OpCode.SYNC)),
    'ADD_WATCH': (FastEncoder._rq_add_watch, int(OpCode.ADD_WATCH)),
    'MULTI': (FastEncoder._rq_multi, int(OpCode.MULTI)),
    'CLOSE_SESSION': (FastEncoder._rq_bare, int(OpCode.CLOSE_SESSION)),
    'PING': (FastEncoder._rq_bare, int(OpCode.PING)),
}

#: reply opcode -> encoder; keep in sync with records._RESP_WRITERS.
_RESP_FAST = {
    'GET_CHILDREN': FastEncoder._rs_children,
    'GET_CHILDREN2': FastEncoder._rs_children2,
    'CREATE': FastEncoder._rs_create,
    'GET_ACL': FastEncoder._rs_get_acl,
    'GET_DATA': FastEncoder._rs_get_data,
    'NOTIFICATION': FastEncoder._rs_notification,
    'EXISTS': FastEncoder._rs_stat_only,
    'SET_DATA': FastEncoder._rs_stat_only,
    'MULTI': FastEncoder._rs_multi,
}
