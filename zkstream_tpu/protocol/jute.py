"""Jute primitive codec.

ZooKeeper's wire format is built from the Hadoop "jute" record primitives:
big-endian signed ints, 8-byte longs, single-byte booleans, int-length-
prefixed byte buffers and UTF-8 strings (reference: lib/jute-buffer.js).

Two asymmetric classes replace the reference's single auto-growing buffer:
``JuteWriter`` appends to a ``bytearray`` (which grows natively) and
``JuteReader`` walks a ``memoryview`` with strict bounds checks.  Python
ints replace the reference's jsbn BigIntegers / raw 8-byte buffers for
64-bit values (zxid, sessionId): they are decoded to plain ``int`` and
accepted as such on encode.

Wire quirks preserved intentionally:

- an *empty* buffer encodes its length as -1, not 0
  (reference: lib/jute-buffer.js:127-130);
- a *negative* buffer length on decode reads as an empty buffer
  (reference: lib/jute-buffer.js:99-100).
"""

from __future__ import annotations

import struct

_INT = struct.Struct('>i')
_LONG = struct.Struct('>q')
_struct_error = struct.error

INT32_MIN = -(1 << 31)
INT32_MAX = (1 << 31) - 1
INT64_MIN = -(1 << 63)
INT64_MAX = (1 << 63) - 1


class JuteTruncatedError(Exception):
    """Decode ran off the end of the buffer."""


class JuteValueError(Exception):
    """A value cannot be represented in the wire format."""


class JuteWriter:
    """Appends jute primitives to an internal growable byte buffer."""

    __slots__ = ('_buf',)

    def __init__(self) -> None:
        self._buf = bytearray()

    def to_bytes(self) -> bytes:
        return bytes(self._buf)

    def __len__(self) -> int:
        return len(self._buf)

    def write_byte(self, v: int) -> None:
        if not (-128 <= v <= 255):
            raise JuteValueError('byte out of range: %r' % (v,))
        self._buf.append(v & 0xff)

    def write_bool(self, v: bool) -> None:
        if not isinstance(v, bool):
            raise JuteValueError('bool expected, got %r' % (v,))
        self._buf.append(1 if v else 0)

    def write_int(self, v: int) -> None:
        if not (INT32_MIN <= v <= INT32_MAX):
            raise JuteValueError('int32 out of range: %r' % (v,))
        self._buf += _INT.pack(v)

    def write_long(self, v: int) -> None:
        if not (INT64_MIN <= v <= INT64_MAX):
            raise JuteValueError('int64 out of range: %r' % (v,))
        self._buf += _LONG.pack(v)

    def write_struct(self, st, *vals) -> None:
        """Encode a run of fixed-width fields in one call — the write
        twin of :meth:`JuteReader.read_struct` (``st`` is a precompiled
        big-endian ``struct.Struct`` of concatenated ints/longs)."""
        try:
            self._buf += st.pack(*vals)
        except _struct_error as e:
            raise JuteValueError(str(e)) from None

    def write_buffer(self, v: bytes) -> None:
        # Empty buffers go on the wire with length -1
        # (reference: lib/jute-buffer.js:127-130).
        if len(v) == 0:
            self.write_int(-1)
            return
        self.write_int(len(v))
        self._buf += v

    def write_ustring(self, v: str) -> None:
        self.write_buffer(v.encode('utf-8'))

    def write_length_prefixed(self, fn) -> None:
        """Reserve a 4-byte length slot, run ``fn(self)``, then backfill
        the slot with the number of bytes ``fn`` wrote
        (reference: lib/jute-buffer.js:181-189)."""
        at = len(self._buf)
        self._buf += b'\x00\x00\x00\x00'
        fn(self)
        _INT.pack_into(self._buf, at, len(self._buf) - at - 4)


class JuteReader:
    """Walks a byte buffer decoding jute primitives with bounds checks."""

    __slots__ = ('_view', '_off', '_end')

    def __init__(self, data, offset: int = 0, end: int | None = None):
        self._view = memoryview(data)
        self._off = offset
        self._end = len(self._view) if end is None else end

    @property
    def offset(self) -> int:
        return self._off

    def at_end(self) -> bool:
        return self._off >= self._end

    def remaining(self) -> int:
        return self._end - self._off

    def remainder(self) -> bytes:
        return bytes(self._view[self._off:self._end])

    def skip(self, n: int) -> None:
        self._need(n)
        self._off += n

    def _need(self, n: int) -> None:
        if self._off + n > self._end:
            raise JuteTruncatedError('need %d bytes at offset %d, have %d'
                % (n, self._off, self._end - self._off))

    def read_byte(self) -> int:
        self._need(1)
        v = self._view[self._off]
        self._off += 1
        return v - 256 if v >= 128 else v

    def read_bool(self) -> bool:
        self._need(1)
        v = self._view[self._off]
        self._off += 1
        if v not in (0, 1):
            raise JuteValueError('bad bool byte %d' % (v,))
        return v == 1

    def read_int(self) -> int:
        self._need(4)
        (v,) = _INT.unpack_from(self._view, self._off)
        self._off += 4
        return v

    def read_long(self) -> int:
        self._need(8)
        (v,) = _LONG.unpack_from(self._view, self._off)
        self._off += 8
        return v

    def read_struct(self, st) -> tuple:
        """Decode a run of fixed-width fields in one call.  ``st`` is a
        precompiled big-endian ``struct.Struct`` whose layout is a
        concatenation of jute ints/longs — semantically identical to
        the per-field reads but one bounds check and one C call for
        the whole run (the scalar decode hot path: see PROFILE.md)."""
        self._need(st.size)
        v = st.unpack_from(self._view, self._off)
        self._off += st.size
        return v

    def read_buffer(self) -> bytes:
        ln = self.read_int()
        # Negative length decodes as the empty buffer
        # (reference: lib/jute-buffer.js:99-100).
        if ln < 0:
            return b''
        self._need(ln)
        v = bytes(self._view[self._off:self._off + ln])
        self._off += ln
        return v

    def read_ustring(self) -> str:
        return self.read_buffer().decode('utf-8')

    def read_length_prefixed(self, fn):
        """Read a 4-byte length, run ``fn`` on a sub-reader restricted to
        that many bytes, and skip past them regardless of how much ``fn``
        consumed (reference: lib/jute-buffer.js:167-179)."""
        ln = self.read_int()
        if ln < 0:
            raise JuteValueError('negative scope length %d' % (ln,))
        self._need(ln)
        sub = JuteReader(self._view, self._off, self._off + ln)
        ret = fn(sub)
        self._off += ln
        return ret
