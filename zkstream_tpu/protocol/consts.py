"""ZooKeeper wire-protocol constant tables.

Protocol facts (opcodes, error codes, permission masks, create flags,
notification types, keeper states, special transaction ids) mirror the
reference client's tables (reference: lib/zk-consts.js:13-138) and the
upstream ZooKeeper jute definitions.  Expressed as Python enums so both
directions of lookup (name -> value, value -> name) come for free.
"""

from __future__ import annotations

import enum


class Perm(enum.IntFlag):
    """ACL permission bit-masks (reference: lib/zk-consts.js:13-19)."""

    READ = 1 << 0
    WRITE = 1 << 1
    CREATE = 1 << 2
    DELETE = 1 << 3
    ADMIN = 1 << 4

    ALL = READ | WRITE | CREATE | DELETE | ADMIN


class CreateFlag(enum.IntFlag):
    """Znode create-mode bit-masks (reference: lib/zk-consts.js:21-24)."""

    EPHEMERAL = 1 << 0
    SEQUENTIAL = 1 << 1


class ErrCode(enum.IntEnum):
    """Server error codes (reference: lib/zk-consts.js:26-47)."""

    OK = 0
    SYSTEM_ERROR = -1
    RUNTIME_INCONSISTENCY = -2
    DATA_INCONSISTENCY = -3
    CONNECTION_LOSS = -4
    MARSHALLING_ERROR = -5
    UNIMPLEMENTED = -6
    OPERATION_TIMEOUT = -7
    BAD_ARGUMENTS = -8
    API_ERROR = -100
    NO_NODE = -101
    NO_AUTH = -102
    BAD_VERSION = -103
    NO_CHILDREN_FOR_EPHEMERALS = -108
    NODE_EXISTS = -110
    NOT_EMPTY = -111
    SESSION_EXPIRED = -112
    INVALID_CALLBACK = -113
    INVALID_ACL = -114
    AUTH_FAILED = -115
    #: This stack's own (no reference analogue): a write reached a
    #: member whose leadership epoch is stale — a deposed leader, or a
    #: follower forwarding under an epoch the quorum has moved past
    #: (server/election.py).  Typed, definite failure: the write was
    #: NOT applied; retry after the member rejoins the current epoch.
    EPOCH_FENCED = -130
    #: This stack's own (no reference analogue): the serving member is
    #: shedding load — its global memory watermark is crossed and new
    #: writes bounce while reads keep flowing (io/overload.py).  Typed,
    #: definite failure: the write was NOT applied; the client backs
    #: off and retries (capped exponential, client.py).
    THROTTLED = -131


#: Human-readable explanations for ErrCode values
#: (reference: lib/zk-consts.js:53-82).
ERR_TEXT: dict[str, str] = {
    'SYSTEM_ERROR': 'An unknown system error occurred on the ZooKeeper '
        'server',
    'RUNTIME_INCONSISTENCY': 'A runtime inconsistency was found, and the '
        'request aborted for safety',
    'DATA_INCONSISTENCY': 'A data inconsistency was found, and the request '
        'aborted for safety',
    'CONNECTION_LOSS': 'Connection to the ZooKeeper server has been lost',
    'MARSHALLING_ERROR': 'Error while marshalling or unmarshalling data',
    'UNIMPLEMENTED': 'ZooKeeper request unimplemented',
    'OPERATION_TIMEOUT': 'ZooKeeper operation timed out',
    'BAD_ARGUMENTS': 'Bad arguments to ZooKeeper request',
    'API_ERROR': '',
    'NO_NODE': 'The specified ZooKeeper path does not exist',
    'NO_AUTH': 'Request requires authentication and your ZooKeeper '
        'connection is anonymous',
    'BAD_VERSION': 'A specific version of an object was named in the '
        'request, but this was not the latest version on the server. The '
        'object may have been changed by another client.',
    'NO_CHILDREN_FOR_EPHEMERALS': 'Ephemeral nodes cannot have children',
    'NODE_EXISTS': 'The specified ZooKeeper path already exists, and the '
        'requested operation requires creating a new node',
    'NOT_EMPTY': 'The specified ZooKeeper node has children and thus '
        'cannot be destroyed',
    'SESSION_EXPIRED': 'ZooKeeper session expired',
    'INVALID_CALLBACK': '',
    'INVALID_ACL': 'The given ZooKeeper ACL was found to be invalid on '
        'the server side',
    'AUTH_FAILED': 'ZooKeeper authentication failed',
    'EPOCH_FENCED': 'The serving member\'s leadership epoch is stale '
        '(a newer leader has been elected); the write was rejected, '
        'not applied',
    'THROTTLED': 'The serving member is overloaded and shedding new '
        'writes (reads keep flowing); the write was rejected, not '
        'applied — back off and retry',
}


class OpCode(enum.IntEnum):
    """Request opcodes (reference: lib/zk-consts.js:84-105)."""

    NOTIFICATION = 0
    CREATE = 1
    DELETE = 2
    EXISTS = 3
    GET_DATA = 4
    SET_DATA = 5
    GET_ACL = 6
    SET_ACL = 7
    GET_CHILDREN = 8
    SYNC = 9
    PING = 11
    GET_CHILDREN2 = 12
    CHECK = 13
    MULTI = 14
    AUTH = 100
    SET_WATCHES = 101
    SASL = 102
    #: This stack's extension beyond the reference client (whose
    #: consts table stops at SASL): the upstream ZooKeeper 3.6+
    #: persistent-watch opcode family.  ADD_WATCH arms a watch that
    #: SURVIVES fires (mode below); SET_WATCHES2 is the reconnect
    #: replay carrying the two persistent lists alongside the three
    #: legacy one-shot lists.
    ADD_WATCH = 106
    SET_WATCHES2 = 107
    CREATE_SESSION = -10
    CLOSE_SESSION = -11
    ERROR = -1


class AddWatchMode(enum.IntEnum):
    """ADD_WATCH subscription modes (upstream ZooKeeper AddWatchMode).

    PERSISTENT: survives fires on the exact node, receives every
    notification type.  PERSISTENT_RECURSIVE: survives fires and
    matches the node plus every descendant, receiving CREATED /
    DELETED / DATA_CHANGED (no CHILDREN_CHANGED — a recursive
    subscriber sees the child's own CREATED/DELETED instead)."""

    PERSISTENT = 0
    PERSISTENT_RECURSIVE = 1


class NotificationType(enum.IntEnum):
    """Watch-event types carried in NOTIFICATION packets
    (reference: lib/zk-consts.js:111-116)."""

    CREATED = 1
    DELETED = 2
    DATA_CHANGED = 3
    CHILDREN_CHANGED = 4


class KeeperState(enum.IntEnum):
    """Keeper states carried in NOTIFICATION packets
    (reference: lib/zk-consts.js:122-129)."""

    DISCONNECTED = 0
    SYNC_CONNECTED = 3
    AUTH_FAILED = 4
    CONNECTED_READ_ONLY = 5
    SASL_AUTHENTICATED = 6
    EXPIRED = -122


#: Reserved transaction ids: replies carrying one of these are not matched
#: against an outstanding request's xid (reference: lib/zk-consts.js:135-138).
XID_NOTIFICATION = -1
XID_PING = -2
XID_AUTHENTICATION = -4
XID_SET_WATCHES = -8

#: Reply xid -> pseudo-opcode for the special xids above
#: (reference: lib/zk-buffer.js:275-279).
SPECIAL_XIDS: dict[int, str] = {
    XID_NOTIFICATION: 'NOTIFICATION',
    XID_PING: 'PING',
    XID_AUTHENTICATION: 'AUTH',
    XID_SET_WATCHES: 'SET_WATCHES',
}

#: Only protocol version 0 is spoken (reference: lib/connection-fsm.js:141).
PROTOCOL_VERSION = 0

#: Frame-size sanity cap applied by the decoder
#: (reference: lib/zk-streams.js:23).
MAX_PACKET = 16 * 1024 * 1024

#: Reply header width: xid:int32 + zxid:int64 + err:int32
#: (reference: lib/zk-buffer.js:281-284).
REPLY_HDR = 16


def err_name(code: int) -> str:
    """Map a numeric error code to its name; unknown codes become
    ``'ERROR_<n>'`` rather than raising, since a misbehaving server must
    not crash the decoder."""
    try:
        return ErrCode(code).name
    except ValueError:
        return 'ERROR_%d' % (code,)


def op_name(code: int) -> str:
    """Map a numeric opcode to its name (raises ValueError if unknown)."""
    return OpCode(code).name
