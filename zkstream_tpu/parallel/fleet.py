"""Mesh-aware fleet ingest: the runtime consumer of the sharded plane.

:class:`zkstream_tpu.io.ingest.FleetIngest` batches a live connection
fleet's receive streams into one decode dispatch per event-loop tick;
this subclass runs that tick's program **dp-sharded over a device
mesh** via ``shard_map`` — the runtime twin of
:func:`zkstream_tpu.parallel.sharded.sharded_wire_step` (which is the
tested unit) — and reduces fleet-global session statistics with XLA
collectives on the way:

- per-stream planes stay ``P('dp', None)``-sharded end to end: each
  device decodes the connections of its shard, and the host reads back
  one packed array exactly as in the single-device ingest;
- the fleet-wide reductions — total frames / replies / notifications /
  pings / errors and the **fleet max zxid** (the resume checkpoint a
  multi-host session manager persists, the distributed analogue of
  lib/zk-session.js:229-235) — run as ``psum`` / unsigned-64 ``pmax``
  collectives over the ``dp`` axis inside the same dispatch, and ride
  back appended to the packed array: zero extra readbacks.

On a multi-host pod slice the same class works over a global mesh with
per-host connection slots (see parallel/multihost.py); the integration
tests drive it on the virtual 8-device CPU mesh with live in-process
connections (tests/test_mesh_ingest.py), and ``__graft_entry__``'s
``dryrun_multichip`` executes it as part of the driver's multi-chip
validation.
"""

from __future__ import annotations

from ..io.ingest import FleetIngest
from ..ops.bytesops import i64pair_to_int
from .mesh import make_mesh

#: appended global columns: frames, replies, notifications, pings,
#: errors, max_zxid_hi, max_zxid_lo
_N_GLOBALS = 7


class MeshFleetIngest(FleetIngest):
    """FleetIngest whose tick program is dp-sharded over ``mesh``.

    Args:
      mesh: a ``(dp, sp)`` mesh (default: all devices on the dp axis).
      **kw: forwarded to :class:`FleetIngest`.  ``bypass_bytes``
        defaults to 0 here — a mesh proxy exists to run the device
        plane, not to bypass it.
    """

    def __init__(self, mesh=None, **kw):
        kw.setdefault('bypass_bytes', 0)
        super().__init__(**kw)
        self.mesh = mesh if mesh is not None else make_mesh()
        #: fleet-global stats of the LAST device tick (None before the
        #: first); scalar/warming ticks do not update it.
        self.global_stats: dict | None = None
        #: running fleet-wide maximum zxid over all device ticks — the
        #: checkpoint a proxy-level session manager would persist.
        self.fleet_max_zxid = 0

    # the mesh decides placement; the latency probe is meaningless here
    def _resolve_placement(self) -> None:
        self._placed = True

    def _bucket(self, n_streams: int, nbytes: int) -> tuple:
        dev, Bp, L = super()._bucket(n_streams, nbytes)
        dp = self.mesh.shape['dp']
        # the batch axis must divide over dp shards
        Bp = max(Bp, dp)
        Bp = ((Bp + dp - 1) // dp) * dp
        return dev, Bp, L

    def _step_fn(self, device_bodies: bool):
        fn = self._fns.get(device_bodies)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from ..ops.bytesops import u64pair_reduce_max
        from .sharded import _u64_axis_max

        def local(buf, lens):
            st, ints, byts = self._trace_step(buf, lens, device_bodies)
            lh, ll = u64pair_reduce_max(st.max_zxid_hi, st.max_zxid_lo)
            gh, gl = _u64_axis_max(lh, ll, 'dp')
            g = jnp.stack([
                lax.psum(jnp.sum(st.n_frames), 'dp'),
                lax.psum(jnp.sum(st.n_replies), 'dp'),
                lax.psum(jnp.sum(st.n_notifications), 'dp'),
                lax.psum(jnp.sum(st.n_pings), 'dp'),
                lax.psum(jnp.sum(st.n_errors), 'dp'),
                gh, gl])
            # replicated globals ride appended to each local row: the
            # packed readback stays one array, zero extra transfers
            ints = jnp.concatenate(
                [ints, jnp.broadcast_to(g, (ints.shape[0],
                                            _N_GLOBALS))], axis=1)
            return (ints, byts) if device_bodies else ints

        out_specs = ((P('dp', None), P('dp', None, None))
                     if device_bodies else P('dp', None))
        fn = jax.jit(shard_map(
            local, mesh=self.mesh,
            in_specs=(P('dp', None), P('dp')),
            out_specs=out_specs))
        self._fns[device_bodies] = fn
        return fn

    def _unpack(self, ints, byts):
        g = ints[0, -_N_GLOBALS:]
        self.global_stats = {
            'total_frames': int(g[0]),
            'total_replies': int(g[1]),
            'total_notifications': int(g[2]),
            'total_pings': int(g[3]),
            'total_errors': int(g[4]),
            'max_zxid': i64pair_to_int(g[5], g[6]),
        }
        self.fleet_max_zxid = max(self.fleet_max_zxid,
                                  self.global_stats['max_zxid'])
        return super()._unpack(ints[:, :-_N_GLOBALS], byts)
