"""Mesh-aware fleet ingest: the runtime consumer of the sharded plane.

:class:`zkstream_tpu.io.ingest.FleetIngest` batches a live connection
fleet's receive streams into one decode dispatch per event-loop tick;
this subclass runs that tick's program **dp-sharded over a device
mesh** via ``shard_map`` — the runtime twin of
:func:`zkstream_tpu.parallel.sharded.sharded_wire_step` (which is the
tested unit) — and reduces fleet-global session statistics with XLA
collectives on the way:

- per-stream planes stay ``P('dp', None)``-sharded end to end: each
  device decodes the connections of its shard, and the host reads back
  one packed array exactly as in the single-device ingest;
- the fleet-wide reductions — total frames / replies / notifications /
  pings / errors and the **fleet max zxid** (the resume checkpoint a
  multi-host session manager persists, the distributed analogue of
  lib/zk-session.js:229-235) — run as ``psum`` / unsigned-64 ``pmax``
  collectives over the ``dp`` axis inside the same dispatch, and ride
  back appended to the packed array: zero extra readbacks.

On a multi-host pod slice the same class works over a global mesh with
per-host connection slots (see parallel/multihost.py); the integration
tests drive it on the virtual 8-device CPU mesh with live in-process
connections (tests/test_mesh_ingest.py), and ``__graft_entry__``'s
``dryrun_multichip`` executes it as part of the driver's multi-chip
validation.
"""

from __future__ import annotations

import numpy as np

from ..io.ingest import FleetIngest
from ..ops.bytesops import i64pair_to_int
from .mesh import make_mesh

#: appended global columns: frames, replies, notifications, pings,
#: errors, max_zxid_hi, max_zxid_lo
_N_GLOBALS = 7


class MeshFleetIngest(FleetIngest):
    """FleetIngest whose tick program is dp-sharded over ``mesh``.

    Args:
      mesh: a ``(dp, sp)`` mesh (default: all devices on the dp axis).
      **kw: forwarded to :class:`FleetIngest`.  ``bypass_bytes``
        defaults to 0 here — a mesh proxy exists to run the device
        plane, not to bypass it.
    """

    def __init__(self, mesh=None, **kw):
        kw.setdefault('bypass_bytes', 0)
        # a mesh proxy exists to run the device plane — and the guard's
        # single-core cost model does not describe a real accelerator
        kw.setdefault('frag_guard', False)
        super().__init__(**kw)
        self.mesh = mesh if mesh is not None else make_mesh()
        #: fleet-global stats of the LAST device tick (None before the
        #: first); scalar/warming ticks do not update it.
        self.global_stats: dict | None = None
        #: running fleet-wide maximum zxid over all device ticks — the
        #: checkpoint a proxy-level session manager would persist.
        self.fleet_max_zxid = 0

    # the mesh decides placement; the latency probe is meaningless here
    def _resolve_placement(self) -> None:
        self._placed = True

    def bind_metrics(self, collector, prefix: str = '') -> None:
        super().bind_metrics(collector, prefix)
        collector.gauge(
            prefix + 'zkstream_fleet_max_zxid',
            lambda: self.fleet_max_zxid,
            'fleet-global max zxid (pmax over the mesh) — the '
            'proxy-level session resume checkpoint')

    def _bucket(self, n_streams: int, nbytes: int) -> tuple:
        dev, Bp, L = super()._bucket(n_streams, nbytes)
        dp = self.mesh.shape['dp']
        # the batch axis must divide over dp shards
        Bp = max(Bp, dp)
        Bp = ((Bp + dp - 1) // dp) * dp
        return dev, Bp, L

    def _step_fn(self, device_bodies: bool):
        fn = self._fns.get(device_bodies)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from ..ops.bytesops import u64pair_reduce_max
        from .sharded import _u64_axis_max

        def local(buf, lens):
            st, ints, byts = self._trace_step(buf, lens, device_bodies)
            lh, ll = u64pair_reduce_max(st.max_zxid_hi, st.max_zxid_lo)
            gh, gl = _u64_axis_max(lh, ll, 'dp')
            g = jnp.stack([
                lax.psum(jnp.sum(st.n_frames), 'dp'),
                lax.psum(jnp.sum(st.n_replies), 'dp'),
                lax.psum(jnp.sum(st.n_notifications), 'dp'),
                lax.psum(jnp.sum(st.n_pings), 'dp'),
                lax.psum(jnp.sum(st.n_errors), 'dp'),
                gh, gl])
            # replicated globals ride appended to each local row: the
            # packed readback stays one array, zero extra transfers
            ints = jnp.concatenate(
                [ints, jnp.broadcast_to(g, (ints.shape[0],
                                            _N_GLOBALS))], axis=1)
            return (ints, byts) if device_bodies else ints

        out_specs = ((P('dp', None), P('dp', None, None))
                     if device_bodies else P('dp', None))
        fn = jax.jit(shard_map(
            local, mesh=self.mesh,
            in_specs=(P('dp', None), P('dp')),
            out_specs=out_specs))
        self._fns[device_bodies] = fn
        return fn

    def _unpack(self, ints, byts):
        g = ints[0, -_N_GLOBALS:]
        self.global_stats = {
            'total_frames': int(g[0]),
            'total_replies': int(g[1]),
            'total_notifications': int(g[2]),
            'total_pings': int(g[3]),
            'total_errors': int(g[4]),
            'max_zxid': i64pair_to_int(g[5], g[6]),
        }
        self.fleet_max_zxid = max(self.fleet_max_zxid,
                                  self.global_stats['max_zxid'])
        return super()._unpack(ints[:, :-_N_GLOBALS], byts)


class MultihostFleetIngest(MeshFleetIngest):
    """Multi-controller fleet proxy: every host of a pod slice serves
    its own live connections through ONE globally sharded tick program.

    The single-host ingest ticks when bytes arrive; that cannot work
    multi-controller — a ``shard_map`` program over a global mesh is a
    collective launch, so every process must launch the same program
    the same number of times.  This class therefore runs on a **fixed
    cadence with fixed shapes**:

    - capacity is static: ``local_rows`` connection slots per host,
      each up to ``stream_len`` buffered bytes per tick (a longer
      backlog carries over — the decode consumes whole frames and
      leaves the remainder buffered);
    - a timer fires every ``tick_interval`` seconds and ALWAYS
      dispatches, even with every slot empty (empty rows decode zero
      frames) — no data-dependent control flow, so the SPMD launch
      counts stay aligned across hosts with at most one interval of
      skew;
    - each host assembles only its own rows
      (:func:`~zkstream_tpu.parallel.multihost.host_local_wire_batch`
      — no cross-host stream bytes, ICI/DCN carries just the psum/pmax
      scalars) and reads back only its addressable shards;
    - the fleet-global stats (total frames, fleet max zxid — the
      resume checkpoint of the WHOLE pod's session population) reduce
      across all hosts inside the dispatch.

    Lifecycle: ``start()`` begins the cadence; ``await
    stop(after_ticks=N)`` stops once N total ticks have run — stopping
    must be coordinated (same N everywhere), because a host that
    stops launching strands the others' collectives; that is the
    multi-controller contract, not a quirk of this class.

    Driven two-process in tests/test_multihost.py
    (multihost_fleet_worker.py) and single-process in
    tests/test_mesh_ingest.py.
    """

    def __init__(self, mesh=None, local_rows: int = 8,
                 stream_len: int = 4096,
                 tick_interval: float = 0.005, **kw):
        import jax

        kw.setdefault('min_len', stream_len)
        super().__init__(mesh=mesh, **kw)
        dp = self.mesh.shape['dp']
        global_rows = local_rows * jax.process_count()
        if global_rows % dp:
            raise ValueError(
                'local_rows=%d x %d processes = %d global rows must '
                'divide over the dp axis (%d)' %
                (local_rows, jax.process_count(), global_rows, dp))
        self.local_rows = local_rows
        self.stream_len = stream_len
        self.tick_interval = tick_interval
        self.tick_count = 0
        #: collective launches actually dispatched; == tick_count
        #: unless a dispatch itself failed (host-side assembly failures
        #: fall back to an empty aligned launch and so keep the two
        #: equal).  ``stop`` checks the invariant loudly.
        self.launch_count = 0
        self._rows: dict[int, int] = {}       # id(conn) -> row
        self._free = list(range(local_rows - 1, -1, -1))
        self._timer = None
        self._stop_at: int | None = None
        #: monotonic time of the last capacity warning; overflow warns
        #: at most once per interval so churn at saturation neither
        #: floods the log nor runs silent (one latch forever would)
        self._warned_capacity_at = float('-inf')

    # event-driven scheduling is disabled: the cadence launches ticks
    def _schedule(self) -> None:
        pass

    def register(self, conn) -> None:
        # Never raise here: register runs inside the connection FSM's
        # state-entry handler, and an exception there would strand a
        # half-wired connection.  Overflow connections get no row —
        # the cadence drains them through the scalar codec instead.
        if self._free:
            self._rows[id(conn)] = self._free.pop()
        else:
            import time
            now = time.monotonic()
            if now - self._warned_capacity_at >= 30.0:
                self._warned_capacity_at = now
                self.log.warning(
                    'MultihostFleetIngest capacity exceeded '
                    '(local_rows=%d); overflow connections are served '
                    'by the scalar drain — size the proxy for the '
                    'host\'s connection budget', self.local_rows)
        super().register(conn)

    def unregister(self, conn) -> None:
        row = self._rows.pop(id(conn), None)
        if row is not None:
            self._free.append(row)
        super().unregister(conn)

    def start(self) -> None:
        """Begin the tick cadence on the running loop."""
        import asyncio

        if self._timer is None:
            self._timer = asyncio.get_running_loop().create_task(
                self._cadence())

    def warmup_tick(self) -> None:
        """Run ONE aligned collective tick synchronously — call it the
        same number of times on every host before ``start()`` to pay
        the XLA compile outside any session's clock."""
        self._mh_tick()

    async def prewarm(self, n_streams: int,
                      nbytes: int | None = None) -> None:
        raise NotImplementedError(
            'MultihostFleetIngest compiles one fixed-shape GLOBAL '
            'program; use warmup_tick() — the same number of times on '
            'every host — instead of the per-bucket prewarm')

    async def stop(self, after_ticks: int | None = None) -> None:
        """Stop the cadence.  With ``after_ticks`` (the coordinated
        form — pass the SAME value on every host) the cadence runs out
        to exactly that launch count and exits by itself, so every
        process ends with identical collective launch counts; without
        it the timer is cancelled immediately (single-process use)."""
        import asyncio

        if self._timer is None:
            return
        if after_ticks is not None:
            if self.tick_count > after_ticks:
                # the alignment contract is already broken — failing
                # loudly beats stranding the other hosts' collectives
                raise RuntimeError(
                    'stop(after_ticks=%d) but %d ticks already ran; '
                    'launch counts would diverge across hosts'
                    % (after_ticks, self.tick_count))
            self._stop_at = after_ticks
            await self._timer
        else:
            self._timer.cancel()
            try:
                await self._timer
            except asyncio.CancelledError:
                pass
        self._timer = None
        if self.launch_count != self.tick_count:
            # a dispatch failed somewhere along the run: this host
            # launched fewer collectives than its cadence counted, so
            # the other hosts' matching collectives are stranded —
            # surface it here rather than letting them hang silently
            raise RuntimeError(
                'collective launch divergence: %d launches for %d '
                'ticks — a dispatch failed mid-cadence; the other '
                'hosts\' launch counts no longer match this one'
                % (self.launch_count, self.tick_count))

    async def _cadence(self) -> None:
        import asyncio

        while self._stop_at is None or self.tick_count < self._stop_at:
            await asyncio.sleep(self.tick_interval)
            if self._stop_at is not None \
                    and self.tick_count >= self._stop_at:
                # stop() landed mid-sleep after the loop check: one
                # more tick here would exceed the coordinated launch
                # count and strand the other hosts' collectives
                break
            try:
                self._mh_tick()
            except Exception:
                # keep launching: a dead cadence on one host strands
                # every other host's collectives (their readbacks
                # block), turning one local error into a fleet-wide
                # stall.  Pre-dispatch host-side errors fall back to
                # an empty aligned launch inside _mh_tick; what
                # reaches here is a failed dispatch (or an empty
                # launch that itself failed) or a routing/delivery
                # error after the dispatch — either way the cadence
                # continues and ``stop``'s launch/tick invariant says
                # whether alignment held.
                self.log.exception('multihost tick failed; '
                                   'cadence continues')

    def _local_view(self, arr):
        """This process's rows of a dp-sharded global array, in row
        order (the inverse of host_local_wire_batch's placement)."""
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return np.concatenate([np.asarray(s.data) for s in shards],
                              axis=0)

    def _assemble_tick(self):
        """Host-side tick assembly: copy each rowed connection's
        buffered bytes into the fixed-shape local batch.  Returns
        (batch, lens, active, overflow)."""
        batch = np.zeros((self.local_rows, self.stream_len), np.uint8)
        lens = np.zeros((self.local_rows,), np.int32)
        active = {}
        overflow = []
        for cid, (conn, buf) in list(self._slots.items()):
            if not buf or not conn.is_in_state('connected'):
                continue
            row = self._rows.get(cid)
            if row is None:          # over capacity: scalar-drained
                overflow.append((conn, buf))
                continue
            n = min(len(buf), self.stream_len)
            batch[row, :n] = np.frombuffer(memoryview(buf)[:n],
                                           np.uint8)
            lens[row] = n
            active[row] = (conn, buf)
        return batch, lens, active, overflow

    def _mh_tick(self) -> None:
        from .multihost import host_local_wire_batch

        self.tick_count += 1
        device = self.body_mode == 'device'
        try:
            batch, lens, active, overflow = self._assemble_tick()
            fn = self._step_fn(device)
            gbuf, glens = host_local_wire_batch(self.mesh, batch, lens)
        except Exception:
            # A pre-dispatch host-side failure (assembly, tracing, or
            # the device placement of the local shards) must not skip
            # the collective launch — the other hosts' matching
            # launches would strand.  Retry the whole pre-dispatch
            # path with an EMPTY batch: nothing was consumed, so the
            # buffered bytes are intact and the next healthy tick
            # delivers them one interval late.  If even the empty
            # placement fails, the launch is genuinely impossible —
            # the error propagates and ``stop``'s launch/tick check
            # reports the divergence.
            self.log.exception('multihost tick pre-dispatch failed; '
                               'launching an empty aligned tick')
            batch = np.zeros((self.local_rows, self.stream_len),
                             np.uint8)
            lens = np.zeros((self.local_rows,), np.int32)
            active, overflow = {}, []
            fn = self._step_fn(device)
            gbuf, glens = host_local_wire_batch(self.mesh, batch, lens)
        # the launch itself is unconditional — collective alignment.
        # Global stats read back on every tick (they carry the OTHER
        # hosts' traffic too); the body planes only when this host has
        # frames to route.
        if device:
            ints, byts = fn(gbuf, glens)
            self.launch_count += 1
            byts = self._local_view(byts) if active else None
        else:
            ints = fn(gbuf, glens)
            self.launch_count += 1
            byts = None
        ints = self._local_view(ints)
        st, bd = self._unpack(ints, byts)
        for conn, buf in overflow:
            if id(conn) in self._slots:
                self._deliver_scalar(conn, buf)
        if not active:
            return
        self.ticks += 1

        for row, (conn, buf) in active.items():
            # an earlier row's delivery callback may have torn this
            # connection down mid-tick (unregister already restored
            # its bytes to the codec)
            if id(conn) not in self._slots:
                continue
            if (int(st.n_frames[row]) == 0 and not bool(st.bad[row])
                    and int(st.resid[row]) == 0
                    and len(buf) >= self.stream_len):
                # a single frame larger than stream_len can never fit
                # a fixed-shape tick: drain this stream through the
                # scalar codec (which has no length bound) instead of
                # re-dispatching the same prefix forever
                self._deliver_scalar(conn, buf)
                continue
            self._route_stream(conn, buf, st, bd, row)
