"""Device-mesh construction helpers.

A 2-D ``dp × sp`` mesh covers this framework's parallelism needs:
``dp`` shards the connection-stream batch (data parallel), ``sp``
shards the byte axis of long streams (sequence parallel).  Axes of
size 1 are always present so the same ``PartitionSpec``s work at any
scale — single chip through pod slice.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(dp: int | None = None, sp: int = 1, devices=None) -> Mesh:
    """Build a ``(dp, sp)`` mesh over ``devices`` (default: all).

    With ``dp=None`` the data-parallel axis absorbs every device not
    used by ``sp``.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if dp is None:
        if n % sp:
            raise ValueError(f'{n} devices not divisible by sp={sp}')
        dp = n // sp
    if dp * sp != n:
        raise ValueError(f'dp*sp = {dp * sp} != {n} devices')
    arr = np.asarray(devices).reshape(dp, sp)
    return Mesh(arr, ('dp', 'sp'))
