"""Sequence-parallel frame scan: one long stream sharded on its byte axis.

The reference's decoder is inherently serial — each frame's position
depends on the previous frame's length (lib/zk-streams.js:39-64).  To
scan a stream far larger than one device's memory, shard the byte axis
over the mesh's ``sp`` axis and hand the frame cursor across shard
boundaries with a ``ppermute`` ring:

1. **Halo exchange** — each shard sends its first 4 bytes to its left
   neighbor, so a length prefix straddling a boundary is readable
   locally.
2. **Ring propagation** — shard 0 starts with cursor 0; each shard,
   once it knows its entry cursor, walks its local frames (a bounded
   ``while_loop``) and forwards its exit cursor to the right neighbor.
   After ``p - 1`` ring steps every shard knows where its first frame
   begins, even when a single frame body spans whole shards (the
   cursor just passes through).
3. **Local mark** — each shard emits the frame-start mask for its own
   chunk.

Wall-clock is O(p) ring steps.  A log(p) variant (pre-computing each
shard's entry→exit map by pointer doubling, then composing maps) was
considered and rejected: composing maps means exchanging O(chunk)
payloads per doubling step where the ring sends a single int32 cursor
per step, so for practical mesh sizes the ring's p tiny hops beat
log(p) heavy ones.  Revisit only if p grows past a few dozen.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..ops.bytesops import be_i32_at
from ..ops.frame_scan import MAX_PACKET


def _walk(ext, base, chunk_end, n, entry):
    """Walk frames from absolute cursor ``entry`` until past
    ``chunk_end`` (or the stream ends / goes bad).

    ``ext`` is the local chunk plus a 4-byte right halo.  Returns
    (exit_cursor, start_mask[C], bad).
    """
    C = ext.shape[0] - 4

    def cond(c):
        q, mask, bad, stop = c
        return ~stop & ~bad & (q < chunk_end) & (q + 4 <= n)

    def body(c):
        q, mask, bad, stop = c
        lq = q - base
        ln = be_i32_at(ext[None, :], lq[None])[0]
        is_bad = (ln < 0) | (ln > MAX_PACKET)
        complete = ~is_bad & (q + 4 + ln <= n)
        mask = jnp.where(complete & (lq >= 0) & (lq < C),
                         mask.at[jnp.clip(lq, 0, C - 1)].set(True), mask)
        qn = jnp.where(complete, q + 4 + ln, q)
        return qn, mask, bad | is_bad, ~complete

    # init carries derived from shard-local values (not fresh
    # constants) so they are varying over sp from the start — while_loop
    # requires carry in/out types, including varying-axis sets, to match
    never = base < 0  # False, but varying over sp
    init = (entry.astype(jnp.int32) + base * 0,
            jnp.zeros((C,), jnp.bool_) | never,
            never, never)
    q, mask, bad, stopped = lax.while_loop(cond, body, init)
    # a bad prefix or truncated frame ends the whole stream's decode:
    # saturate the exit cursor so downstream shards see entry past
    # their chunk and do nothing (the sequential decoder's stop-at-
    # error behavior, lib/zk-streams.js:47-53)
    q = jnp.where(bad | stopped, jnp.int32(1 << 30), q)
    return q, mask, bad


def seq_parallel_frame_scan(mesh: Mesh):
    """Build the jitted sp-sharded scan for ``mesh``.

    Returns ``scan(buf, n) -> (is_start, total_frames, bad)`` where
    ``buf`` is uint8 [N] with N divisible by the sp axis size, ``n`` is
    the valid length, ``is_start`` is bool [N] marking each complete
    frame's prefix offset (sharded over sp), and ``total_frames`` /
    ``bad`` are replicated scalars.
    """
    p = mesh.shape['sp']
    fwd = [(i, (i + 1) % p) for i in range(p)]
    bwd = [((i + 1) % p, i) for i in range(p)]

    def local(buf, n):
        C = buf.shape[0]
        idx = lax.axis_index('sp')
        base = (idx * C).astype(jnp.int32)
        chunk_end = jnp.minimum(base + C, n).astype(jnp.int32)
        halo = lax.ppermute(buf[:4], 'sp', bwd)
        ext = jnp.concatenate([buf, halo])

        valid = idx == 0
        entry = base * 0
        C_local = buf.shape[0]

        def walk_from(e):
            return _walk(ext, base, chunk_end, n, e)

        def keep(state):
            def f(_):
                return state
            return f

        # Each shard walks its chunk EXACTLY once — when it learns its
        # entry cursor (shard 0 at init, others on adopt) — and carries
        # the resulting (exit, mask, bad) through the ring.  Shards
        # whose turn hasn't come skip the walk via lax.cond (a real
        # branch per device under shard_map, not a select).
        zero_state = (jnp.int32(-1) + base * 0,
                      jnp.zeros((C_local,), jnp.bool_) | (base < 0),
                      base < 0)
        state = lax.cond(valid, walk_from, keep(zero_state), entry)

        def ring_step(carry, _):
            valid, entry, state = carry
            exit_q = state[0]
            snd = jnp.where(valid, exit_q, -1)
            rcv = lax.ppermute(snd, 'sp', fwd)
            adopt = ~valid & (rcv >= 0)
            entry = jnp.where(adopt, rcv, entry)
            state = lax.cond(adopt, walk_from, keep(state), entry)
            return (valid | adopt, entry, state), None

        (valid, entry, state), _ = lax.scan(
            ring_step, (valid, entry, state), None, length=max(p - 1, 1))
        _, mask, bad = state
        total = lax.psum(jnp.sum(mask.astype(jnp.int32)), 'sp')
        any_bad = lax.psum(bad.astype(jnp.int32), 'sp') > 0
        return mask, total, any_bad

    sharded = shard_map(
        local,
        mesh=mesh,
        in_specs=(P('sp'), P()),
        out_specs=(P('sp'), P(), P()),
    )
    return jax.jit(sharded)
