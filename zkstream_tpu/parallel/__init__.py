"""Distributed data plane: mesh construction and sharded wire decode.

The reference's "distributed" machinery is a client-side ensemble pool
over raw TCP (lib/client.js:88-118) — there are no collectives to
translate.  What *does* shard on a TPU pod is the data plane built in
:mod:`zkstream_tpu.ops`: a fleet of connection streams decodes
data-parallel over a device mesh, global session statistics reduce with
``psum``/``pmax`` over ICI, and a single long stream can be scanned
sequence-parallel along its byte axis with a ``ppermute`` ring carrying
the frame cursor across shard boundaries.

- :mod:`mesh` — mesh construction helpers (dp × sp axes).
- :mod:`sharded` — ``shard_map`` batched decode + collective reductions.
- :mod:`seqscan` — byte-axis sequence-parallel frame scan (ring
  cursor hand-off via ``ppermute``).
- :mod:`fleet` — :class:`MeshFleetIngest`, the runtime consumer: a
  live connection fleet's per-tick decode dp-sharded over the mesh.
"""

from .fleet import MeshFleetIngest, MultihostFleetIngest
from .mesh import make_mesh
from .multihost import host_local_wire_batch, initialize
from .sharded import sharded_wire_roundtrip, sharded_wire_step
from .seqscan import seq_parallel_frame_scan

__all__ = ['MeshFleetIngest', 'MultihostFleetIngest',
           'host_local_wire_batch', 'initialize',
           'make_mesh', 'sharded_wire_roundtrip', 'sharded_wire_step',
           'seq_parallel_frame_scan']
