"""Multi-host (DCN) entry points for the sharded data plane.

One process per host, ICI inside a host, DCN between hosts — the
standard JAX multi-controller layout.  The single-host mesh code in
this package works unchanged once three things hold:

1. every process has called :func:`initialize` (jax.distributed — the
   coordinator barrier, global device enumeration);
2. the mesh is built over ``jax.devices()`` (GLOBAL devices — the
   default in :func:`make_mesh`), with the ``dp`` axis ordered so that
   a stream batch's shards land on the devices of the host that
   accepted those connections (ICI does the reductions inside a host;
   only the scalar psum/pmax results cross DCN);
3. per-host inputs are assembled into global arrays with
   :func:`host_local_wire_batch` rather than shipped to one host.

The reference has no analogue — its "distributed backend" is a TCP
client pool against a server ensemble (SURVEY.md §5) — but a fleet
proxy decoding connection streams on every host of a pod slice is the
scale story this framework is built for.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join (or start) the multi-controller cluster.

    Thin passthrough to ``jax.distributed.initialize`` with the same
    auto-detection behavior (env vars / cloud metadata when arguments
    are omitted).  Call once per process, before any other JAX use.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


def host_local_wire_batch(mesh: Mesh, local_buf, local_lens):
    """Assemble per-host stream batches into dp-sharded global arrays.

    Each host passes the [b, L] uint8 bytes and [b] int32 lengths of
    ITS OWN connections (b = global B / process_count); the returned
    global arrays are sharded over the mesh's ``dp`` axis without any
    cross-host data movement — each host's shard stays on its devices
    (``jax.make_array_from_process_local_data``).  Feed them straight
    to ``sharded_wire_step(mesh, ...)``.
    """
    local_buf = np.ascontiguousarray(local_buf)
    local_lens = np.ascontiguousarray(local_lens)
    buf_sharding = NamedSharding(mesh, P('dp', None))
    len_sharding = NamedSharding(mesh, P('dp'))
    gbuf = jax.make_array_from_process_local_data(
        buf_sharding, local_buf)
    glens = jax.make_array_from_process_local_data(
        len_sharding, local_lens)
    return gbuf, glens
