"""Data-parallel sharded wire decode with collective reductions.

Shards the [B, L] stream batch across the mesh's ``dp`` axis with
``shard_map``; each device runs the local :func:`wire_pipeline_step`
and the global session summary (total frames/notifications, fleet-wide
max zxid) reduces over ICI with ``psum`` / unsigned-64 ``pmax`` on
(hi, lo) pairs.  The fleet-wide max zxid is what a multi-host session
manager would persist as its resume checkpoint — the distributed
analogue of lib/zk-session.js:229-235.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..ops.bytesops import u64pair_reduce_max
from ..ops.encode import build_reply_streams
from ..ops.pipeline import WireStats, wire_pipeline_step


class GlobalWireStats(NamedTuple):
    """Fleet-wide reductions (replicated scalars)."""

    total_frames: jnp.ndarray
    total_notifications: jnp.ndarray
    total_errors: jnp.ndarray
    max_zxid_hi: jnp.ndarray
    max_zxid_lo: jnp.ndarray


_SIGN = -0x80000000


def _u64_axis_max(h, l, axis_name):
    """Unsigned 64-bit max of a (hi, lo) int32 scalar pair across a
    mesh axis, without 64-bit lanes: flip signs so signed pmax orders
    like unsigned, take pmax of hi, then pmax of lo among the winners."""
    sign = jnp.int32(_SIGN)
    uh = h ^ sign
    mh = lax.pmax(uh, axis_name)
    lo_key = jnp.where(uh == mh, l ^ sign, sign)
    ml = lax.pmax(lo_key, axis_name)
    return mh ^ sign, ml ^ sign


def sharded_wire_step(mesh: Mesh, max_frames: int = 32):
    """Build the jitted dp-sharded pipeline step for ``mesh``.

    Returns a function ``step(buf, lens) -> (WireStats, GlobalWireStats)``
    where ``buf`` is uint8 [B, L] with B divisible by the dp axis size;
    per-stream outputs stay dp-sharded, global stats are replicated.
    """

    def local_step(buf, lens):
        stats = wire_pipeline_step(buf, lens, max_frames=max_frames)
        # local lexicographic zxid winner, then the cross-device
        # unsigned-64 pmax over the dp axis
        lh, ll = u64pair_reduce_max(stats.max_zxid_hi, stats.max_zxid_lo)
        gh, gl = _u64_axis_max(lh, ll, 'dp')
        g = GlobalWireStats(
            total_frames=lax.psum(jnp.sum(stats.n_frames), 'dp'),
            total_notifications=lax.psum(
                jnp.sum(stats.n_notifications), 'dp'),
            total_errors=lax.psum(jnp.sum(stats.n_errors), 'dp'),
            max_zxid_hi=gh,
            max_zxid_lo=gl,
        )
        return stats, g

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P('dp', None), P('dp')),
        out_specs=(_WIRE_STATS_DP_SPEC,
                   GlobalWireStats(P(), P(), P(), P(), P())),
    )
    return jax.jit(sharded)


_WIRE_STATS_DP_SPEC = WireStats(
    starts=P('dp', None), sizes=P('dp', None),
    xids=P('dp', None), errs=P('dp', None),
    zxid_hi=P('dp', None), zxid_lo=P('dp', None),
    n_frames=P('dp'), n_replies=P('dp'),
    n_notifications=P('dp'), n_pings=P('dp'),
    n_errors=P('dp'), max_zxid_hi=P('dp'),
    max_zxid_lo=P('dp'), bad=P('dp'), resid=P('dp'),
)


def sharded_wire_roundtrip(mesh: Mesh, out_len: int,
                           max_frames: int | None = None):
    """Build the jitted dp-sharded encode->decode loop for ``mesh``.

    Each device encodes its shard of per-frame field planes into wire
    streams (ops/encode.py) and immediately decodes them back
    (ops/pipeline.py); the fleet-wide frame count psum-reduces over the
    dp axis.  Returns ``loop(xid, zhi, zlo, err, sizes) ->
    (WireStats, total_frames)`` with all plane inputs int32 [B, F], B
    divisible by the dp axis size.

    ``out_len`` has no safe default: frames past it are dropped by the
    encoder (its documented overflow contract), so the caller must size
    it for their largest fleet row.  ``max_frames`` defaults to the
    plane width F, which cannot under-decode.
    """

    def local(xid, zhi, zlo, err, sizes):
        F = max_frames if max_frames is not None else sizes.shape[1]
        buf, lens = build_reply_streams(xid, zhi, zlo, err, sizes,
                                        out_len=out_len)
        stats = wire_pipeline_step(buf, lens, max_frames=F)
        return stats, lax.psum(jnp.sum(stats.n_frames), 'dp')

    sharded = shard_map(
        local,
        mesh=mesh,
        in_specs=(P('dp', None),) * 5,
        out_specs=(_WIRE_STATS_DP_SPEC, P()),
    )
    return jax.jit(sharded)
