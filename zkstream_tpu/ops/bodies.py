"""Batched frame-body extraction: the last step of a full decode.

After :mod:`frame_scan` locates frames and :mod:`headers` parses the
16-byte reply headers, consumers that want the opcode-specific payload
need the body bytes themselves (what the scalar codec hands to
``records.read_response``, reference: lib/zk-streams.js:74-79).  This
op slices every frame's body out of the stream batch into a dense
padded tensor in one gather — no per-frame host loop.
"""

from __future__ import annotations

import jax.numpy as jnp


def slice_frame_bodies(buf, starts, sizes, max_body: int,
                       skip_header: bool = False):
    """Gather frame bodies into a padded [B, F, max_body] tensor.

    Args:
      buf: uint8 [B, L] stream bytes.
      starts: int32 [B, F] body start offsets (-1 = no frame), as
        produced by the scans / the Pallas kernel.
      sizes: int32 [B, F] body byte counts.
      max_body: static width of the output's trailing axis; longer
        bodies are truncated to it (callers size it from the protocol,
        e.g. 16 + max payload; truncation is visible via ``sizes``).
      skip_header: drop the leading 16-byte reply header, yielding just
        the opcode-specific payload (sizes still count the header, as
        on the wire).

    Returns:
      (bodies, mask): uint8 [B, F, max_body] zero-padded bytes and
      bool [B, F, max_body] validity mask.
    """
    B, L = buf.shape
    hdr = 16 if skip_header else 0
    valid = starts >= 0
    base = jnp.where(valid, starts, 0) + hdr
    pos = jnp.arange(max_body, dtype=jnp.int32)
    # [B, F, max_body] absolute byte positions, clamped in-bounds;
    # the mask kills reads past each frame's real extent.
    idx = base[..., None] + pos
    mask = valid[..., None] & (pos < (sizes[..., None] - hdr)) & \
        (idx < L)
    # where(mask, idx, 0) is the single bounds mechanism: every index
    # the mask rejects gathers from position 0 and is zeroed after.
    bodies = jnp.take_along_axis(
        buf[:, None, :], jnp.where(mask, idx, 0), axis=2)
    return jnp.where(mask, bodies, 0).astype(jnp.uint8), mask
