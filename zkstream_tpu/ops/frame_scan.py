"""Frame-boundary discovery as array programs.

The reference finds frame boundaries with a sequential accumulator loop
— read 4-byte length, slice, repeat (lib/zk-streams.js:39-64), guarding
length < 0 or > 16 MiB (lib/zk-streams.js:23,47-53).  Two TPU-shaped
reformulations live here:

``frame_cursor_scan``
    Decodes a *batch* of independent streams in lockstep: one
    ``lax.scan`` step advances every stream's cursor by its current
    frame length, so the scan length is max-frames-per-stream while the
    work per step is vectorized across the whole batch.  This is the
    server-fleet shape: thousands of connections, each with a handful
    of frames per network tick.

``frame_starts_pointer_doubling``
    Finds every frame of a *single* long stream in O(log L) parallel
    steps.  Every byte position i speculatively computes its successor
    "if a frame started here, the next would start at i + 4 + len(i)";
    frame starts are then exactly the positions reachable from 0 in the
    successor graph, computed by pointer doubling (scatter-or of a
    reachability mask while squaring the successor map).  The
    sequential chain the reference walks one frame at a time becomes a
    log-depth gather/scatter cascade.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

from .bytesops import be_i32_at

# single source of truth shared with the scalar FrameDecoder
# (reference: lib/zk-streams.js:23); protocol.consts imports no JAX
from ..protocol.consts import MAX_PACKET


def frame_cursor_scan(buf, lens, max_frames: int):
    """Lockstep frame scan over a batch of streams.

    Args:
      buf: uint8 [B, L] — each row is one connection's accumulated bytes.
      lens: int32 [B] — valid byte count per row.
      max_frames: static bound on frames per stream (scan length).

    Returns:
      starts: int32 [B, max_frames] — body start offset per frame, -1
        where no frame.
      sizes: int32 [B, max_frames] — body length per frame, 0 where none.
      counts: int32 [B] — complete frames found per stream.
      bad: bool [B] — a negative/oversized length prefix was seen
        (the BAD_LENGTH protocol error, lib/zk-streams.js:47-53).
      resid: int32 [B] — cursor after the last complete frame (bytes
        from here to ``lens`` are a partial frame to keep buffered).
    """
    B, L = buf.shape
    lens = lens.astype(jnp.int32)

    def step(carry, _):
        cur, bad = carry
        has_prefix = cur + 4 <= lens
        ln = be_i32_at(buf, cur)
        ln = jnp.where(has_prefix, ln, 0)
        is_bad = has_prefix & ((ln < 0) | (ln > MAX_PACKET))
        complete = has_prefix & ~is_bad & ~bad & (cur + 4 + ln <= lens)
        start = jnp.where(complete, cur + 4, -1)
        size = jnp.where(complete, ln, 0)
        nxt = jnp.where(complete, cur + 4 + ln, cur)
        return (nxt, bad | is_bad), (start, size)

    # init carry derived from `lens` (not fresh constants) so that under
    # shard_map the carry is varying over the mesh axis from the start,
    # matching the loop body's output types
    init = (lens * 0, lens < 0)
    (resid, bad), (starts, sizes) = lax.scan(
        step, init, None, length=max_frames)
    starts = jnp.moveaxis(starts, 0, 1)
    sizes = jnp.moveaxis(sizes, 0, 1)
    counts = jnp.sum((starts >= 0).astype(jnp.int32), axis=1)
    return starts, sizes, counts, bad, resid


def frame_starts_pointer_doubling(buf, n):
    """All frame starts of one stream in O(log L) parallel steps.

    Args:
      buf: uint8 [L] — a single stream's bytes.
      n: int32 scalar — valid byte count.

    Returns:
      is_start: bool [L] — True at each offset where a complete frame's
        4-byte length prefix begins.
      bad: bool — a reachable position had an invalid length prefix.

    The successor map saturates at sentinel L for incomplete/invalid
    positions, so reachability never escapes the buffer.  Positions
    past a bad prefix are unreachable, matching the sequential
    decoder's stop-at-error behavior.
    """
    L = buf.shape[0]
    idx = jnp.arange(L, dtype=jnp.int32)
    ln = be_i32_at(buf[None, :], idx[None, :])[0]
    has_prefix = idx + 4 <= n
    ln = jnp.where(has_prefix, ln, 0)
    invalid = has_prefix & ((ln < 0) | (ln > MAX_PACKET))
    complete = has_prefix & ~invalid & (idx + 4 + ln <= n)
    succ = jnp.where(complete, idx + 4 + ln, L).astype(jnp.int32)

    # Reachability from position 0 by pointer doubling: after k rounds
    # every position within 2^k frame-hops of 0 is marked.
    f = jnp.concatenate([succ, jnp.array([L], jnp.int32)])  # f[L] = L
    reach = jnp.zeros((L + 1,), jnp.bool_).at[0].set(True)
    rounds = max(1, math.ceil(math.log2(max(2, L))))

    def body(_, carry):
        f, reach = carry
        # scatter-or: mark f[i] reachable wherever i is, then square f
        reach = reach.at[f[:-1]].max(reach[:-1])
        f = f[f]
        return f, reach

    f, reach = lax.fori_loop(0, rounds, body, (f, reach))
    is_start = reach[:L] & complete
    bad = jnp.any(reach[:L] & invalid)
    return is_start, bad
