"""Batched opcode-specific reply-body decode.

The scalar codec parses each reply body with a per-opcode reader
(``records.read_response``; reference: lib/zk-buffer.js:281-370).  This
module is the tensor restatement for the body layouts that are fixed
offset or single-variable-field — which covers every reply the hot path
cares about:

- ``EXISTS`` / ``SET_DATA``: a bare 68-byte Stat record
  (reference: lib/zk-buffer.js:428-442);
- ``GET_DATA``: buffer(data) then Stat (lib/zk-buffer.js:353-357);
- ``CREATE``: ustring path (lib/zk-buffer.js:333-335);
- ``NOTIFICATION``: type:int32, state:int32, path ustring
  (lib/zk-buffer.js:364-370).

List-shaped bodies (children lists, ACL lists) stay on the scalar
decoder: their layout is a length-prefixed *sequence of variable-width
records*, which has no fixed-shape tensor form worth the gather storm.

Dispatch strategy: rather than routing frames by opcode on device
(dynamic control flow XLA can't tile), :func:`parse_reply_bodies`
speculatively parses **every** layout at **every** frame — each parse is
a handful of ~4-byte gathers, so the redundant work is noise — and the
consumer selects the right view per frame using its host-side
xid -> opcode map.  All reads are mask-clamped: invalid frames and
out-of-extent offsets yield zeros, never out-of-bounds gathers.

64-bit Stat fields (zxids, times, ephemeralOwner) are (hi, lo) int32
pairs, per the convention in :mod:`bytesops`.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ..protocol.consts import MAX_PACKET, REPLY_HDR
from .bytesops import be_i32_at, be_i64pair_at

#: Serialized Stat width: 6 longs + 5 ints
#: (reference: lib/zk-buffer.js:428-442).
STAT_WIRE = 68

#: (field name, byte offset within the Stat, is 64-bit) in wire order.
_STAT_FIELDS = (
    ('czxid', 0, True),
    ('mzxid', 8, True),
    ('ctime', 16, True),
    ('mtime', 24, True),
    ('version', 32, False),
    ('cversion', 36, False),
    ('aversion', 40, False),
    ('ephemeralOwner', 44, True),
    ('dataLength', 52, False),
    ('numChildren', 56, False),
    ('pzxid', 60, True),
)


class StatPlanes(NamedTuple):
    """A batched Stat: one int32 [B, F] plane per 32-bit field, (hi, lo)
    plane pairs per 64-bit field, plus the validity mask."""

    czxid_hi: jnp.ndarray
    czxid_lo: jnp.ndarray
    mzxid_hi: jnp.ndarray
    mzxid_lo: jnp.ndarray
    ctime_hi: jnp.ndarray
    ctime_lo: jnp.ndarray
    mtime_hi: jnp.ndarray
    mtime_lo: jnp.ndarray
    version: jnp.ndarray
    cversion: jnp.ndarray
    aversion: jnp.ndarray
    ephemeralOwner_hi: jnp.ndarray
    ephemeralOwner_lo: jnp.ndarray
    dataLength: jnp.ndarray
    numChildren: jnp.ndarray
    pzxid_hi: jnp.ndarray
    pzxid_lo: jnp.ndarray
    valid: jnp.ndarray


def parse_stats(buf, off, valid) -> StatPlanes:
    """Parse a Stat record at absolute byte offset ``off`` of each
    stream.

    Args:
      buf: uint8 [B, L] stream bytes.
      off: int32 [B, F] absolute offset of each frame's Stat.
      valid: bool [B, F] which (stream, frame) slots hold a Stat whose
        extent really lies within the frame; fields are 0 elsewhere.
    """
    off = jnp.where(valid, off, 0)
    out = {}
    for name, rel, is_long in _STAT_FIELDS:
        if is_long:
            hi, lo = be_i64pair_at(buf, off + rel)
            out[name + '_hi'] = jnp.where(valid, hi, 0)
            out[name + '_lo'] = jnp.where(valid, lo, 0)
        else:
            out[name] = jnp.where(valid, be_i32_at(buf, off + rel), 0)
    return StatPlanes(valid=valid, **out)


def slice_var_bytes(buf, off, lens, max_len: int):
    """Gather a variable-width byte field (buffer payload or ustring
    text) from each frame into a dense [B, F, max_len] tensor.

    Args:
      buf: uint8 [B, L] stream bytes.
      off: int32 [B, F] absolute start of the field's bytes.
      lens: int32 [B, F] field byte counts (callers pass the already
        clamped-to->=0 jute length).
      max_len: static output width; longer fields truncate (visible to
        callers via ``lens``).

    Returns:
      (data, mask): uint8 [B, F, max_len] zero-padded and its validity
      mask.
    """
    B, L = buf.shape
    pos = jnp.arange(max_len, dtype=jnp.int32)
    idx = off[..., None] + pos
    mask = (pos < lens[..., None]) & (idx < L) & (off[..., None] >= 0)
    data = jnp.take_along_axis(
        buf[:, None, :], jnp.where(mask, idx, 0), axis=2)
    return jnp.where(mask, data, 0).astype(jnp.uint8), mask


def _ustring_at(buf, off, valid, frame_end, max_len: int):
    """Parse a jute buffer/ustring (int32 length + bytes) at ``off``.
    Negative length decodes as empty (reference:
    lib/jute-buffer.js:99-100).  Returns (raw_len, bytes, mask, ok)
    where ``ok`` means the field's extent fits inside the frame."""
    off = jnp.where(valid, off, 0)
    raw = jnp.where(valid, be_i32_at(buf, off), 0)
    # Clamp BEFORE the extent arithmetic: a wire-controlled length
    # near INT32_MAX would wrap ``off + 4 + n`` negative and make a
    # field that overruns the frame look valid.  No legal field can
    # exceed MAX_PACKET, so the clamp never changes a legal decode.
    n = jnp.minimum(jnp.maximum(raw, 0), MAX_PACKET + 1)
    ok = valid & (off + 4 + n <= frame_end)
    n = jnp.where(ok, n, 0)
    data, mask = slice_var_bytes(buf, off + 4, n, max_len)
    return jnp.where(ok, raw, 0), data, mask, ok


class ReplyBodies(NamedTuple):
    """Speculative parse of every fixed-layout reply body at every
    frame.  Select the view matching each frame's opcode:

    - EXISTS / SET_DATA -> ``stat0``
    - GET_DATA          -> ``data_len``/``data``/``data_mask`` +
      ``stat_after_data`` (its ``valid`` also proves the buffer field
      fit the frame)
    - CREATE            -> ``str0_len``/``str0``/``str0_mask``
    - NOTIFICATION      -> ``ntype``/``nstate`` +
      ``npath_len``/``npath``/``npath_mask``
    """

    stat0: StatPlanes
    data_len: jnp.ndarray
    data: jnp.ndarray
    data_mask: jnp.ndarray
    data_ok: jnp.ndarray       # buffer field extent fit the frame
    stat_after_data: StatPlanes
    str0_len: jnp.ndarray
    str0: jnp.ndarray
    str0_mask: jnp.ndarray
    str0_ok: jnp.ndarray       # ustring extent fit the frame
    ntype: jnp.ndarray
    nstate: jnp.ndarray
    npath_len: jnp.ndarray
    npath: jnp.ndarray
    npath_mask: jnp.ndarray
    npath_ok: jnp.ndarray      # notification path extent fit the frame


def parse_reply_bodies(buf, starts, sizes, max_data: int = 128,
                       max_path: int = 128) -> ReplyBodies:
    """Parse all fixed-layout reply-body interpretations of every frame.

    Args:
      buf: uint8 [B, L] stream bytes.
      starts: int32 [B, F] frame body offsets (-1 = no frame), as
        produced by the frame scans (the reply header sits at the body
        start; opcode payloads begin 16 bytes in).
      sizes: int32 [B, F] frame body lengths.
      max_data: static width for the GET_DATA payload bytes.
      max_path: static width for CREATE/NOTIFICATION path bytes.
    """
    frame_ok = (starts >= 0) & (sizes >= REPLY_HDR)
    start = jnp.where(frame_ok, starts, 0)
    end = start + jnp.where(frame_ok, sizes, 0)      # frame extent
    p = start + REPLY_HDR                            # payload start

    # EXISTS / SET_DATA: Stat at payload start.
    stat0 = parse_stats(buf, p, frame_ok & (p + STAT_WIRE <= end))

    # GET_DATA: buffer then Stat.
    data_len, data, data_mask, data_ok = _ustring_at(
        buf, p, frame_ok, end, max_data)
    stat_off = p + 4 + jnp.maximum(data_len, 0)
    stat_after_data = parse_stats(
        buf, stat_off, data_ok & (stat_off + STAT_WIRE <= end))

    # CREATE: ustring at payload start — the buffer layout again, so
    # when the plane widths match it IS the GET_DATA view: reuse it
    # (measured ~20% of this parse at the deployed 256/256 widths;
    # XLA does not CSE the duplicate gathers away).
    if max_path == max_data:
        str0_len, str0, str0_mask, str0_ok = (data_len, data,
                                              data_mask, data_ok)
    else:
        str0_len, str0, str0_mask, str0_ok = _ustring_at(
            buf, p, frame_ok, end, max_path)

    # NOTIFICATION: type:int32, state:int32, path ustring
    # (reference: lib/zk-buffer.js:364-370).
    n_ok = frame_ok & (p + 8 <= end)
    np_ = jnp.where(n_ok, p, 0)
    ntype = jnp.where(n_ok, be_i32_at(buf, np_), 0)
    nstate = jnp.where(n_ok, be_i32_at(buf, np_ + 4), 0)
    npath_len, npath, npath_mask, npath_ok = _ustring_at(
        buf, p + 8, n_ok, end, max_path)

    return ReplyBodies(
        stat0=stat0,
        data_len=data_len, data=data, data_mask=data_mask,
        data_ok=data_ok,
        stat_after_data=stat_after_data,
        str0_len=str0_len, str0=str0, str0_mask=str0_mask,
        str0_ok=str0_ok,
        ntype=ntype, nstate=nstate,
        npath_len=npath_len, npath=npath, npath_mask=npath_mask,
        npath_ok=npath_ok,
    )


class ListBodies(NamedTuple):
    """Speculative parse of the list-shaped reply bodies at every
    frame — children lists (GET_CHILDREN / GET_CHILDREN2, reference:
    lib/zk-buffer.js:337-347) and ACL lists (GET_ACL,
    lib/zk-buffer.js:349-351,372-426) — bounded by static
    (max_children, max_name) / (max_acls, max_scheme, max_id).

    ``ch_ok`` / ``acl_ok`` mean the whole list fits the bounds AND lies
    within the frame; a False slot must take the scalar fallback (which
    either parses the oversized list or raises exactly the scalar
    error).  Element length planes hold the **decoded** byte count —
    clamped to >= 0, because a negative jute length decodes as an empty
    string (lib/jute-buffer.js:99-100) — so wherever the ok mask is
    set, every length lies in [0, max_*]; consumers slice with it
    directly."""

    ch_count: jnp.ndarray        # int32 [B, F]
    ch_len: jnp.ndarray          # int32 [B, F, K] decoded lengths >= 0
    ch_bytes: jnp.ndarray        # uint8 [B, F, K, S]
    ch_ok: jnp.ndarray           # bool [B, F]
    stat_after_children: StatPlanes   # GET_CHILDREN2 trailing Stat
    acl_count: jnp.ndarray       # int32 [B, F]
    acl_perms: jnp.ndarray       # int32 [B, F, A]
    acl_scheme_len: jnp.ndarray  # int32 [B, F, A]
    acl_scheme: jnp.ndarray      # uint8 [B, F, A, SS]
    acl_id_len: jnp.ndarray      # int32 [B, F, A]
    acl_id: jnp.ndarray          # uint8 [B, F, A, SI]
    acl_ok: jnp.ndarray          # bool [B, F]
    stat_after_acl: StatPlanes   # GET_ACL trailing Stat


def _scan_ustring(buf, cur, active, frame_end, max_len: int):
    """One jute-string step of a sequential list walk: parse the
    (int32 len, bytes) at ``cur`` where ``active``; an element is ok
    when its extent fits the frame AND its length fits ``max_len``
    (truncation is not an option for list elements — the whole frame
    falls back instead).  Returns (len, bytes, ok, next_cur) where
    ``len`` is the DECODED byte count — a negative jute length decodes
    as empty (lib/jute-buffer.js:99-100), so the plane reports 0, not
    the raw wire value."""
    at = jnp.where(active, cur, 0)
    raw = jnp.where(active, be_i32_at(buf, at), 0)
    n = jnp.maximum(raw, 0)
    ok = active & (cur + 4 + n <= frame_end) & (n <= max_len)
    data, _mask = slice_var_bytes(buf, cur + 4, jnp.where(ok, n, 0),
                                  max_len)
    return (jnp.where(ok, n, 0), data, ok,
            jnp.where(ok, cur + 4 + n, cur))


def parse_list_bodies(buf, starts, sizes,
                      max_children: int = 16, max_name: int = 64,
                      max_acls: int = 4, max_scheme: int = 16,
                      max_id: int = 64) -> ListBodies:
    """Parse the children-list and ACL-list interpretations of every
    frame (the bodies :func:`parse_reply_bodies` leaves to the scalar
    reader).  Kept separate from it on purpose: the K x S byte gathers
    are only worth paying when a consumer (the fleet ingest's device
    body mode) actually routes list replies.

    A list is a *sequential* layout — element k's offset depends on
    every earlier length — so the walk is a short unrolled chain of
    masked gathers (static ``max_children`` / ``max_acls`` steps), one
    XLA program with no dynamic shapes.
    """
    from jax import lax

    frame_ok = (starts >= 0) & (sizes >= REPLY_HDR)
    start = jnp.where(frame_ok, starts, 0)
    end = start + jnp.where(frame_ok, sizes, 0)
    p = start + REPLY_HDR

    have = frame_ok & (p + 4 <= end)
    count = jnp.where(have, be_i32_at(buf, jnp.where(have, p, 0)), 0)

    # -- children: count, then count x ustring.  The walk is
    # sequential (element k's offset depends on every earlier length),
    # so it is a lax.scan over the static element bound — the step
    # traces once, keeping the compiled program small --
    def ch_step(carry, k):
        cur, ok = carry
        active = ok & (k < count)
        raw, data, elem_ok, cur = _scan_ustring(
            buf, cur, active, end, max_name)
        return (cur, ok & (~active | elem_ok)), (raw, data)

    in_bounds = have & (count >= 0) & (count <= max_children)
    (cur, ok), (ch_len, ch_bytes) = lax.scan(
        ch_step, (p + 4, in_bounds),
        jnp.arange(max_children, dtype=jnp.int32))
    ch_len = jnp.moveaxis(ch_len, 0, 2)            # [B, F, K]
    ch_bytes = jnp.moveaxis(ch_bytes, 0, 2)        # [B, F, K, S]
    stat_after_children = parse_stats(
        buf, cur, ok & (cur + STAT_WIRE <= end))

    # -- ACL: count, then count x (perms:int32, scheme, id) --
    def acl_step(carry, k):
        cur, aok = carry
        active = aok & (k < count)
        at = jnp.where(active, cur, 0)
        pm_ok = active & (cur + 4 <= end)
        pm = jnp.where(pm_ok, be_i32_at(buf, at), 0)
        cur = jnp.where(pm_ok, cur + 4, cur)
        sraw, sdata, s_ok, cur = _scan_ustring(
            buf, cur, pm_ok, end, max_scheme)
        iraw, idata, i_ok, cur = _scan_ustring(
            buf, cur, s_ok, end, max_id)
        aok = aok & (~active | (pm_ok & s_ok & i_ok))
        return (cur, aok), (pm, sraw, sdata, iraw, idata)

    a_in = have & (count >= 0) & (count <= max_acls)
    (acur, aok), (perms, slens, sbts, ilens, ibts) = lax.scan(
        acl_step, (p + 4, a_in),
        jnp.arange(max_acls, dtype=jnp.int32))
    stat_after_acl = parse_stats(
        buf, acur, aok & (acur + STAT_WIRE <= end))

    return ListBodies(
        ch_count=jnp.where(ok, count, 0),
        ch_len=ch_len, ch_bytes=ch_bytes, ch_ok=ok,
        stat_after_children=stat_after_children,
        acl_count=jnp.where(aok, count, 0),
        acl_perms=jnp.moveaxis(perms, 0, 2),
        acl_scheme_len=jnp.moveaxis(slens, 0, 2),
        acl_scheme=jnp.moveaxis(sbts, 0, 2),
        acl_id_len=jnp.moveaxis(ilens, 0, 2),
        acl_id=jnp.moveaxis(ibts, 0, 2),
        acl_ok=aok,
        stat_after_acl=stat_after_acl,
    )


# -- host-side views (numpy in, dataclasses out) --

def stat_from_planes(planes, b: int, f: int):
    """Collapse one (stream, frame) slot of a :class:`StatPlanes` (as
    host numpy arrays) into the scalar codec's ``Stat`` dataclass."""
    from ..protocol.records import Stat
    from .bytesops import i64pair_to_int

    def i64(name):
        return i64pair_to_int(getattr(planes, name + '_hi')[b, f],
                              getattr(planes, name + '_lo')[b, f])

    def i32(name):
        return int(getattr(planes, name)[b, f])

    return Stat(
        czxid=i64('czxid'), mzxid=i64('mzxid'),
        ctime=i64('ctime'), mtime=i64('mtime'),
        version=i32('version'), cversion=i32('cversion'),
        aversion=i32('aversion'),
        ephemeralOwner=i64('ephemeralOwner'),
        dataLength=i32('dataLength'), numChildren=i32('numChildren'),
        pzxid=i64('pzxid'))
