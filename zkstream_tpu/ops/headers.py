"""Batched reply-header parse and per-stream session reductions.

Every steady-state reply starts with a 16-byte header — xid:int32,
zxid:int64, err:int32 (reference: lib/zk-buffer.js:275-331) — and the
connected-state drain loop routes each packet on its xid: NOTIFICATION
(-1) to the watcher engine, PING (-2) to the keepalive, SET_WATCHES
(-8), AUTH (-4), and everything else to the pending-request table
(lib/connection-fsm.js:213-229, xid table lib/zk-consts.js:135-138).
The session separately tracks the largest zxid seen across all replies
— its resume checkpoint (lib/zk-session.js:229-235).

Here the whole drain is one vectorized pass: parse all headers of all
streams, classify by xid with compare masks, and reduce max-zxid per
stream with an unsigned-64 pairwise max.
"""

from __future__ import annotations

import jax.numpy as jnp

from .bytesops import be_i32_at, be_i64pair_at, u64pair_reduce_max

XID_NOTIFICATION = -1
XID_PING = -2
XID_AUTH = -4
XID_SET_WATCHES = -8


def parse_reply_headers(buf, starts, sizes=None):
    """Parse reply headers at each frame start.

    Args:
      buf: uint8 [B, L] stream bytes.
      starts: int32 [B, F] frame body offsets (-1 = no frame), as
        produced by :func:`frame_cursor_scan`.
      sizes: int32 [B, F] frame body lengths; when given, frames
        shorter than the 16-byte reply header are excluded from
        ``valid`` (and surfaced via ``short``) instead of reading
        bytes belonging to the next frame — the scalar codec raises
        BAD_DECODE on such frames.

    Returns dict of int32 [B, F] arrays: ``xid``, ``zxid_hi``,
    ``zxid_lo``, ``err`` — values are 0 where ``valid`` is False —
    plus bool masks ``valid`` and ``short``.
    """
    valid = starts >= 0
    short = valid & (sizes < 16) if sizes is not None else (
        jnp.zeros_like(valid))
    valid = valid & ~short
    off = jnp.where(valid, starts, 0)
    xid = jnp.where(valid, be_i32_at(buf, off), 0)
    zh, zl = be_i64pair_at(buf, off + 4)
    err = be_i32_at(buf, off + 12)
    return {
        'valid': valid,
        'short': short,
        'xid': xid,
        'zxid_hi': jnp.where(valid, zh, 0),
        'zxid_lo': jnp.where(valid, zl, 0),
        'err': jnp.where(valid, err, 0),
    }


def stream_stats(headers):
    """Per-stream reductions over parsed headers.

    Mirrors what one pass of the drain loop accumulates: reply/
    notification/ping routing counts and the max zxid for the session
    checkpoint.  Notifications carry zxid -1 on the wire and must not
    advance the checkpoint — the valid mask plus xid>=0 filter handles
    that (reference: lib/zk-session.js:229-235 only advances on
    positive zxids).

    Returns dict of int32 [B] arrays: ``n_replies``, ``n_notifications``,
    ``n_pings``, ``n_errors``, ``max_zxid_hi``, ``max_zxid_lo``.
    """
    valid = headers['valid']
    xid = headers['xid']
    err = headers['err']

    def count(mask):
        return jnp.sum((valid & mask).astype(jnp.int32), axis=1)

    is_notif = xid == XID_NOTIFICATION
    is_ping = xid == XID_PING
    is_reply = xid >= 0

    # zxid max over data replies only (masked frames contribute (0,0))
    zh = jnp.where(valid & is_reply, headers['zxid_hi'], 0)
    zl = jnp.where(valid & is_reply, headers['zxid_lo'], 0)
    mh, ml = u64pair_reduce_max(zh, zl, axis=1)

    return {
        'n_replies': count(is_reply),
        'n_notifications': count(is_notif),
        'n_pings': count(is_ping),
        'n_errors': count(is_reply & (err != 0)),
        'max_zxid_hi': mh,
        'max_zxid_lo': ml,
    }
