"""Gather-based big-endian field extraction over uint8 tensors.

The Jute wire format is big-endian throughout (reference:
lib/jute-buffer.js:102-125).  These helpers read int32 / int64 fields at
arbitrary (batched) byte offsets out of uint8 buffers using four/eight
one-byte gathers plus shift-or assembly — fully vectorized, no byte
loops.

64-bit fields (zxid, sessionId, timestamps) are represented as
``(hi, lo)`` int32 pairs.  The reference faces the same problem — Node
pre-BigInt has no int64 — and solves it with jsbn BigInteger
(lib/jute-buffer.js:63-77); on TPU the natural carrier is a pair of
32-bit lanes, with unsigned comparison built from the sign-flip trick.
All offset gathers are clamped so speculative lanes (masked-off frames)
stay in bounds.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# 0x80000000 as an int32 bit pattern.  A numpy scalar, NOT jnp: a
# module-level jnp scalar is a device array that jit captures as a
# buffer constant, which costs ~2 ms per dispatch through a remote-TPU
# tunnel; a np scalar inlines into the HLO as a literal.
_SIGN = np.int32(-0x80000000)


def _byte_at(buf, off):
    """Gather one byte per offset -> int32.

    ``buf`` is uint8 [..., L]; ``off`` either matches buf's rank (K
    offsets per row, result [..., K]) or has one fewer dim (one offset
    per row, result [...]).
    """
    off = jnp.clip(off.astype(jnp.int32), 0, buf.shape[-1] - 1)
    squeeze = off.ndim == buf.ndim - 1
    if squeeze:
        off = off[..., None]
    out = jnp.take_along_axis(buf, off, axis=-1).astype(jnp.int32)
    return out[..., 0] if squeeze else out


def be_i32_at(buf, off):
    """Read a big-endian int32 at byte offset ``off``.

    ``buf`` is uint8 [..., L]; ``off`` is int32 broadcastable to
    buf.shape[:-1] + (k,) trailing offsets.  Two's-complement wraparound
    of the high-byte shift yields the signed value directly.
    """
    b0 = _byte_at(buf, off)
    b1 = _byte_at(buf, off + 1)
    b2 = _byte_at(buf, off + 2)
    b3 = _byte_at(buf, off + 3)
    return (b0 << 24) | (b1 << 16) | (b2 << 8) | b3


def be_i64pair_at(buf, off):
    """Read a big-endian int64 at ``off`` as an ``(hi, lo)`` int32 pair."""
    return be_i32_at(buf, off), be_i32_at(buf, off + 4)


def _as_unsigned_key(x):
    """Map int32 -> int32 so that signed compare == unsigned compare."""
    return x ^ _SIGN


def u64pair_lt(ah, al, bh, bl):
    """Unsigned 64-bit ``a < b`` on (hi, lo) pairs."""
    ah_u, bh_u = _as_unsigned_key(ah), _as_unsigned_key(bh)
    al_u, bl_u = _as_unsigned_key(al), _as_unsigned_key(bl)
    return (ah_u < bh_u) | ((ah == bh) & (al_u < bl_u))


def u64pair_max(ah, al, bh, bl):
    """Elementwise unsigned 64-bit max on (hi, lo) pairs."""
    a_lt_b = u64pair_lt(ah, al, bh, bl)
    return jnp.where(a_lt_b, bh, ah), jnp.where(a_lt_b, bl, al)


def u64pair_reduce_max(h, l, axis=None):
    """Unsigned 64-bit max-reduce of (hi, lo) int32 pairs along
    ``axis`` (None = all), without a scan: unsigned max of hi, then
    unsigned max of lo among the elements achieving it."""
    uh = h ^ _SIGN
    mh_u = jnp.max(uh, axis=axis, keepdims=True)
    lo_key = jnp.where(uh == mh_u, l ^ _SIGN, _SIGN)
    ml_u = jnp.max(lo_key, axis=axis)
    if axis is None:
        mh_u = mh_u.reshape(())
    else:
        mh_u = jnp.squeeze(mh_u, axis=axis)
    return mh_u ^ _SIGN, ml_u ^ _SIGN


def u64pair_to_int(h, l) -> int:
    """Host-side: collapse a (hi, lo) pair (or arrays thereof) to Python
    int / numpy int64 for interop with the scalar codec."""
    h = (np.asarray(h).astype(np.int64) & 0xFFFFFFFF).astype(np.uint64)
    l = (np.asarray(l).astype(np.int64) & 0xFFFFFFFF).astype(np.uint64)
    out = (h << np.uint64(32)) | l
    return int(out) if out.ndim == 0 else out


def i64pair_to_int(h, l) -> int:
    """Host-side: collapse a (hi, lo) pair to the SIGNED int64 the wire
    carries — the scalar codec's ``read_long`` is ``>q``
    (reference long fields are signed, lib/jute-buffer.js:63-77)."""
    out = np.asarray(u64pair_to_int(h, l), dtype=np.uint64)
    signed = out.view(np.int64)
    return int(signed) if signed.ndim == 0 else signed
