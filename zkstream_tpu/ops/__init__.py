"""Tensor wire-codec ops — the TPU data plane.

The reference client's hot path is a scalar byte loop: slice 4-byte
big-endian length prefixes out of a TCP stream (lib/zk-streams.js:39-64)
and dispatch each frame on its reply header (lib/connection-fsm.js:213-229).
This package re-states that work as array programs so a fleet of
connection streams can be decoded in one fused XLA computation:

- :mod:`bytesops` — gather-based big-endian field extraction, with
  64-bit protocol fields (zxid, sessionId) carried as (hi, lo) int32
  pairs: the same move the reference makes with jsbn BigInteger for
  pre-BigInt Node (lib/jute-buffer.js:63-77), chosen here because TPU
  vector lanes are 32-bit native.
- :mod:`frame_scan` — frame-boundary discovery: a lockstep cursor scan
  vectorized across a batch of streams, and a pointer-doubling
  reachability scan that finds every frame of a single long stream in
  O(log L) parallel steps.
- :mod:`headers` — batched reply-header parse (xid / zxid / err) and
  the per-stream reductions the session layer needs (max zxid seen,
  notification counts) (lib/zk-session.js:229-235).
- :mod:`pipeline` — the flagship jittable step combining all of the
  above for a [batch, stream_len] tensor of raw connection bytes.
- :mod:`encode` — the inverse direction: batched field planes ->
  length-prefixed reply streams (the tensor restatement of the scalar
  codec's isServer encode mode, lib/zk-streams.js:121-148).
"""

from .bodies import slice_frame_bodies
from .encode import build_reply_streams
from .bytesops import (
    be_i32_at,
    be_i64pair_at,
    u64pair_max,
    u64pair_lt,
    u64pair_reduce_max,
)
from .frame_scan import (
    MAX_PACKET,
    frame_cursor_scan,
    frame_starts_pointer_doubling,
)
from .headers import parse_reply_headers, stream_stats
from .pipeline import (
    WireStats,
    wire_pipeline_step,
    wire_pipeline_step_auto,
)
from .replies import (
    ReplyBodies,
    StatPlanes,
    parse_reply_bodies,
    parse_stats,
    slice_var_bytes,
    stat_from_planes,
)

__all__ = [
    'MAX_PACKET',
    'build_reply_streams',
    'slice_frame_bodies',
    'be_i32_at',
    'be_i64pair_at',
    'u64pair_max',
    'u64pair_lt',
    'u64pair_reduce_max',
    'frame_cursor_scan',
    'frame_starts_pointer_doubling',
    'parse_reply_headers',
    'stream_stats',
    'WireStats',
    'wire_pipeline_step',
    'wire_pipeline_step_auto',
    'ReplyBodies',
    'StatPlanes',
    'parse_reply_bodies',
    'parse_stats',
    'slice_var_bytes',
    'stat_from_planes',
]
