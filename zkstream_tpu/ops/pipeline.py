"""The flagship jittable step: batched wire decode for a stream fleet.

One call = one "network tick" for B connections: slice every complete
frame out of every stream, parse every reply header, route by xid, and
reduce the per-stream session checkpoints — the vectorized equivalent
of running the reference's decode loop (lib/zk-streams.js:39-99) and
connected-state drain (lib/connection-fsm.js:213-229) once per
connection, but as a single fused XLA computation with static shapes.

This is the unit the driver compile-checks (see __graft_entry__.py) and
the benchmark measures (bench.py).  Two equivalent implementations:
``wire_pipeline_step`` (pure jnp/lax — runs anywhere; the XLA scan
gathers only the ~20 header bytes per frame, so it is the fast path on
TPU v5e) and ``wire_pipeline_step_pallas`` (the scan + header parse
fused into one Mosaic kernel, ops/pallas_scan.py — a single
custom-call, worth it when per-op dispatch overhead dominates); both
share :func:`_assemble` so the routing/stats semantics cannot diverge.
bench.py times both and reports the best.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from .frame_scan import frame_cursor_scan
from .headers import parse_reply_headers, stream_stats


class WireStats(NamedTuple):
    """Per-stream results of one pipeline step (all shaped [B] unless
    noted)."""

    starts: jnp.ndarray        # int32 [B, F] frame body offsets, -1 pad
    sizes: jnp.ndarray         # int32 [B, F] frame body lengths
    xids: jnp.ndarray          # int32 [B, F] reply xids (0 where pad)
    errs: jnp.ndarray          # int32 [B, F] reply error codes
    zxid_hi: jnp.ndarray       # int32 [B, F] per-reply zxid, high word
    zxid_lo: jnp.ndarray       # int32 [B, F] per-reply zxid, low word
    n_frames: jnp.ndarray      # int32 [B]
    n_replies: jnp.ndarray     # int32 [B]
    n_notifications: jnp.ndarray  # int32 [B]
    n_pings: jnp.ndarray       # int32 [B]
    n_errors: jnp.ndarray      # int32 [B]
    max_zxid_hi: jnp.ndarray   # int32 [B] session checkpoint, high word
    max_zxid_lo: jnp.ndarray   # int32 [B] session checkpoint, low word
    bad: jnp.ndarray           # bool [B] BAD_LENGTH or short-frame seen
    resid: jnp.ndarray         # int32 [B] partial-frame cursor


def _assemble(headers, starts, sizes, counts, bad, resid) -> WireStats:
    """Shared tail of both pipeline variants: routing reductions over
    parsed headers + WireStats assembly.  A frame too short to hold the
    16-byte reply header is a protocol violation (scalar codec:
    BAD_DECODE) — flagged via ``bad``, never misparsed."""
    stats = stream_stats(headers)
    return WireStats(
        starts=starts,
        sizes=sizes,
        xids=headers['xid'],
        errs=headers['err'],
        zxid_hi=headers['zxid_hi'],
        zxid_lo=headers['zxid_lo'],
        n_frames=counts,
        n_replies=stats['n_replies'],
        n_notifications=stats['n_notifications'],
        n_pings=stats['n_pings'],
        n_errors=stats['n_errors'],
        max_zxid_hi=stats['max_zxid_hi'],
        max_zxid_lo=stats['max_zxid_lo'],
        bad=bad | jnp.any(headers['short'], axis=1),
        resid=resid,
    )


def _stats_from_scan(r) -> WireStats:
    """WireStats from a Pallas scan-result dict — the shared tail of
    both Pallas entry points, so the short-frame/header routing rules
    cannot diverge between them."""
    valid = r['starts'] >= 0
    short = valid & (r['sizes'] < 16)
    headers = {
        'valid': valid & ~short,
        'short': short,
        'xid': r['xid'],
        'zxid_hi': r['zxid_hi'],
        'zxid_lo': r['zxid_lo'],
        'err': r['err'],
    }
    return _assemble(headers, r['starts'], r['sizes'], r['counts'],
                     r['bad'], r['resid'])


def wire_pipeline_step_pallas(buf, lens, max_frames: int = 32,
                              block_rows: int = 64,
                              interpret: bool = False) -> WireStats:
    """Same step as :func:`wire_pipeline_step`, with the scan + header
    parse fused into one Pallas kernel (ops/pallas_scan.py); only the
    cheap [B, F] -> [B] routing reductions remain as XLA ops.

    Shapes whose kernel would exceed the per-program scoped-VMEM limit
    fall back to the (unbounded, usually faster) jnp pipeline instead
    of failing to compile."""
    from .pallas_scan import fits_vmem, pallas_wire_scan

    if not interpret and not fits_vmem(buf.shape[0], buf.shape[1],
                                       max_frames, block_rows):
        return wire_pipeline_step(buf, lens, max_frames=max_frames)
    r = pallas_wire_scan(buf, lens, max_frames=max_frames,
                         block_rows=block_rows, interpret=interpret)
    return _stats_from_scan(r)


class GetDataBodies(NamedTuple):
    """The GET_DATA slice of :class:`..replies.ReplyBodies`, as
    produced by the fused Pallas full decode — field-for-field the
    planes ``parse_reply_bodies`` emits for that layout."""

    data_len: jnp.ndarray      # int32 [B, F] raw jute length (0/-1 ok)
    data: jnp.ndarray          # uint8 [B, F, max_data] zero-padded
    data_mask: jnp.ndarray     # bool [B, F, max_data]
    data_ok: jnp.ndarray       # bool [B, F] field extent fit the frame
    stat_after_data: 'object'  # replies.StatPlanes


def getdata_bodies_jnp(buf, st: WireStats,
                       max_data: int) -> GetDataBodies:
    """The GET_DATA planes via the jnp body parser — the reference
    semantics the fused kernel must match, packaged as GetDataBodies.
    Used as the VMEM-overflow fallback of
    :func:`wire_full_decode_pallas` and as the equal-work jnp
    candidate in tools/sweep_pallas.py."""
    from . import replies as R

    frame_ok = (st.starts >= 0) & (st.sizes >= 16)
    start = jnp.where(frame_ok, st.starts, 0)
    end = start + jnp.where(frame_ok, st.sizes, 0)
    p = start + 16
    dlen, data, mask, ok = R._ustring_at(buf, p, frame_ok, end,
                                         max_data)
    soff = p + 4 + jnp.maximum(dlen, 0)
    stat = R.parse_stats(buf, soff, ok & (soff + 68 <= end))
    return GetDataBodies(data_len=dlen, data=data, data_mask=mask,
                         data_ok=ok, stat_after_data=stat)


def wire_full_decode_pallas(buf, lens, max_frames: int = 32,
                            max_data: int = 16, block_rows: int = 64,
                            interpret: bool = False):
    """Fused FULL decode (scan + headers + GET_DATA bodies) in one
    Mosaic pass (ops/pallas_scan.pallas_wire_full_scan), plus the
    cheap elementwise unpack XLA fuses for free.  Returns
    ``(WireStats, GetDataBodies)`` — the Pallas counterpart of
    ``wire_pipeline_step`` + ``parse_reply_bodies``'s GET_DATA planes
    (property-tested equivalent in tests/test_pallas.py).  Shapes
    whose kernel would exceed the scoped-VMEM limit fall back to the
    jnp path, like :func:`wire_pipeline_step_pallas`."""
    from ..protocol.consts import MAX_PACKET
    from .pallas_scan import fits_vmem_full, pallas_wire_full_scan
    from .replies import _STAT_FIELDS, StatPlanes

    if not interpret and not fits_vmem_full(
            buf.shape[0], buf.shape[1], max_frames, block_rows,
            max_data):
        st = wire_pipeline_step(buf, lens, max_frames=max_frames)
        return st, getdata_bodies_jnp(buf, st, max_data)

    r = pallas_wire_full_scan(buf, lens, max_frames=max_frames,
                              block_rows=block_rows, max_data=max_data,
                              interpret=interpret)
    st = _stats_from_scan(r)

    frame_ok = (r['starts'] >= 0) & ~(r['sizes'] < 16)
    draw = r['dlen_raw']
    # same clamp as the kernel and replies._ustring_at: extent math
    # must not wrap on wire-controlled lengths
    nb = jnp.minimum(jnp.maximum(draw, 0), MAX_PACKET + 1)
    # the _ustring_at extent rule: p+4+n <= end, with p = start+16
    data_ok = frame_ok & (20 + nb <= r['sizes'])
    data_len = jnp.where(data_ok, draw, 0)
    n_ok = jnp.where(data_ok, nb, 0)
    # BE words -> bytes, masked to the field extent
    shifts = jnp.asarray([24, 16, 8, 0], jnp.int32)
    byts = ((r['data_words'][..., None] >> shifts) & 0xFF)
    B, F = draw.shape
    byts = byts.reshape(B, F, max_data)
    pos = jnp.arange(max_data, dtype=jnp.int32)
    data_mask = pos < n_ok[..., None]
    data = jnp.where(data_mask, byts, 0).astype(jnp.uint8)

    stat_ok = frame_ok & (20 + nb + 68 <= r['sizes'])
    sw = r['stat_words']
    # one source of truth for the Stat layout: the kernel writes word
    # rel//4 (+1 for the low half of 64-bit fields)
    vals = {}
    for name, rel, is_long in _STAT_FIELDS:
        k = rel // 4
        if is_long:
            vals[name + '_hi'] = sw[:, :, k]
            vals[name + '_lo'] = sw[:, :, k + 1]
        else:
            vals[name] = sw[:, :, k]
    stat = StatPlanes(valid=stat_ok, **vals)
    return st, GetDataBodies(data_len=data_len, data=data,
                             data_mask=data_mask, data_ok=data_ok,
                             stat_after_data=stat)


def wire_pipeline_step(buf, lens, max_frames: int = 32) -> WireStats:
    """Decode one tick of B streams.

    Args:
      buf: uint8 [B, L] accumulated bytes per connection.
      lens: int32 [B] valid byte counts.
      max_frames: static per-stream frame bound for this tick.
    """
    starts, sizes, counts, bad, resid = frame_cursor_scan(
        buf, lens, max_frames)
    headers = parse_reply_headers(buf, starts, sizes)
    return _assemble(headers, starts, sizes, counts, bad, resid)


def _pallas_pocket(B: int, max_frames: int) -> bool:
    """The shape region where the fused kernel measurably beats the
    jnp pipeline on TPU v5e (PROFILE.md 'Pallas crossover study',
    tools/sweep_pallas.py): frame-dense midsize fleets — at
    (8192, 64) the kernel holds 1.20-1.24x across repeated interleaved
    runs with block_rows=64.  Everywhere else the two are within the
    ±10 % run-noise band or jnp wins (worst pallas cell: 0.78x at
    (32768, 8)), so jnp is the default.

    Caveat: under ``shard_map`` (parallel/fleet.py) ``B`` here is the
    per-shard LOCAL batch (global B / dp), while the pocket was
    measured on single-device global shapes — so a mesh ingest enters
    the pocket when each device's shard is itself pocket-sized, which
    is the per-device work the measurement actually bounds (the kernel
    runs per shard).  Perf-only either way: both paths are
    property-tested equivalent."""
    return max_frames >= 32 and 4096 <= B <= 16384


def _target_platform() -> str:
    """The platform the caller's computation will actually lower to:
    honors an active ``jax.default_device`` override (the fleet
    ingest pins ticks to the host CPU backend this way) before falling
    back to the default backend."""
    import jax

    dev = jax.config.jax_default_device
    if dev is not None:
        # jax.default_device accepts a Device or a platform string
        return dev if isinstance(dev, str) else dev.platform
    return jax.default_backend()


def wire_pipeline_step_auto(buf, lens, max_frames: int = 32) -> WireStats:
    """Dispatch to the *measured* winner for this shape: the Pallas
    kernel (block_rows=64) inside its recorded win pocket on TPU, the
    jnp pipeline everywhere else — and on every non-TPU platform,
    where Mosaic cannot lower.  The decision is trace-time (shapes are
    static under jit); both paths are property-tested equivalent."""
    if (_target_platform() == 'tpu'
            and _pallas_pocket(buf.shape[0], max_frames)):
        return wire_pipeline_step_pallas(buf, lens,
                                         max_frames=max_frames,
                                         block_rows=64)
    return wire_pipeline_step(buf, lens, max_frames=max_frames)
