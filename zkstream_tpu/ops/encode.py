"""Batched wire ENCODE: field planes -> framed reply streams.

The decode pipeline (ops/pipeline.py) turns [B, L] byte streams into
header field planes; this is its inverse — given per-frame fields, emit
length-prefixed ZooKeeper reply frames for a whole fleet of streams in
one jitted computation.  It restates the scalar encoder's header pack
(reference: lib/zk-buffer.js:186-231 writes len/xid/zxid/err the same
way for the ``isServer`` codec mode that the reference uses to build
fake test servers, lib/zk-streams.js:121-148) as a scatter of byte
planes at cumulative frame offsets.

Use cases: generating decode-bench fleets on device, fake-server
fleets for adversarial testing, and the encode->decode self-inverse
property test (tests/test_encode.py).
"""

from __future__ import annotations

import jax.numpy as jnp

#: Reply header bytes: len prefix (4) + xid (4) + zxid (8) + err (4).
_HDR = 20


def _be_bytes(word, n: int = 4):
    """int32 [..., 1] -> n big-endian byte planes [..., n] (uint8)."""
    shifts = jnp.arange(8 * (n - 1), -1, -8, dtype=jnp.int32)
    return ((word >> shifts) & 0xFF).astype(jnp.uint8)


def build_reply_streams(xid, zxid_hi, zxid_lo, err, body_sizes,
                        out_len: int):
    """Encode a fleet of reply streams.

    Args:
      xid, zxid_hi, zxid_lo, err: int32 [B, F] per-frame header fields.
      body_sizes: int32 [B, F] reply body length per frame INCLUDING
        the 16-byte header (the value that goes in the length prefix);
        < 16 marks the frame absent (not emitted).  Body bytes beyond
        the header are zero-filled.
      out_len: static output width L; frames past it are dropped (the
        caller sizes L generously, e.g. ``int(sizes.sum(1).max()) ``).

    Returns:
      (buf, lens): uint8 [B, out_len] streams and int32 [B] byte
      counts — exactly the inputs of :func:`..pipeline.wire_pipeline_step`.
      The wire has no gaps, so absent frames are compacted away: a
      later decode yields the emitted frames left-packed in order
      (property-tested, including interleaved absent frames).
    """
    valid = body_sizes >= 16
    sizes = jnp.where(valid, body_sizes, 0)
    frame_sizes = jnp.where(valid, sizes + 4, 0)
    ends = jnp.cumsum(frame_sizes, axis=1)
    starts = ends - frame_sizes
    fits = valid & (ends <= out_len)
    lens = jnp.max(jnp.where(fits, ends, 0), axis=1).astype(jnp.int32)

    # [B, F, 20] header byte values...
    hdr = jnp.concatenate([
        _be_bytes(sizes[..., None]),
        _be_bytes(xid[..., None]),
        _be_bytes(zxid_hi[..., None]),
        _be_bytes(zxid_lo[..., None]),
        _be_bytes(err[..., None]),
    ], axis=-1)
    # ...scattered at each frame's cumulative offset.
    cols = starts[..., None] + jnp.arange(_HDR, dtype=jnp.int32)
    B = xid.shape[0]
    rows = jnp.broadcast_to(
        jnp.arange(B, dtype=jnp.int32)[:, None, None], cols.shape)
    cols = jnp.where(fits[..., None], cols, out_len)  # park dropped
    buf = jnp.zeros((B, out_len + 1), jnp.uint8)
    buf = buf.at[rows, cols].set(hdr, mode='drop')
    return buf[:, :out_len], lens
