"""Fused Pallas TPU kernel for the wire-decode hot path.

One kernel invocation = frame scan + reply-header parse for a block of
connection streams, entirely in VMEM.  This fuses what
:mod:`zkstream_tpu.ops.frame_scan` and :mod:`zkstream_tpu.ops.headers`
express as separate XLA ops (a ``lax.scan`` whose every step re-gathers
from the HBM-resident buffer, then a second gather pass for headers)
into a single pass: the byte block is staged into VMEM once, and the
per-frame cursor walk plus all five header-field reads run on-chip as
weighted lane-reduces — each 4-byte window gets big-endian place
values (1 << 8*(3-d)) and a row-sum assembles the word.  That is the
VPU-shaped formulation of a per-row dynamic gather, which Mosaic has
no native vector instruction for.

Semantics match ``frame_cursor_scan`` + ``parse_reply_headers`` exactly
(property-tested against them in tests/test_pallas.py); both re-state
the reference's sequential decode loop, lib/zk-streams.js:39-99, and
drain-loop routing, lib/connection-fsm.js:213-229, as array code.

Grid: one program per row-block, ``dimension_semantics=("parallel",)``
so Megacore splits blocks across TensorCores.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..protocol.consts import MAX_PACKET

# Header field offsets relative to the frame's length prefix: the body
# begins at +4 with xid:int32, zxid:int64 (as hi/lo words), err:int32
# (reference: lib/zk-buffer.js:275-331).
_LEN_OFF = 0
_XID_OFF = 4
_ZHI_OFF = 8
_ZLO_OFF = 12
_ERR_OFF = 16
# widest read starts at cur + 16 and spans 4 bytes -> need 20 bytes of
# zero padding past the last valid position so speculative reads of
# masked-off lanes stay in bounds
_PAD = 20


def _word_plane(buf_ref):
    """Precompute, once per block, the big-endian int32 word STARTING
    at every byte position: w32[r, l] = b[l]<<24 | b[l+1]<<16 |
    b[l+2]<<8 | b[l+3] (the vectorized restatement of
    lib/jute-buffer.js:102-106).  Static lane rotates are native
    Mosaic ops; the wrap-around at the row tail only touches positions
    >= n - 3, which every reader masks off.  Non-overlapping bit
    planes, so wrapping int32 adds reproduce the signed bit pattern
    exactly."""
    _R, Lp = buf_ref.shape
    b = buf_ref[:].astype(jnp.int32)
    return ((b << 24) + (pltpu.roll(b, Lp - 1, 1) << 16)
            + (pltpu.roll(b, Lp - 2, 1) << 8)
            + pltpu.roll(b, Lp - 3, 1))


def _scan_frame(lane, w32, n, cur, bad):
    """One frame step of the cursor scan, shared by the tick kernel
    and the fused full-decode kernel so the frame state machine cannot
    diverge between them.  One subtract per step; each field read is a
    single-lane equality select + row-sum over the precomputed words —
    no per-field variable shifts or int multiplies in the loop.

    Returns (start, size, ln, hdr_ok, (xid, zhi, zlo, err), new_cur,
    new_bad, gather) — ``gather`` reads more 4-byte words at offsets
    relative to the frame's length prefix."""
    d = lane - cur

    def gather(off):
        return jnp.sum(jnp.where(d == off, w32, 0),
                       axis=1, keepdims=True)

    has_prefix = cur + 4 <= n
    ln = jnp.where(has_prefix, gather(_LEN_OFF), 0)
    is_bad = has_prefix & ((ln < 0) | (ln > MAX_PACKET))
    complete = (has_prefix & ~is_bad & (bad == 0)
                & (cur + 4 + ln <= n))
    start = jnp.where(complete, cur + 4, -1)
    size = jnp.where(complete, ln, 0)
    # header fields only exist when the body holds the full 16-byte
    # reply header; shorter complete frames are protocol violations
    # surfaced via size (pipeline flags them as short)
    hdr_ok = complete & (ln >= 16)
    fields = tuple(jnp.where(hdr_ok, gather(off), 0)
                   for off in (_XID_OFF, _ZHI_OFF, _ZLO_OFF, _ERR_OFF))
    return (start, size, ln, hdr_ok, fields,
            jnp.where(complete, cur + 4 + ln, cur),
            bad | is_bad.astype(jnp.int32), gather)


def _kernel(buf_ref, len_ref, starts_ref, sizes_ref, xid_ref,
            zhi_ref, zlo_ref, err_ref, resid_ref, bad_ref,
            *, max_frames: int):
    """Scan one [R, Lp] uint8 block; emit [F, R] frame/header planes."""
    R, Lp = buf_ref.shape
    lane = jax.lax.broadcasted_iota(jnp.int32, (R, Lp), 1)
    n = len_ref[:]  # [R, 1]
    w32 = _word_plane(buf_ref)

    def step(j, carry):
        cur, bad = carry  # bad is int32 0/1 (Mosaic-friendly carry)
        (start, size, _ln, _hdr_ok, (xid, zhi, zlo, err),
         cur, bad, _gather) = _scan_frame(lane, w32, n, cur, bad)
        row = pl.ds(j, 1)
        starts_ref[row, :] = start.reshape(1, R)
        sizes_ref[row, :] = size.reshape(1, R)
        xid_ref[row, :] = xid.reshape(1, R)
        zhi_ref[row, :] = zhi.reshape(1, R)
        zlo_ref[row, :] = zlo.reshape(1, R)
        err_ref[row, :] = err.reshape(1, R)
        return (cur, bad)

    cur0 = jnp.zeros((R, 1), jnp.int32)
    bad0 = jnp.zeros((R, 1), jnp.int32)
    cur, bad = jax.lax.fori_loop(0, max_frames, step, (cur0, bad0))
    resid_ref[0, :] = cur.reshape(R)
    bad_ref[0, :] = bad.reshape(R)


#: Stat word layout for the fused full-decode kernel: 17 big-endian
#: int32 words covering the 68-byte Stat (6 longs as hi/lo pairs + 5
#: ints), wire order (reference: lib/zk-buffer.js:428-442) — index i
#: reads at byte offset 4*i from the Stat start.
_STAT_WORDS = 17


def _full_kernel(buf_ref, len_ref, starts_ref, sizes_ref, xid_ref,
                 zhi_ref, zlo_ref, err_ref, dlen_ref, dw_ref, sw_ref,
                 resid_ref, bad_ref,
                 *, max_frames: int, max_data: int):
    """The tick kernel (_kernel) with the GET_DATA body fused in: the
    jute buffer length at body+4, the data bytes (as BE words), and
    the Stat record after the data — all gathered in the same VMEM
    pass, no intermediate HBM round trip (VERDICT r3 next #3's
    experiment).  Layout: lib/zk-buffer.js:353-357 (buffer then Stat).
    """
    R, Lp = buf_ref.shape
    DW = max_data // 4
    lane = jax.lax.broadcasted_iota(jnp.int32, (R, Lp), 1)
    n = len_ref[:]
    w32 = _word_plane(buf_ref)

    def step(j, carry):
        cur, bad = carry
        (start, size, ln, hdr_ok, (xid, zhi, zlo, err),
         new_cur, new_bad, gather) = _scan_frame(lane, w32, n, cur,
                                                 bad)

        # -- GET_DATA body: buffer(len, bytes) at body+4, then Stat --
        # raw jute length field (may be -1 = empty); masked to frames
        # with a full reply header.  Clamp before extent arithmetic:
        # a wire length near INT32_MAX must not wrap the checks below
        # (mirrors replies._ustring_at).
        draw = jnp.where(hdr_ok, gather(20), 0)
        nb = jnp.minimum(jnp.maximum(draw, 0), MAX_PACKET + 1)
        # data words: bytes cur+24 .. cur+24+max_data as BE words;
        # gather only words the field reaches (byte masking happens in
        # the XLA unpack, where it is elementwise)
        row = pl.ds(j, 1)
        for w in range(DW):
            need = hdr_ok & (4 * w < nb)
            dw_ref[pl.ds(j * DW + w, 1), :] = jnp.where(
                need, gather(24 + 4 * w), 0).reshape(1, R)
        # Stat after the data: valid only when its 68 bytes fit the
        # frame (20 + nb + 68 <= ln, the parse_stats extent rule)
        s_ok = hdr_ok & (20 + nb + 68 <= ln)
        s_off = 24 + nb
        for w in range(_STAT_WORDS):
            sw_ref[pl.ds(j * _STAT_WORDS + w, 1), :] = jnp.where(
                s_ok, gather(s_off + 4 * w), 0).reshape(1, R)

        starts_ref[row, :] = start.reshape(1, R)
        sizes_ref[row, :] = size.reshape(1, R)
        xid_ref[row, :] = xid.reshape(1, R)
        zhi_ref[row, :] = zhi.reshape(1, R)
        zlo_ref[row, :] = zlo.reshape(1, R)
        err_ref[row, :] = err.reshape(1, R)
        dlen_ref[row, :] = draw.reshape(1, R)
        return (new_cur, new_bad)

    cur0 = jnp.zeros((R, 1), jnp.int32)
    bad0 = jnp.zeros((R, 1), jnp.int32)
    cur, bad = jax.lax.fori_loop(0, max_frames, step, (cur0, bad0))
    resid_ref[0, :] = cur.reshape(R)
    bad_ref[0, :] = bad.reshape(R)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _read_vmem_limit() -> int:
    """Per-program scoped-VMEM ceiling used by the compile guard.

    Defaults to the 16 MiB budget calibrated on v5e; other TPU
    generations (or future Mosaic versions) may allow more, so the
    guard is overridable via ``ZKSTREAM_PALLAS_VMEM_BYTES``.  Read
    once at import: ``pallas_wire_scan`` is jitted, so a per-call read
    would only take effect at first trace per shape and could diverge
    from ``fits_vmem``."""
    import os
    import warnings
    env = os.environ.get('ZKSTREAM_PALLAS_VMEM_BYTES')
    if env:
        try:
            val = int(env)
        except ValueError:
            val = -1
        if val > 0:
            return val
        warnings.warn(
            'ignoring ZKSTREAM_PALLAS_VMEM_BYTES=%r (must be a '
            'positive integer byte count); using 16 MiB' % (env,))
    return 16 * 1024 * 1024


_VMEM_LIMIT = _read_vmem_limit()


def _vmem_estimate(R: int, Lp: int, max_frames: int,
                   words_per_frame: int = 6) -> int:
    """Projected scoped-VMEM bytes for one program: ~3 int32 planes of
    [R, Lp] live at once (byte plane, rolled word plane, lane iota /
    temporaries) plus the double-buffered u8 input and the per-frame
    output blocks (6 int32 words/frame for the tick kernel; the fused
    full-decode kernel adds the dlen/data/Stat words).  Calibrated
    against observed Mosaic stack OOMs (20.8M at R=256, Lp=5120;
    20.5M at R=128, Lp=13568)."""
    plane = R * Lp * 4
    return (int(3.2 * plane) + words_per_frame * max_frames * R * 4
            + (1 << 20))


def _block_shape(B: int, L: int, block_rows: int,
                 interpret: bool = False) -> tuple[int, int, int]:
    """(R, Bp, Lp) blocking for one kernel program.  Mosaic tiling: the
    [F, R] output blocks put rows on the lane axis, so a multi-block
    grid needs R % 128 == 0; a single block spanning the whole (padded)
    batch is exempt.  Shared by the compile path and fits_vmem so the
    guard can never drift from the actual blocking."""
    if interpret:
        R = min(block_rows, _round_up(B, 8))
        Bp = _round_up(B, R)
    elif B <= block_rows:
        R = Bp = _round_up(B, 8)
    else:
        R = _round_up(block_rows, 128)
        Bp = _round_up(B, R)
    return R, Bp, _round_up(L + _PAD, 128)


def fits_vmem(B: int, L: int, max_frames: int = 32,
              block_rows: int = 64) -> bool:
    """Whether :func:`pallas_wire_scan` can compile for this shape
    without exceeding the per-program scoped-VMEM limit."""
    R, _Bp, Lp = _block_shape(B, L, block_rows)
    return _vmem_estimate(R, Lp, max_frames) <= _VMEM_LIMIT


@functools.partial(
    jax.jit, static_argnames=('max_frames', 'block_rows', 'interpret'))
def pallas_wire_scan(buf, lens, max_frames: int = 32,
                     block_rows: int = 64, interpret: bool = False):
    """Fused frame scan + header parse on TPU via Pallas.

    Args:
      buf: uint8 [B, L] accumulated bytes per connection.
      lens: int32 [B] valid byte counts.
      max_frames: static per-stream frame bound.
      block_rows: streams per kernel program (grid = B / block_rows).
      interpret: run in the Pallas interpreter (for CPU-based tests).

    Returns:
      dict with int32 [B, F] planes ``starts``, ``sizes``, ``xid``,
      ``zxid_hi``, ``zxid_lo``, ``err``; int32 [B] ``counts`` and
      ``resid``; bool [B] ``bad`` — field-for-field the outputs of
      ``frame_cursor_scan`` + ``parse_reply_headers``.
    """
    B, L = buf.shape
    R, Bp, Lp = _block_shape(B, L, block_rows, interpret)
    if not interpret and \
            _vmem_estimate(R, Lp, max_frames) > _VMEM_LIMIT:
        raise ValueError(
            'pallas_wire_scan shape (rows/program R=%d from '
            'block_rows=%d, L=%d, max_frames=%d) needs ~%d MiB of '
            'scoped VMEM (> %d MiB limit); shrink block_rows or L, or '
            'use the jnp pipeline (wire_pipeline_step), which has no '
            'such bound'
            % (R, block_rows, L, max_frames,
               _vmem_estimate(R, Lp, max_frames) >> 20,
               _VMEM_LIMIT >> 20))

    buf = jnp.zeros((Bp, Lp), jnp.uint8).at[:B, :L].set(buf)
    lens = jnp.zeros((Bp, 1), jnp.int32).at[:B, 0].set(
        lens.astype(jnp.int32))

    kern = functools.partial(_kernel, max_frames=max_frames)
    plane = jax.ShapeDtypeStruct((max_frames, Bp), jnp.int32)
    rowvec = jax.ShapeDtypeStruct((1, Bp), jnp.int32)
    grid = (Bp // R,)
    in_specs = [
        pl.BlockSpec((R, Lp), lambda i: (i, 0)),
        pl.BlockSpec((R, 1), lambda i: (i, 0)),
    ]
    plane_spec = pl.BlockSpec((max_frames, R), lambda i: (0, i))
    row_spec = pl.BlockSpec((1, R), lambda i: (0, i))

    out = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=(plane_spec,) * 6 + (row_spec, row_spec),
        out_shape=(plane,) * 6 + (rowvec, rowvec),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=('parallel',)),
        interpret=interpret,
    )(buf, lens)
    starts, sizes, xid, zhi, zlo, err, resid, bad = out

    def unpad(p):
        return jnp.moveaxis(p, 0, 1)[:B]

    starts = unpad(starts)
    return {
        'starts': starts,
        'sizes': unpad(sizes),
        'xid': unpad(xid),
        'zxid_hi': unpad(zhi),
        'zxid_lo': unpad(zlo),
        'err': unpad(err),
        'counts': jnp.sum((starts >= 0).astype(jnp.int32), axis=1),
        'resid': resid[0, :B],
        'bad': bad[0, :B].astype(jnp.bool_),
    }


def full_scan_words(max_data: int) -> int:
    """Output words/frame of the fused full-decode kernel (for the
    VMEM guard): 6 tick planes + dlen + data words + Stat words."""
    return 7 + max_data // 4 + _STAT_WORDS


def fits_vmem_full(B: int, L: int, max_frames: int = 32,
                   block_rows: int = 64, max_data: int = 16) -> bool:
    """VMEM guard for :func:`pallas_wire_full_scan`."""
    R, _Bp, Lp = _block_shape(B, L, block_rows)
    return _vmem_estimate(R, Lp, max_frames,
                          full_scan_words(max_data)) <= _VMEM_LIMIT


@functools.partial(
    jax.jit, static_argnames=('max_frames', 'block_rows', 'max_data',
                              'interpret'))
def pallas_wire_full_scan(buf, lens, max_frames: int = 32,
                          block_rows: int = 64, max_data: int = 16,
                          interpret: bool = False):
    """Fused FULL decode on TPU via Pallas: frame scan + reply header
    + the GET_DATA body (jute buffer length, data bytes, trailing
    Stat) in one VMEM pass — the experiment that decides whether a
    custom kernel earns its keep on the body path (VERDICT r3 next
    #3; the jnp alternative round-trips frame planes through HBM
    between the scan and each body gather).

    Returns the tick planes of :func:`pallas_wire_scan` plus:
      ``dlen_raw``  int32 [B, F]  raw jute length field at body+4
                    (pre-validity; consumers apply the extent rule);
      ``data_words`` int32 [B, F, max_data//4]  payload bytes as BE
                    words (unpack + byte-mask on the XLA side);
      ``stat_words`` int32 [B, F, 17]  the Stat record as BE words,
                    zeroed where the Stat does not fit the frame.
    """
    if max_data % 4:
        raise ValueError('max_data must be a multiple of 4')
    B, L = buf.shape
    R, Bp, Lp = _block_shape(B, L, block_rows, interpret)
    DW = max_data // 4
    words = full_scan_words(max_data)
    if not interpret and \
            _vmem_estimate(R, Lp, max_frames, words) > _VMEM_LIMIT:
        raise ValueError(
            'pallas_wire_full_scan shape (R=%d, L=%d, max_frames=%d, '
            'max_data=%d) needs ~%d MiB scoped VMEM (> %d MiB); '
            'shrink block_rows/L/max_data or use the jnp full decode'
            % (R, L, max_frames, max_data,
               _vmem_estimate(R, Lp, max_frames, words) >> 20,
               _VMEM_LIMIT >> 20))

    buf = jnp.zeros((Bp, Lp), jnp.uint8).at[:B, :L].set(buf)
    lens = jnp.zeros((Bp, 1), jnp.int32).at[:B, 0].set(
        lens.astype(jnp.int32))

    kern = functools.partial(_full_kernel, max_frames=max_frames,
                             max_data=max_data)
    plane = jax.ShapeDtypeStruct((max_frames, Bp), jnp.int32)
    dplane = jax.ShapeDtypeStruct((max_frames * DW, Bp), jnp.int32)
    splane = jax.ShapeDtypeStruct((max_frames * _STAT_WORDS, Bp),
                                  jnp.int32)
    rowvec = jax.ShapeDtypeStruct((1, Bp), jnp.int32)
    grid = (Bp // R,)
    in_specs = [
        pl.BlockSpec((R, Lp), lambda i: (i, 0)),
        pl.BlockSpec((R, 1), lambda i: (i, 0)),
    ]
    plane_spec = pl.BlockSpec((max_frames, R), lambda i: (0, i))
    dw_spec = pl.BlockSpec((max_frames * DW, R), lambda i: (0, i))
    sw_spec = pl.BlockSpec((max_frames * _STAT_WORDS, R),
                           lambda i: (0, i))
    row_spec = pl.BlockSpec((1, R), lambda i: (0, i))

    out = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=(plane_spec,) * 7 + (dw_spec, sw_spec)
        + (row_spec, row_spec),
        out_shape=(plane,) * 7 + (dplane, splane) + (rowvec, rowvec),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=('parallel',)),
        interpret=interpret,
    )(buf, lens)
    (starts, sizes, xid, zhi, zlo, err, dlen, dw, sw,
     resid, bad) = out

    def unpad(p):
        return jnp.moveaxis(p, 0, 1)[:B]

    def unpad3(p, k):
        # [F*k, Bp] -> [B, F, k]
        return jnp.transpose(
            p.reshape(max_frames, k, -1), (2, 0, 1))[:B]

    starts = unpad(starts)
    return {
        'starts': starts,
        'sizes': unpad(sizes),
        'xid': unpad(xid),
        'zxid_hi': unpad(zhi),
        'zxid_lo': unpad(zlo),
        'err': unpad(err),
        'counts': jnp.sum((starts >= 0).astype(jnp.int32), axis=1),
        'resid': resid[0, :B],
        'bad': bad[0, :B].astype(jnp.bool_),
        'dlen_raw': unpad(dlen),
        'data_words': unpad3(dw, DW),
        'stat_words': unpad3(sw, _STAT_WORDS),
    }
