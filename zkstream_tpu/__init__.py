"""zkstream_tpu — a from-scratch Python rebuild of the capabilities of
TritonDataCenter/node-zkstream: a minimal, streams-oriented ZooKeeper
wire-protocol client (Jute codec, length-prefixed framing, connection and
session state machines, watcher engine with lost-wakeup self-checking,
ensemble failover with session resumption), plus an in-process ZooKeeper
server for tests.

The reference (mounted at /root/reference) is pure JavaScript with zero
native components and no ML workload; see SURVEY.md and BASELINE.json for
the structural analysis.
"""

__version__ = '0.1.0'

from .client import Client  # noqa: F401
from .protocol.consts import CreateFlag, Perm  # noqa: F401
from .protocol.errors import (  # noqa: F401
    ZKError,
    ZKNotConnectedError,
    ZKPingTimeoutError,
    ZKProtocolError,
)
from .protocol.records import ACL, OPEN_ACL_UNSAFE, Id, Stat  # noqa: F401
from .utils.logging import Logger  # noqa: F401
from .utils.metrics import Collector  # noqa: F401
