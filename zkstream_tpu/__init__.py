"""zkstream_tpu — a from-scratch Python rebuild of the capabilities of
TritonDataCenter/node-zkstream: a minimal, streams-oriented ZooKeeper
wire-protocol client (Jute codec, length-prefixed framing, connection and
session state machines, watcher engine with lost-wakeup self-checking,
ensemble failover with session resumption), plus an in-process ZooKeeper
server for tests.

Layer map (the analogue of the reference's overview diagram,
lib/index.js:14-54; see PARITY.md for the full component table)::

    client.py            Client — public API facade, event surface
      |                    (FSM: normal/closing/closed)
    io/pool.py           ConnectionPool — backend set, retry policy,
      |                    decoherence rebalance (cueball equivalent)
    io/connection.py     ZKConnection — one TCP connection's lifecycle,
      |   \\                xids, pending requests, ping keepalive
      |    io/session.py ZKSession — the durable session (peer of the
      |    io/watcher.py   connection, attaches to whichever is live);
      |                    ZKWatcher/ZKWatchEvent re-arm engine
    protocol/framing.py  FrameDecoder/PacketCodec — length-prefixed
      |                    framing, symmetric client/server mode
    protocol/records.py  message bodies, special-XID dispatch, Stat/ACL
    protocol/jute.py     Jute primitive codec
    protocol/consts.py   opcodes, error codes, perms, XIDs
    utils/               FSM base, events, metrics, logging, native
    ops/ parallel/       the TPU data plane: batched/sharded wire codec

The reference (mounted at /root/reference) is pure JavaScript with zero
native components and no ML workload; see SURVEY.md and BASELINE.json for
the structural analysis.
"""

__version__ = '0.1.0'

from .client import Client  # noqa: F401
from .protocol.consts import CreateFlag, Perm  # noqa: F401
from .protocol.errors import (  # noqa: F401
    ZKDeadlineError,
    ZKError,
    ZKNotConnectedError,
    ZKPingTimeoutError,
    ZKProtocolError,
)
from .protocol.records import ACL, OPEN_ACL_UNSAFE, Id, Stat  # noqa: F401
from .utils.logging import Logger  # noqa: F401
from .utils.metrics import Collector, Histogram  # noqa: F401
from .utils.trace import TraceRing  # noqa: F401
