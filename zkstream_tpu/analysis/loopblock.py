"""Checker: blocking calls must never run on the event loop.

The PR 5 durability rule — "fsync never blocks the loop" — plus its
generalization: ``os.fsync``, ``time.sleep``, ``subprocess`` waits and
synchronous socket dials belong on an executor thread (or in a sync
function the loop never runs).  A violation stalls EVERY session the
loop serves for the duration of the call; the WAL's group fsync and
the fault injector's device-latency sleeps both run inside executor
thunks for exactly this reason (server/persist.py ``work()``).

Flagged contexts:

- a blocking call whose nearest enclosing function is ``async def``;
- a blocking call inside a sync function (or lambda) that this module
  hands to the loop: an argument to ``call_soon`` / ``call_later`` /
  ``call_at`` / ``call_soon_threadsafe`` / ``add_done_callback``.

Not flagged: calls inside nested sync ``def`` bodies that are not
loop-registered (executor thunks — ``run_in_executor`` receives the
function object, so the blocking call's nearest enclosing function is
the thunk, not the coroutine).

Escape hatch: ``# zkanalyze: off-loop <reason>`` on the call line —
the reason prints in ``--list-suppressions``.
"""

from __future__ import annotations

import ast

from .core import (Context, Finding, FuncStackVisitor, Module,
                   import_aliases, resolve_call)

NAME = 'loop-blocking'

#: Dotted call targets that block the calling thread.
BLOCKING = {
    'os.fsync': 'fsync blocks until the device acks',
    'os.fdatasync': 'fdatasync blocks until the device acks',
    'time.sleep': 'sleep parks the whole loop, not one task',
    'subprocess.run': 'waits for child exit',
    'subprocess.call': 'waits for child exit',
    'subprocess.check_call': 'waits for child exit',
    'subprocess.check_output': 'waits for child exit',
    'socket.create_connection': 'synchronous TCP dial',
    'socket.getaddrinfo': 'synchronous resolver round trip',
}

#: Loop-callback registration points: a sync function passed here
#: runs ON the loop.
REGISTRARS = ('call_soon', 'call_later', 'call_at',
              'call_soon_threadsafe', 'add_done_callback')


def _callback_targets(tree: ast.AST) -> tuple[set[str], set[int]]:
    """Names (and lambda node ids) this module registers as loop
    callbacks."""
    names: set[str] = set()
    lambdas: set[int] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in REGISTRARS):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Name):
                names.add(arg.id)
            elif isinstance(arg, ast.Attribute):
                names.add(arg.attr)
            elif isinstance(arg, ast.Lambda):
                lambdas.add(id(arg))
    return names, lambdas


class _Visitor(FuncStackVisitor):
    def __init__(self, module: Module, aliases: dict[str, str],
                 cb_names: set[str], cb_lambdas: set[int]):
        super().__init__()
        self.module = module
        self.aliases = aliases
        self.cb_names = cb_names
        self.cb_lambdas = cb_lambdas
        self.findings: list[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        target = resolve_call(node, self.aliases)
        why = BLOCKING.get(target or '')
        if why is not None and self.stack:
            fn = self.stack[-1]
            ctx = None
            if isinstance(fn, ast.AsyncFunctionDef):
                ctx = 'async def %s' % (fn.name,)
            elif (isinstance(fn, ast.FunctionDef)
                    and fn.name in self.cb_names):
                ctx = 'loop callback %s' % (fn.name,)
            elif (isinstance(fn, ast.Lambda)
                    and id(fn) in self.cb_lambdas):
                ctx = 'loop-registered lambda'
            if ctx is not None:
                self.findings.append(Finding(
                    self.module.path, node.lineno, NAME,
                    'blocking call %s() on the event loop (%s; %s) '
                    '— run_in_executor it, or annotate '
                    '"# zkanalyze: off-loop <reason>"'
                    % (target, ctx, why)))
        self.generic_visit(node)


def check(module: Module, ctx: Context) -> list[Finding]:
    aliases = import_aliases(module.tree)
    cb_names, cb_lambdas = _callback_targets(module.tree)
    v = _Visitor(module, aliases, cb_names, cb_lambdas)
    v.visit(module.tree)
    return v.findings
