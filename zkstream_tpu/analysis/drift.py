"""Checker: knobs and metrics must not drift from the README
inventory, and a metric's label set must be fixed.

Every ``ZKSTREAM_*`` environment read and every metric name
registered on a collector is part of the operator surface — the
README's knob mentions and metrics table ARE the inventory operators
grep.  A knob or series that exists only in code is invisible until
the incident where it mattered; the reference gates the same way by
hand-reviewing artedi registrations.

Three rules:

- every ``os.environ.get('ZKSTREAM_X')`` / ``os.environ['ZKSTREAM_X']``
  / ``os.getenv('ZKSTREAM_X')`` name must appear in README.md;
- every registered metric name (``collector.counter/histogram/gauge/
  multi_gauge``) must appear in README.md — names are resolved
  through module-level ``METRIC_* = '...'`` constants (cross-module,
  via the shared constant table) and, for loop/prefix registrations,
  by scanning the registering function for metric-shaped string
  literals;
- a metric's label KEY set must be identical at every ``increment`` /
  ``observe`` call site that passes a literal dict — the Prometheus
  rule that a series' label names are fixed at registration
  (mismatched keys silently split one series into two).
"""

from __future__ import annotations

import ast
import re

from .core import Context, Finding, Module, dotted_name

NAME = 'drift'

ENV_NAME_RE = re.compile(r'^ZKSTREAM_[A-Z0-9_]+$')
METRIC_NAME_RE = re.compile(r'^(zk|zookeeper|zkstream)_[a-z0-9_]+$')
REG_ATTRS = ('counter', 'histogram', 'gauge', 'multi_gauge')
_REG_RECV_RE = re.compile(r'(?i)(collector|source)')
USE_ATTRS = ('increment', 'observe')


def _const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _collect_env_reads(module: Module, ctx: Context) -> None:
    for node in ast.walk(module.tree):
        name = None
        if isinstance(node, ast.Call):
            target = dotted_name(node.func) or ''
            if (target.endswith('environ.get')
                    or target.endswith('environ.pop')
                    or target.endswith('os.getenv')
                    or target == 'getenv') and node.args:
                name = _const_str(node.args[0])
        elif (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and (dotted_name(node.value) or '')
                .endswith('environ')):
            # Load only: os.environ['X'] = '1' is a write (the
            # child-process handshake pattern), not a knob read
            name = _const_str(node.slice)
        if name is not None and ENV_NAME_RE.match(name):
            ctx.env_reads.append((name, module.path, node.lineno))


def _enclosing_function_strings(module: Module,
                                call: ast.Call) -> list[str]:
    """Metric-shaped string literals in the function containing
    ``call`` — the fallback for loop/prefix registrations
    (``collector.gauge(prefix + name, ...)`` over a literal table,
    server/persist.py / io/ingest.py style)."""
    best: ast.AST | None = None
    for fn in ast.walk(module.tree):
        if not isinstance(fn, (ast.FunctionDef,
                               ast.AsyncFunctionDef)):
            continue
        if (fn.lineno <= call.lineno
                and call.lineno <= (fn.end_lineno or fn.lineno)):
            if best is None or fn.lineno > best.lineno:
                best = fn
    if best is None:
        return []
    out = []
    for node in ast.walk(best):
        s = _const_str(node)
        if s is not None and METRIC_NAME_RE.match(s):
            out.append(s)
    return out


def _collect_registrations(module: Module, ctx: Context,
                           findings: list[Finding]) -> None:
    #: (attr-or-var name) -> metric name, for label-use resolution
    var_map: dict[str, str] = {}
    local_consts: dict[str, str] = {}
    for node in module.tree.body:
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    local_consts[t.id] = node.value.value
    assign_of: dict[int, ast.Assign] = {}
    for node in ast.walk(module.tree):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            assign_of[id(node.value)] = node
    for reg in ast.walk(module.tree):
        if not (isinstance(reg, ast.Call)
                and isinstance(reg.func, ast.Attribute)
                and reg.func.attr in REG_ATTRS
                and _REG_RECV_RE.search(module.src(reg.func.value))
                and reg.args):
            continue
        assign = assign_of.get(id(reg))
        arg0 = reg.args[0]
        names: list[str] = []
        resolved_one = _const_str(arg0)
        if resolved_one is None and isinstance(arg0, ast.Name):
            # the module's OWN constant wins; the cross-module table
            # only resolves imported names (a same-named constant in
            # another module must not shadow this one)
            resolved_one = local_consts.get(
                arg0.id, ctx.constants.get(arg0.id))
        if resolved_one is not None:
            names = [resolved_one]
        else:
            names = _enclosing_function_strings(module, reg)
            if not names:
                findings.append(Finding(
                    module.path, reg.lineno, NAME,
                    'metric name %r is not statically resolvable '
                    '(no constant, no metric-shaped literal in the '
                    'registering function) — the README inventory '
                    'cannot be checked'
                    % (module.src(arg0),)))
                continue
        if assign is not None and resolved_one is not None:
            for t in assign.targets:
                if isinstance(t, ast.Attribute):
                    var_map[t.attr] = resolved_one
                elif isinstance(t, ast.Name):
                    var_map[t.id] = resolved_one
        for n in names:
            ctx.metric_regs.append((n, module.path, reg.lineno))
    _collect_label_uses(module, ctx, var_map)


def _collect_label_uses(module: Module, ctx: Context,
                        var_map: dict[str, str]) -> None:
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in USE_ATTRS):
            continue
        recv = node.func.value
        key = None
        if isinstance(recv, ast.Attribute):
            key = recv.attr
        elif isinstance(recv, ast.Name):
            key = recv.id
        metric = var_map.get(key or '')
        if metric is None:
            continue
        labels = None
        want_pos = 0 if node.func.attr == 'increment' else 1
        if len(node.args) > want_pos:
            labels = node.args[want_pos]
        for kw in node.keywords:
            if kw.arg == 'labels':
                labels = kw.value
        if not isinstance(labels, ast.Dict):
            continue            # dynamic label dict: unresolvable
        keys = []
        for k in labels.keys:
            s = _const_str(k)
            if s is None:
                break
            keys.append(s)
        else:
            ctx.label_uses.setdefault(metric, {}).setdefault(
                frozenset(keys), (module.path, node.lineno))


def check(module: Module, ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    _collect_env_reads(module, ctx)
    _collect_registrations(module, ctx, findings)
    return findings


def finalize(ctx: Context) -> list[Finding]:
    """Cross-module phase: diff the aggregated inventories against
    the README and check label-set consistency."""
    findings: list[Finding] = []
    readme = ctx.readme_text
    if readme is not None:
        def documented(name: str) -> bool:
            # word-boundary match, not substring: a knob named
            # ZKSTREAM_FLUSH must not ride on ZKSTREAM_FLUSH_CAP's
            # documentation (all inventory names are \w-only, so \b
            # is exact)
            return re.search(r'\b%s\b' % re.escape(name),
                             readme) is not None

        seen: set[str] = set()
        for name, path, line in ctx.env_reads:
            if name in seen or documented(name):
                continue
            seen.add(name)
            findings.append(Finding(
                path, line, NAME,
                'env knob %s is read here but undocumented in '
                'README.md — add it to the knob inventory'
                % (name,)))
        seen = set()
        for name, path, line in ctx.metric_regs:
            if name in seen or documented(name):
                continue
            seen.add(name)
            findings.append(Finding(
                path, line, NAME,
                'metric %s is registered here but missing from the '
                'README metrics table' % (name,)))
    for metric, uses in sorted(ctx.label_uses.items()):
        if len(uses) <= 1:
            continue
        sets = sorted(sorted(s) for s in uses)
        path, line = sorted(uses.values())[0]
        findings.append(Finding(
            path, line, NAME,
            'metric %s is used with conflicting label-key sets %s '
            '— label names are fixed at registration; one series '
            'must not fork' % (metric, sets)))
    return findings
