"""Checker: thread-lock bodies must not suspend; shared attributes
must not be read-modify-written across an ``await``.

The PR 3 ``_apply_until`` class of bug: classes shared across the
loop/thread boundary (anything owning a ``threading.Lock`` — the
replica stores, the ingest placer) interleave loop callbacks with
worker threads.  Two contract halves:

- **await-under-lock** — an ``await`` inside a *sync* ``with <lock>:``
  body holds a thread lock across a suspension point: every thread
  contending for that lock stalls until the loop resumes the
  coroutine, and a resume that needs the same thread deadlocks.
  (``async with`` on asyncio locks is fine and not matched.)
- **rmw-across-await** — in an async method of a lock-owning class, a
  ``self.X`` read followed by an ``await`` followed by a ``self.X``
  write is a lost-update window: the thread side can interleave at
  the suspension and its update is overwritten.

Receiver heuristic for the first half: a ``with`` item whose source
names a recorded threading-lock attribute of the enclosing class, or
whose name has a ``lock``/``mutex`` segment.
"""

from __future__ import annotations

import ast

from .core import (Context, Finding, Module, dotted_name,
                   import_aliases, walk_no_funcs)

NAME = 'await-under-lock'

_LOCK_FACTORIES = {'threading.Lock', 'threading.RLock',
                   'threading.Condition', 'threading.Semaphore',
                   'threading.BoundedSemaphore'}


def _is_lockish_name(text: str) -> bool:
    segs = [s for s in
            text.replace('(', ' ').replace(')', ' ')
            .replace('.', ' ').replace('_', ' ').lower().split()
            if s]
    return any(s in ('lock', 'mutex', 'rlock') for s in segs)


def _lock_attrs(cls: ast.ClassDef,
                aliases: dict[str, str]) -> set[str]:
    """Attribute names assigned a threading lock anywhere in the
    class body (``self._lock = threading.Lock()``)."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        name = dotted_name(node.value.func)
        if name is None:
            continue
        head, _, rest = name.partition('.')
        resolved = aliases.get(head, head)
        full = '%s.%s' % (resolved, rest) if rest else resolved
        if full not in _LOCK_FACTORIES:
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == 'self'):
                out.add(t.attr)
    return out


def _self_attr(node: ast.AST) -> str | None:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == 'self'):
        return node.attr
    return None


def _check_with_bodies(module: Module, cls_locks: set[str],
                       tree: ast.AST, findings: list[Finding],
                       seen_withs: set[int],
                       seen_awaits: set[int]) -> None:
    """``seen_withs`` keeps a With scoped to its innermost class
    (the caller walks classes innermost-first); ``seen_awaits``
    yields ONE finding per await even under nested lock blocks."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.With) or id(node) in seen_withs:
            continue
        seen_withs.add(id(node))
        held = None
        for item in node.items:
            expr = item.context_expr
            attr = _self_attr(expr)
            text = module.src(expr)
            if ((attr is not None and attr in cls_locks)
                    or _is_lockish_name(text)):
                held = text
                break
        if held is None:
            continue
        for sub in node.body:
            for inner in walk_no_funcs(sub):
                if (isinstance(inner, ast.Await)
                        and id(inner) not in seen_awaits):
                    seen_awaits.add(id(inner))
                    findings.append(Finding(
                        module.path, inner.lineno, NAME,
                        'await while holding thread lock %r — '
                        'every contending thread stalls across the '
                        'suspension; release first or use an '
                        'asyncio primitive' % (held,)))


def _check_rmw(module: Module, cls: ast.ClassDef,
               lock_attrs: set[str],
               findings: list[Finding]) -> None:
    for fn in cls.body:
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        reads: dict[str, list[int]] = {}
        writes: list[tuple[str, int, ast.AST]] = []
        awaits: list[int] = []
        for node in walk_no_funcs(fn):
            if isinstance(node, ast.Await):
                awaits.append(node.lineno)
                continue
            attr = _self_attr(node)
            if attr is None or attr in lock_attrs:
                continue
            if isinstance(node.ctx, ast.Load):
                reads.setdefault(attr, []).append(node.lineno)
            elif isinstance(node.ctx, ast.Store):
                writes.append((attr, node.lineno, node))
        seen: set[str] = set()
        for attr, lw, _node in writes:
            if attr in seen:
                continue
            spans = any(lr < lw and any(lr <= la <= lw
                                        for la in awaits)
                        for lr in reads.get(attr, ()))
            if spans:
                seen.add(attr)
                findings.append(Finding(
                    module.path, lw, NAME,
                    'self.%s read before an await and written after '
                    'it in async %s of lock-owning class %s — a '
                    'thread can interleave at the suspension and '
                    'lose its update; recompute after the await or '
                    'restructure' % (attr, fn.name, cls.name)))


def check(module: Module, ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    aliases = import_aliases(module.tree)
    class_nodes = [n for n in ast.walk(module.tree)
                   if isinstance(n, ast.ClassDef)]
    per_class: dict[int, set[str]] = {
        id(cls): _lock_attrs(cls, aliases) for cls in class_nodes}
    # innermost class first (nested classes start on later lines),
    # so a With binds to its OWN class's lock attributes; the final
    # module-level pass catches lock-named managers outside classes
    seen_withs: set[int] = set()
    seen_awaits: set[int] = set()
    for cls in sorted(class_nodes, key=lambda c: -c.lineno):
        _check_with_bodies(module, per_class[id(cls)], cls,
                           findings, seen_withs, seen_awaits)
    _check_with_bodies(module, set(), module.tree, findings,
                       seen_withs, seen_awaits)
    for cls in class_nodes:
        if per_class[id(cls)]:
            _check_rmw(module, cls, per_class[id(cls)], findings)
    return findings
