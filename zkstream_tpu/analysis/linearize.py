"""Per-key WGL linearizability over two-sided chaos histories.

The invariant engine's first eight checks (io/invariants.py) judge
one-sided facts — an acked write exists, zxids never regress per
session.  What they cannot see is the bugs only CONCURRENT writers
expose: a lost update under quorum degrade, a stale read across
failover, an ack sequenced against the CommitBarrier in an order no
sequential execution explains.  This module is invariant 9: a
Wing&Gong-style linearizability search (the worklist form Lowe's
testing framework popularized — "WGL") over the *interval* records
the concurrent tier writes (``History.invoke``/``settle`` pairs),
checked per key against the sequential znode spec.

The consistency contract checked is ZooKeeper's real one, which this
ensemble implements today (README "Ensemble failover matrix"):

- **writes are linearizable.**  Every write routes through the one
  leader; per key — keys a MULTI touches merge into one component,
  the batch applying whole-or-not-at-all, each sub-op at its own
  zxid — the WGL search must find an order consistent with both
  real time (op A precedes op B iff A settled before B invoked) and
  the reply zxids (leader-sequenced: a later-invoked write acked at
  a lower zxid is a circular ack order no sequential execution
  explains), reaching the leader's final tree.
- **reads are prefix-consistent, not linearizable.**  A read may be
  served by a lagging follower, so it may legitimately observe a
  *stale* snapshot of its key — but never a forged one: the
  observed (data, version, mzxid) must be a snapshot some
  zxid-ordered write prefix actually produced, produced by a write
  that had been invoked by the time the read returned (no reading
  the future), and MULTI batches never tear (no snapshot exposes a
  strict sub-batch: sub-zxids are interior points no member state
  ever shows).  :func:`check_session_reads` layers the last rung —
  a session never observes state older than it has already seen —
  held since PR 15 by the zxid read gate (server/server.py
  ReadGate + the client read plane's header-zxid validation) and
  wired into ``check_history`` on both chaos tiers; the env-gated
  ungated path (``ZKSTREAM_NO_READ_GATE=1``) is the validator this
  rung exists to catch.
- **ambiguity** follows invariant 1 exactly: a call whose outcome is
  unknown (CONNECTION_LOSS / deadline / never settled) may linearize
  as applied at any point after its invocation, or be dropped
  entirely.  A call that definitely never applied (``status='fail'``)
  is excluded.

On failure the violation string carries a **minimal counterexample
window**: the linearized frontier at the deepest point the search
reached, the spec state there, and every pending op with the reason
it cannot linearize next — readable next to ``format_history(...,
columns=True)``'s per-client interleaving.

Entry points: :func:`check_linearizable` (wired into
``check_history`` as invariant 9; vacuous on histories with no
interval records), :func:`check_recovered_prefix` (the durability
composition: the crash-recovered tree must equal the zxid-ordered
replay prefix) and :func:`check_session_reads` (the read-plane
gate, wired into ``check_history`` and the process tier's
concurrent pass since PR 15).  Rerun any failing seed with
``python -m zkstream_tpu chaos --tier ensemble --clients N --seed
S``.
"""

from __future__ import annotations

import dataclasses
import math

#: Definite spec verdicts a settle may carry as ``status='error'``:
#: the op linearizes as a no-effect op yielding exactly this error.
SPEC_ERRORS = frozenset(('NO_NODE', 'NODE_EXISTS', 'BAD_VERSION'))

#: Mutating op names (the zxid-ordered ones).
_WRITES = frozenset(('create', 'set', 'set_data', 'delete', 'multi'))

#: Default node budget for one component's search.  The per-key
#: partition + zxid pruning keep real campaign histories orders of
#: magnitude under this (tools/bench_linearize.py guards the cost);
#: hitting it is reported as its own violation, never silent.
MAX_NODES = 250_000


def _b(x):
    """bytes-normalize: JSON-carried corpus histories hold str."""
    if isinstance(x, str):
        return x.encode('utf-8')
    return bytes(x) if x is not None else None


@dataclasses.dataclass
class IntervalOp:
    """One settled call, as the search consumes it."""

    call: int
    client: object
    op: str                     # create|set|delete|get|exists|multi
    path: str | None
    data: bytes | None          # argument payload (writes)
    version: int | None         # argument version (None/-1 = any)
    subs: list | None           # multi: [(op, path, data, version)]
    status: str                 # 'ok' | 'error' | 'unknown'
    error: str | None
    zxid: int | None            # reply zxid / observed stat.mzxid
    obs_data: bytes | None      # reads: observed payload
    obs_version: int | None     # observed stat.version
    invoke_t: int
    settle_t: float             # math.inf while outcome-unknown

    def keys(self) -> list[str]:
        if self.op == 'multi':
            return [s[1] for s in (self.subs or [])]
        return [self.path] if self.path else []

    def label(self) -> str:
        what = self.op if self.op != 'multi' else \
            'multi[%s]' % ','.join('%s %s' % (s[0], s[1])
                                   for s in (self.subs or []))
        bits = ['#%d' % self.call, 'c%s' % (self.client,), what]
        if self.path:
            bits.append(self.path)
        if self.version is not None and self.version >= 0:
            bits.append('v=%d' % self.version)
        bits.append(self.status if self.status != 'error'
                    else str(self.error))
        if self.zxid is not None:
            bits.append('z=%d' % self.zxid)
        return ' '.join(bits)


def intervals(history) -> list['IntervalOp']:
    """Pair the invoke/settle records of a history (a ``History`` or
    a plain record list, JSON-roundtripped corpora included) into
    :class:`IntervalOp` rows.  An invoke with no settle is
    outcome-unknown; ``status='fail'`` settles (definitely never
    applied) are dropped here."""
    records = getattr(history, 'records', history)
    out: dict[int, IntervalOp] = {}
    for r in records:
        if r['kind'] == 'invoke':
            subs = r.get('subs')
            out[r['call']] = IntervalOp(
                call=r['call'], client=r.get('client', 0),
                op=r['op'], path=r.get('path'),
                data=_b(r.get('data')), version=r.get('version'),
                subs=[(s[0], s[1], _b(s[2]),
                       s[3] if len(s) > 3 else None)
                      for s in subs] if subs is not None else None,
                status='unknown', error=None, zxid=None,
                obs_data=None, obs_version=None,
                invoke_t=r['t'], settle_t=math.inf)
        elif r['kind'] == 'settle':
            o = out.get(r['call'])
            if o is None:
                continue            # settle without invoke: ignore
            o.status = r['status']
            o.error = r.get('error')
            o.zxid = r.get('zxid')
            o.obs_data = _b(r.get('data'))
            o.obs_version = r.get('version')
            o.settle_t = r['t']
    return [o for o in out.values() if o.status != 'fail']


# ---------------------------------------------------------------------
# The sequential znode spec.  Per-key state is None (absent) or
# ``(data, version, mzxid)``; mzxid is None when the last effective
# write's zxid is unknown (an applied ambiguous op).
# ---------------------------------------------------------------------


def _apply_write(st, op: str, data, version, zxid):
    """One sub-op against one key's state: ``(outcome, new_state)``
    — outcome 'ok' or the spec error code (state unchanged then)."""
    versioned = version is not None and version >= 0
    if op == 'create':
        if st is not None:
            return 'NODE_EXISTS', st
        return 'ok', (data, 0, zxid)
    if op in ('set', 'set_data'):
        if st is None:
            return 'NO_NODE', st
        if versioned and version != st[1]:
            return 'BAD_VERSION', st
        return 'ok', (data, st[1] + 1, zxid)
    assert op == 'delete', op
    if st is None:
        return 'NO_NODE', st
    if versioned and version != st[1]:
        return 'BAD_VERSION', st
    return 'ok', None


def _try_linearize(o: IntervalOp, state: dict):
    """Attempt to linearize the WRITE ``o`` at ``state`` (a
    key->state dict for the component).  Returns ``(None,
    new_state)`` on success or ``(reason, None)`` when the op cannot
    linearize here.  Unknown-outcome ops succeed only when they
    APPLY with effect (the no-effect/error branch is identical to
    dropping them).  Reads never enter the search — they are
    prefix-consistent, validated against the snapshot logs by
    :func:`_check_reads`."""
    if o.op == 'multi':
        new = dict(state)
        outcome = 'ok'
        subs = o.subs or []
        # each sub-op runs through the exact single-op apply path
        # (server/store.py ``ZKDatabase.multi``), so each consumes
        # its OWN zxid; the batch reply carries the last one — sub i
        # of m committed at reply_zxid - (m - 1 - i)
        m = len(subs)
        for i, (sub, path, data, version) in enumerate(subs):
            z = o.zxid - (m - 1 - i) if o.zxid is not None else None
            outcome, st = _apply_write(new.get(path), sub, data,
                                       version, z)
            if outcome != 'ok':
                break
            new[path] = st
        if o.status == 'error':
            if outcome != 'ok':
                return None, state       # rejected whole: no effect
            return 'spec applies the whole batch, op was ' \
                'rejected', None
        if outcome != 'ok':
            if o.status == 'unknown':
                return 'no effect', None
            return 'spec rejects the batch (%s)' % (outcome,), None
        return None, new
    # single-key write
    outcome, st = _apply_write(state.get(o.path), o.op, o.data,
                               o.version, o.zxid)
    if o.status == 'error':
        if outcome == o.error:
            return None, state           # definite verdict, no effect
        return ('spec says %s, op observed %s'
                % (outcome, o.error)), None
    if outcome != 'ok':
        if o.status == 'unknown':
            return 'no effect', None
        return 'spec says %s, op was acked ok' % (outcome,), None
    new = dict(state)
    new[o.path] = st
    if o.status == 'ok' and o.obs_version is not None \
            and st is not None and st[1] != o.obs_version:
        return ('spec version would be %d, reply stat said %d'
                % (st[1], o.obs_version)), None
    return None, new


# ---------------------------------------------------------------------
# Component partition + the WGL search.
# ---------------------------------------------------------------------


def _components(ops: list[IntervalOp]) -> list[list[IntervalOp]]:
    """Partition ops by key, keys unioned across MULTI batches."""
    parent: dict[str, str] = {}

    def find(k: str) -> str:
        while parent.setdefault(k, k) != k:
            parent[k] = parent[parent[k]]
            k = parent[k]
        return k

    for o in ops:
        keys = o.keys()
        for k in keys[1:]:
            parent[find(k)] = find(keys[0])
    groups: dict[str, list[IntervalOp]] = {}
    for o in ops:
        keys = o.keys()
        if not keys:
            continue
        groups.setdefault(find(keys[0]), []).append(o)
    return [sorted(g, key=lambda o: o.invoke_t)
            for _, g in sorted(groups.items())]


def _state_key(state: dict, keys: tuple) -> tuple:
    return tuple(state.get(k) for k in keys)


#: A key the caller could not read back definitively: its final
#: state places no constraint on the linearization (plain-mapping
#: ``db`` only — a real database's absence IS definitive).
_UNPINNED = object()


def _final_state(db, key: str):
    """The final data for ``key`` from a ZKDatabase-like (``.nodes``
    of objects with ``.data``), or a plain ``{path: bytes|None}``
    mapping; None = absent, a key MISSING from a plain mapping =
    :data:`_UNPINNED` (unconstrained)."""
    if db is None:
        return None
    nodes = getattr(db, 'nodes', None)
    if nodes is not None:
        node = nodes.get(key)
        return None if node is None else bytes(node.data)
    if key not in db:
        return _UNPINNED
    return _b(db.get(key))


def _no_effect(o: IntervalOp) -> bool:
    """Search ops that never change the spec state: definite
    spec-error verdicts (the op linearizes as a no-op yielding the
    error — a write's verdict comes from the leader, so it carries
    full real-time force, unlike a follower-served read)."""
    return o.status == 'error'


def _search(ops: list[IntervalOp], finals: dict | None,
            max_nodes: int):
    """WGL over one component.  Returns ``None`` when a linearization
    exists, else a dict describing the deepest stuck point (or the
    exhausted budget).

    Two prunings keep this flat on real histories (``make
    bench-linearize`` guards the cost):

    - **zxid order**: completed-ok writes are leader-sequenced, so
      only the one with the minimal remaining zxid may linearize
      next — write placement never branches;
    - **greedy no-effect commits**: a candidate no-effect op that
      matches the current state can be committed immediately without
      losing completeness.  Proof sketch: a candidate has no
      remaining op real-time-preceding it (its invoke predates every
      remaining response), so any valid linearization can be
      rewritten with this op moved to the front — it changes no
      state, every other op still sees the same spec.  A
      non-matching no-effect op simply waits for the state to reach
      what it observed; it never branches either.

    Branching therefore comes only from outcome-unknown ops (apply
    now, or keep not applying) — exactly the irreducible ambiguity.
    """
    keys = tuple(sorted({k for o in ops for k in o.keys()}))
    completed = [o for o in ops if o.status in ('ok', 'error')]
    by_id = {o.call: o for o in ops}
    state0: dict = {}
    # DFS frames: (done frozenset, path tuple, state dict)
    stack = [(frozenset(), (), state0)]
    seen: set = set()
    nodes = 0
    best: dict = {'done': (), 'state': state0, 'reject': [],
                  'depth': -1}
    while stack:
        done, path, state = stack.pop()
        # greedily commit matching no-effect candidates (complete,
        # see above); loop because each commit can raise the bound
        progressed = True
        while progressed:
            progressed = False
            remaining = [o for o in completed if o.call not in done]
            if not remaining:
                break
            bound = min(o.settle_t for o in remaining)
            for o in remaining:
                if not _no_effect(o) or o.invoke_t >= bound:
                    continue
                why, _st = _try_linearize(o, state)
                if why is None:
                    done = done | {o.call}
                    path = path + (o.call,)
                    progressed = True
                    break
        mark = (done, _state_key(state, keys))
        if mark in seen:
            continue
        seen.add(mark)
        nodes += 1
        if nodes > max_nodes:
            return {'budget': nodes, 'keys': keys, 'ops': len(ops)}
        remaining = [o for o in completed if o.call not in done]
        if not remaining:
            if finals is None or all(
                    finals.get(k) is _UNPINNED
                    or ((state.get(k) is None)
                        == (finals.get(k) is None)
                        and (state.get(k) is None
                             or state[k][0] == finals[k]))
                    for k in keys):
                return None
            reject = [('final tree', 'component state %s does not '
                       'reach the final tree %s'
                       % (_fmt_state(state, keys),
                          _fmt_finals(finals, keys)))]
        else:
            reject = []
        bound = min(o.settle_t for o in remaining) \
            if remaining else math.inf
        min_zxid = min((o.zxid for o in remaining
                        if o.op in _WRITES and o.status == 'ok'
                        and o.zxid is not None), default=None)
        cands = []
        for o in by_id.values():
            if o.call in done or o.invoke_t >= bound:
                continue
            if o.status == 'error':
                # greedy already commits these when they match; a
                # stuck verdict is window material, not a branch
                why, _st = _try_linearize(o, state)
                if why is not None:
                    reject.append((o.label(), why))
                continue
            cands.append(o)
        # unknown ops pushed first so the completed write (pushed
        # last) pops first: the happy path linearizes greedily
        cands.sort(key=lambda o: (o.status != 'unknown',
                                  -o.invoke_t))
        for o in cands:
            if o.status == 'ok' and o.op in _WRITES \
                    and o.zxid is not None and min_zxid is not None \
                    and o.zxid > min_zxid:
                reject.append((o.label(),
                               'zxid %d cannot precede pending '
                               'zxid %d' % (o.zxid, min_zxid)))
                continue
            why, new = _try_linearize(o, state)
            if why is not None:
                if o.status != 'unknown':
                    reject.append((o.label(), why))
                continue
            stack.append((done | {o.call}, path + (o.call,), new))
        if len(path) > best['depth'] and (remaining or reject):
            best = {'done': path, 'state': state,
                    'reject': reject, 'depth': len(path)}
    best.update(keys=keys, ops=len(ops), by_id=by_id)
    return best


def _fmt_state(state: dict, keys: tuple) -> str:
    bits = []
    for k in keys:
        st = state.get(k)
        if st is None:
            bits.append('%s=absent' % (k,))
        else:
            bits.append('%s=%r v%d%s'
                        % (k, st[0], st[1],
                           '' if st[2] is None else ' z=%d'
                           % (st[2],)))
    return '{%s}' % ', '.join(bits)


def _fmt_finals(finals: dict | None, keys: tuple) -> str:
    if finals is None:
        return '(unconstrained)'
    return '{%s}' % ', '.join(
        '%s=%s' % (k, '?' if finals.get(k) is _UNPINNED
                   else 'absent' if finals.get(k) is None
                   else repr(finals[k])) for k in keys)


def _format_window(stuck: dict) -> str:
    """Render the minimal counterexample window: the frontier at the
    deepest point the search reached, the spec state there, and each
    pending op with why it cannot linearize next."""
    if 'budget' in stuck:
        return ('search budget exceeded (%d nodes over %d ops on '
                '%s) — not a proven violation; rerun with a larger '
                'max_nodes or shrink the schedule'
                % (stuck['budget'], stuck['ops'],
                   ','.join(stuck['keys'])))
    by_id = stuck['by_id']
    frontier = [by_id[c].label() for c in stuck['done'][-4:]]
    lines = ['no linearization over %d op(s) on %s'
             % (stuck['ops'], ','.join(stuck['keys']))]
    lines.append('  linearized %d; frontier: %s'
                 % (len(stuck['done']),
                    ' | '.join(frontier) if frontier else '(start)'))
    lines.append('  spec state: %s'
                 % _fmt_state(stuck['state'], stuck['keys']))
    for label, why in stuck.get('reject', [])[:6]:
        lines.append('  pending: %s — %s' % (label, why))
    return '\n'.join(lines)


# ---------------------------------------------------------------------
# Prefix-consistent reads: per-key snapshot logs from the
# zxid-ordered write prefix, and the validations layered on them.
# ---------------------------------------------------------------------


@dataclasses.dataclass
class _Snap:
    """One snapshot a zxid-ordered write prefix produced for a key:
    the key held ``(data, version)`` for member states T in
    ``[zxid, end)`` — ``end`` is the next write to the key (interior
    zxids of a MULTI batch are no member state at all, so a sub-op's
    snapshot starts at its own zxid but the OBSERVABLE floor jumps
    to the batch end; :func:`check_session_reads` uses ``batch_end``
    for exactly that).  ``absent`` covers the initial state and
    post-delete windows."""

    zxid: int
    absent: bool
    data: bytes | None
    version: int | None          # None once unknown writes blur it
    end: float                   # next write's zxid, or +inf
    batch_end: int | None        # MULTI: the batch's last sub zxid
    invoke_t: int                # producing write's invocation


def _write_events(ops: list[IntervalOp]):
    """Flatten completed-ok writes into per-key (zxid, op, data,
    producing-op) events, MULTI subs at their own zxids."""
    events: list[tuple] = []
    for o in ops:
        if o.status != 'ok' or o.op not in _WRITES \
                or o.zxid is None:
            continue
        if o.op == 'multi':
            subs = o.subs or []
            m = len(subs)
            for i, (sub, path, data, _version) in enumerate(subs):
                events.append((o.zxid - (m - 1 - i), sub, path,
                               data, o.zxid, o))
        else:
            events.append((o.zxid, o.op, o.path, o.data, None, o))
    events.sort(key=lambda e: e[0])
    return events


def _snapshot_logs(ops: list[IntervalOp]):
    """``(logs, fuzzy)``: per-key :class:`_Snap` lists from the
    completed-ok writes, and the set of keys an outcome-unknown (or
    zxid-less) write may also have touched — their version chains
    and snapshot completeness can no longer be trusted exactly."""
    logs: dict[str, list[_Snap]] = {}
    fuzzy: set[str] = set()
    for o in ops:
        if o.op in _WRITES and (o.status == 'unknown'
                                or (o.status == 'ok'
                                    and o.zxid is None)):
            fuzzy.update(o.keys())
    for z, op, path, data, batch_end, src in _write_events(ops):
        log = logs.setdefault(path, [
            _Snap(0, True, None, None, math.inf, None, -1)])
        prev = log[-1]
        prev.end = z
        if op == 'delete':
            snap = _Snap(z, True, None, None, math.inf, batch_end,
                         src.invoke_t)
        elif op == 'create':
            snap = _Snap(z, False, data, 0, math.inf, batch_end,
                         src.invoke_t)
        else:                        # set / set_data
            ver = None if (prev.absent or prev.version is None
                           or path in fuzzy) \
                else prev.version + 1
            snap = _Snap(z, False, data, ver, math.inf, batch_end,
                         src.invoke_t)
        log.append(snap)
    return logs, fuzzy


def _match_read(r: IntervalOp, logs: dict, fuzzy: set,
                unknown_writes: list):
    """Validate one ok/NO_NODE read against the snapshot logs.
    Returns ``(None, snap)`` on success (``snap`` may be None when
    the read was excused by an ambiguous write) or a reason
    string."""
    k = r.path
    log = logs.get(k, [_Snap(0, True, None, None, math.inf, None,
                             -1)])
    blurred = k in fuzzy

    def excused() -> bool:
        # an outcome-unknown write may have produced what was seen
        for o in unknown_writes:
            if k not in o.keys():
                continue
            if r.status == 'error':
                if o.op == 'delete' or o.op == 'multi':
                    return True
            elif r.obs_data is None or o.op == 'multi' \
                    or o.data == r.obs_data:
                return True
        return False

    if r.status == 'error':          # observed NO_NODE
        if any(s.absent for s in log) or excused():
            return None, None
        return ('no write prefix ever leaves %s absent, op '
                'observed NO_NODE' % (k,)), None
    if r.zxid is not None:
        snap = next((s for s in log if s.zxid == r.zxid
                     and not s.absent), None)
        if snap is None:
            if excused():
                return None, None
            return ('observed mzxid %d matches no write on %s'
                    % (r.zxid, k)), None
        if r.op == 'get' and r.obs_data is not None \
                and snap.data != r.obs_data:
            if excused():
                return None, None
            return ('snapshot at mzxid %d holds %r, op observed %r'
                    % (r.zxid, snap.data, r.obs_data)), None
        if r.obs_version is not None and snap.version is not None \
                and not blurred and snap.version != r.obs_version:
            return ('snapshot at mzxid %d is version %d, op '
                    'observed %d' % (r.zxid, snap.version,
                                     r.obs_version)), None
        if snap.invoke_t >= r.settle_t:
            return ('observed the write at zxid %d before it was '
                    'invoked (reply settled at t=%d, write invoked '
                    't=%d)' % (r.zxid, r.settle_t,
                               snap.invoke_t)), None
        return None, snap
    # no mzxid recorded: any matching snapshot (or excuse) will do
    for s in log:
        if s.absent:
            continue
        if r.op == 'get' and r.obs_data is not None \
                and s.data != r.obs_data:
            continue
        if r.obs_version is not None and s.version is not None \
                and not blurred and s.version != r.obs_version:
            continue
        if s.invoke_t < r.settle_t:
            return None, s
    if excused():
        return None, None
    return ('no write prefix produced the observed state '
            '(data %r, version %r)' % (r.obs_data,
                                       r.obs_version)), None


def _check_reads(ops: list[IntervalOp]) -> list[str]:
    """Prefix-consistency of every completed read: the observed
    (data, version, mzxid) must be a snapshot some zxid-ordered
    write prefix produced — stale is legal (a lagging follower may
    have served it), forged or future is not."""
    logs, fuzzy = _snapshot_logs(ops)
    unknown_writes = [o for o in ops if o.op in _WRITES
                      and o.status == 'unknown']
    out = []
    for r in ops:
        if r.op not in ('get', 'exists') \
                or r.status not in ('ok', 'error'):
            continue
        why, _snap = _match_read(r, logs, fuzzy, unknown_writes)
        if why is not None:
            out.append('linearizability: read %s has no '
                       'prefix-consistent explanation — %s'
                       % (r.label(), why))
    return out


def check_session_reads(history) -> list[str]:
    """The read-plane gate, wired into ``check_history`` (PR 15): a
    session never observes state older than what it has already
    seen.  The pool migrates sessions onto lagging followers and
    observers, and the zxid read gate (server/server.py ReadGate:
    every session carries a last-seen-zxid floor, a read on a member
    behind it blocks briefly or bounces; the client read plane adds
    a header-zxid validation on distributed reads) is what holds
    this rung; ``ZKSTREAM_NO_READ_GATE=1`` is the env-gated ungated
    validator this checker exists to catch.

    Per client, in completion order, a floor tracks the newest
    member state the session provably saw (write reply zxids, read
    mzxids — a MULTI sub observation jumps the floor to the batch
    END, its interior zxids being states no member ever shows).  A
    read whose snapshot window dies before the floor is a session
    view regression; keys blurred by outcome-unknown writes are
    skipped."""
    ops = intervals(history)
    if not ops:
        return []
    logs, fuzzy = _snapshot_logs(ops)
    unknown_writes = [o for o in ops if o.op in _WRITES
                      and o.status == 'unknown']
    floors: dict = {}
    out = []
    for r in sorted(ops, key=lambda o: o.settle_t):
        if r.status != 'ok':
            continue
        floor = floors.get(r.client, 0)
        if r.op in _WRITES:
            if r.zxid is not None:
                floors[r.client] = max(floor, r.zxid)
            continue
        if r.path in fuzzy:
            continue
        why, snap = _match_read(r, logs, fuzzy, unknown_writes)
        if why is not None or snap is None:
            continue                 # _check_reads' finding, not ours
        if snap.end <= floor:
            out.append(
                'session-reads: client %s observed %s at mzxid %d '
                '(stale window [%d, %s)) after its session had '
                'already seen zxid %d — the session view went '
                'backwards' % (r.client, r.path, snap.zxid,
                               snap.zxid,
                               '%d' % snap.end
                               if snap.end != math.inf else 'inf',
                               floor))
            continue
        seen = snap.batch_end if snap.batch_end is not None \
            else snap.zxid
        floors[r.client] = max(floor, seen)
    return out


def check_linearizable(history, db=None,
                       floor_zxid: int | None = None,
                       quorum_zxid: int | None = None,
                       max_nodes: int = MAX_NODES) -> list[str]:
    """Invariant 9: the write history admits a WGL linearization
    against the sequential znode spec per key (MULTI-linked keys
    searched as one component, batches atomic), and every read is
    prefix-consistent against the zxid-ordered write snapshots
    (stale is legal — follower reads — forged, torn or future is
    not; :func:`check_session_reads` adds the session-monotone rung
    separately).  ``db`` (the leader's final tree, or a plain
    ``{path: data}`` mapping) additionally pins the linearization's
    end state — an acked write silently dropped on a shared key
    surfaces here even when every read happened to miss it.
    ``floor_zxid``/``quorum_zxid`` demote acks exactly as invariant
    1 does (recovery checks: an ok write past the durable floor
    becomes outcome-unknown, never demoted at or under the quorum
    floor).  Histories with no interval records return []."""
    ops = intervals(history)
    if not ops:
        return []
    if floor_zxid is not None:
        for o in ops:
            if o.status == 'ok' and o.op in _WRITES \
                    and (o.zxid is None or o.zxid > floor_zxid) \
                    and not (quorum_zxid is not None
                             and o.zxid is not None
                             and o.zxid <= quorum_zxid):
                o.status = 'unknown'
    writes = [o for o in ops if o.op in _WRITES]
    out = []
    for comp in _components(writes):
        keys = sorted({k for o in comp for k in o.keys()})
        finals = None
        if db is not None:
            finals = {k: _final_state(db, k) for k in keys}
        stuck = _search(comp, finals, max_nodes)
        if stuck is not None:
            out.append('linearizability: %s' % _format_window(stuck))
    out.extend(_check_reads(ops))
    return out


def check_recovered_prefix(history, rdb) -> list[str]:
    """Durability composition for the concurrent tier: the crash-
    recovered tree must equal the spec replay of the completed-ok
    writes with zxid <= the recovered zxid, in zxid order (the WAL is
    a prefix — a contiguous tail dies with the page cache, never a
    middle record; no fsync floor is needed here, because a write
    with zxid under the recovered zxid is in the replayed prefix by
    construction).  Components containing an outcome-unknown write,
    or an ok write with no zxid, are skipped (the unknown write may
    or may not be in the log; strict equality would false-positive).
    Replay outcomes are themselves checked: an acked write the replay
    rejects is a circular ack order no recovery can explain."""
    ops = intervals(history)
    if not ops:
        return []
    out = []
    for comp in _components(ops):
        writes = [o for o in comp if o.op in _WRITES]
        if any(o.status == 'unknown' or
               (o.status == 'ok' and o.zxid is None)
               for o in writes):
            continue
        keys = sorted({k for o in comp for k in o.keys()})
        state: dict = {}
        replayed = [o for o in writes
                    if o.status == 'ok' and o.zxid <= rdb.zxid]
        replayed.sort(key=lambda o: o.zxid)
        bad = False
        for o in replayed:
            why, new = _try_linearize(o, state)
            if why is not None:
                out.append(
                    'linearizability: recovered replay rejects '
                    'acked %s — %s (ack order has no sequential '
                    'explanation)' % (o.label(), why))
                bad = True
                break
            state = new
        if bad:
            continue
        for k in keys:
            st = state.get(k)
            fin = _final_state(rdb, k)
            if (st is None) != (fin is None) or \
                    (st is not None and st[0] != fin):
                out.append(
                    'linearizability: recovered tree diverges from '
                    'the zxid-ordered replay at %s: replay says %s, '
                    'recovery holds %s'
                    % (k, 'absent' if st is None else repr(st[0]),
                       'absent' if fin is None else repr(fin)))
    return out
