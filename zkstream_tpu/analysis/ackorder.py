"""Checker: no ack byte before the fsync barrier AND the quorum gate.

The ack-order contract PR 12 establishes (io/sendplane.py ``barrier``;
server/replication.py ``CommitBarrier``): a server reply reaches the
transport only once BOTH halves of the leader's ack barrier have
cleared — the WAL's group fsync covering the txn, and (when the
member carries a quorum gate) the majority ack over it.  An ack path
that performs a raw transport write *before* taking a barrier it also
uses is exactly the bug quorum-commit exists to rule out: the client
sees an ack a leader death can still un-happen.

Mechanically: in any function body that calls BOTH a barrier-taking
method and a raw transport write, every raw write must come after the
first barrier call in source order.  Receivers are matched by the
project's naming conventions — barriers on ``barrier`` / ``wal`` /
``quorum`` / ``gate`` / ``_tx``-plane receivers (``gate_flush`` /
``sync_for_flush`` / ``flush_hard`` / quorum ``wait``), raw writes as
``.write(...)`` on ``writer`` / ``transport`` receivers — with
``# zkanalyze: ignore[ack-order] <reason>`` for the cases it
misreads.  Functions that only write (the plane's own sink callbacks,
admin words, election gossip) are out of scope: the contract binds
paths that themselves take a barrier.
"""

from __future__ import annotations

import ast
import re

from .core import Context, Finding, Module, walk_no_funcs

NAME = 'ack-order'

#: (attr, receiver-regex) pairs that count as taking the ack barrier.
BARRIER_CALLS = (
    ('gate_flush', re.compile(r'(?i)(barrier|wal|quorum|gate)')),
    ('sync_for_flush', re.compile(r'(?i)(barrier|wal|quorum|gate)')),
    ('flush_hard', re.compile(r'(?i)(_tx$|plane|cork)')),
    ('wait', re.compile(r'(?i)quorum')),
)

#: Raw transport writes: the bytes leave this process.
_WRITE_RE = re.compile(r'(?i)(writer|transport)$')


def _calls_in(fn: ast.AST):
    for node in walk_no_funcs(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            yield node


def check(module: Module, ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    funcs = [n for n in ast.walk(module.tree)
             if isinstance(n, (ast.FunctionDef,
                               ast.AsyncFunctionDef))]
    for fn in funcs:
        barriers: list[tuple[int, int, str]] = []
        writes: list[tuple[int, int, str]] = []
        for call in _calls_in(fn):
            recv = module.src(call.func.value)
            attr = call.func.attr
            for battr, brx in BARRIER_CALLS:
                if attr == battr and brx.search(recv):
                    barriers.append((call.lineno, call.col_offset,
                                     '%s.%s' % (recv, attr)))
                    break
            else:
                if attr == 'write' and _WRITE_RE.search(recv):
                    writes.append((call.lineno, call.col_offset,
                                   '%s.%s' % (recv, attr)))
        if not barriers or not writes:
            continue
        first_barrier = min(barriers)
        for line, col, name in sorted(writes):
            if (line, col) < (first_barrier[0], first_barrier[1]):
                findings.append(Finding(
                    module.path, line, NAME,
                    'raw transport write %s() precedes the ack '
                    'barrier %s() at line %d — no ack byte may reach '
                    'the transport before the fsync barrier AND the '
                    'quorum gate have cleared (io/sendplane.py '
                    'barrier contract; server/replication.py '
                    'CommitBarrier)' % (name, first_barrier[2],
                                        first_barrier[0])))
    return findings
