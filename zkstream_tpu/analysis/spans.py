"""Checker: every span started must settle on all paths.

The PR 7 abandoned-span class: an op that dies between
``trace.start()`` and the pending-table insert leaves a phantom
"open" span in the ring forever — the chaos campaigns assert
``TraceRing.open_spans()`` is empty at teardown, but only a schedule
that happens to hit the window catches it dynamically.  This checker
proves it structurally: a variable assigned from ``<ring>.start(...)``
(receiver naming ``trace``/``ring``/``span``) must, on every path out
of the function, either

- **settle** — ``var.finish(...)`` / ``var.settle(...)``, or
- **escape** — ownership handed off: stored into an attribute /
  container (``req.span = span``), passed to a call, returned,
  yielded, aliased, or captured by a nested function (the receiver
  settles it, as io/connection.py does for request spans).

Exception edges: an ``await`` (or bare ``raise``) reached while the
span is open and unprotected leaks it if the awaited future raises —
unless an enclosing ``try`` settles the span in a handler or
``finally`` (the client.py ``_start_op`` idiom).  A start whose
result is dropped outright is flagged too (``TraceRing.note`` is the
instant-settle API for that).

Loops are approximated (body runs zero or one time); ``with`` bodies
are inlined.  This is a project lint, not a prover: name heuristics
pick the spans, and ``# zkanalyze: ignore[span-leak] <reason>``
documents the escapes it cannot see.
"""

from __future__ import annotations

import ast
import re

from .core import Context, Finding, Module, walk_no_funcs

NAME = 'span-leak'

_RECV_RE = re.compile(r'(?i)(trace|ring|span)')
_SETTLE_ATTRS = ('finish', 'settle')

# abstract states of one tracked span variable
_OPEN, _SETTLED, _ESCAPED = 'open', 'settled', 'escaped'


def _is_start_call(node: ast.AST, module: Module) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == 'start'
            and bool(node.args or node.keywords)
            and _RECV_RE.search(module.src(node.func.value))
            is not None)


def _settles(stmt: ast.AST, var: str) -> bool:
    for node in ast.walk(stmt):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SETTLE_ATTRS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == var):
            return True
    return False


def _escapes(stmt: ast.AST, var: str) -> bool:
    """Ownership leaves this function: var stored somewhere, passed
    somewhere, returned/yielded, aliased, or closure-captured."""
    for node in ast.walk(stmt):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for inner in ast.walk(node):
                if isinstance(inner, ast.Name) and inner.id == var:
                    return True
            continue
        if isinstance(node, ast.Call):
            for arg in list(node.args) + [k.value
                                          for k in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id == var:
                    return True
        elif isinstance(node, ast.Assign):
            if (isinstance(node.value, ast.Name)
                    and node.value.id == var):
                return True     # alias or req.span = var
        elif isinstance(node, (ast.Return, ast.Yield,
                               ast.YieldFrom)):
            v = node.value
            if v is not None:
                for inner in ast.walk(v):
                    if (isinstance(inner, ast.Name)
                            and inner.id == var):
                        return True
        elif isinstance(node, (ast.List, ast.Tuple, ast.Set,
                               ast.Dict)):
            for inner in ast.iter_child_nodes(node):
                if isinstance(inner, ast.Name) and inner.id == var:
                    return True
    return False


def _has_raise_point(stmt: ast.AST, var: str) -> bool:
    """The statement can raise past an open span: an ``await``, or a
    call on anything other than the span itself (``conn.request(pkt)``
    raising between start and the pending-table insert IS the PR 7
    leak; ``span.xid = ...`` attribute stamps are safe)."""
    for node in walk_no_funcs(stmt):
        if isinstance(node, ast.Await):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            on_var = (isinstance(f, ast.Attribute)
                      and isinstance(f.value, ast.Name)
                      and f.value.id == var)
            if not on_var:
                return True
    return False


class _Tracker:
    """Walk the statements after one ``start()`` assign, tracking the
    span variable's state over every structural path."""

    def __init__(self, module: Module, var: str, start_line: int):
        self.module = module
        self.var = var
        self.start_line = start_line
        self.findings: list[Finding] = []
        #: one finding per raise-point LINE (not one per span: a
        #: suppression on the first raise point must not silently
        #: cover later ones added behind it)
        self._raise_lines: set[int] = set()

    def _flag(self, line: int, msg: str) -> None:
        self.findings.append(Finding(
            self.module.path, line, NAME,
            'span %r (started line %d) %s'
            % (self.var, self.start_line, msg)))

    def run_block(self, stmts: list[ast.stmt], state: str,
                  protected: bool) -> set[str]:
        """Returns the possible states at the end of the block;
        terminal paths (return/raise) report and vanish."""
        states = {state}
        for stmt in stmts:
            if _OPEN not in states:
                break           # settled/escaped on all live paths
            nxt: set[str] = set()
            for s in states:
                nxt |= self._step(stmt, s, protected)
            states = nxt
            if not states:
                break           # every path terminated
        return states

    def _step(self, stmt: ast.stmt, state: str,
              protected: bool) -> set[str]:
        var = self.var
        if state != _OPEN:
            return {state}
        if isinstance(stmt, ast.Return):
            if stmt.value is not None and _escapes(stmt, var):
                return set()
            self._flag(stmt.lineno,
                       'may return unsettled here — finish/settle '
                       'it (or hand it off) first')
            return set()
        if isinstance(stmt, ast.Raise):
            if not protected:
                self._flag(stmt.lineno,
                           'raised past while open — settle before '
                           'raising (status="abandoned"/"error")')
            return set()
        if isinstance(stmt, ast.If):
            out = self.run_block(stmt.body, state, protected)
            out |= self.run_block(stmt.orelse, state, protected)
            return out
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            once = self.run_block(stmt.body, state, protected)
            skip = self.run_block(stmt.orelse, state, protected)
            return once | skip
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            if _escapes(stmt, var) or _settles(stmt, var):
                return self._leaf(stmt, state, protected)
            return self.run_block(stmt.body, state, protected)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, state, protected)
        return self._leaf(stmt, state, protected)

    def _leaf(self, stmt: ast.stmt, state: str,
              protected: bool) -> set[str]:
        if _escapes(stmt, self.var):
            return {_ESCAPED}
        if _settles(stmt, self.var):
            return {_SETTLED}
        if (not protected and stmt.lineno not in self._raise_lines
                and _has_raise_point(stmt, self.var)):
            self._raise_lines.add(stmt.lineno)
            self._flag(stmt.lineno,
                       'leaks if this call/await raises — settle it '
                       'in a finally/except (the _start_op idiom), '
                       'or hand it off first')
        return {state}

    def _try(self, stmt: ast.Try, state: str,
             protected: bool) -> set[str]:
        var = self.var
        handlers_settle = bool(stmt.handlers) and all(
            any(_settles(s, var) or _escapes(s, var)
                for s in h.body)
            for h in stmt.handlers)
        final_settles = any(_settles(s, var) or _escapes(s, var)
                            for s in stmt.finalbody)
        body_protected = (protected or handlers_settle
                          or final_settles)
        out_body = self.run_block(stmt.body, state, body_protected)
        out = set()
        for s in out_body:      # orelse continues the success path
            out |= self.run_block(stmt.orelse, s, protected)
        for h in stmt.handlers:
            out |= self.run_block(h.body, state, protected)
        if stmt.finalbody:
            joined = set()
            for s in out or {state}:
                joined |= self.run_block(stmt.finalbody, s,
                                         protected)
            out = joined
        return out


def _function_blocks(fn: ast.AST):
    """Yield (block, idx) pairs positioning every statement of ``fn``
    without descending into nested functions."""
    stack = [fn.body]
    while stack:
        block = stack.pop()
        for i, stmt in enumerate(block):
            yield block, i, stmt
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                continue
            for field in ('body', 'orelse', 'finalbody'):
                sub = getattr(stmt, field, None)
                if sub:
                    stack.append(sub)
            for h in getattr(stmt, 'handlers', ()) or ():
                stack.append(h.body)


def _spine(fn: ast.AST, target_block: list) -> list[list[ast.stmt]]:
    """Continuation blocks from the target's block outward to the
    function body (each sliced after the enclosing statement by the
    caller)."""
    # Path reconstruction: walk down from fn.body looking for the
    # block object identity.
    def descend(block, acc):
        if block is target_block:
            return acc + [block]
        for stmt in block:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                continue
            subs = [getattr(stmt, f, None)
                    for f in ('body', 'orelse', 'finalbody')]
            subs += [h.body for h in
                     getattr(stmt, 'handlers', ()) or ()]
            for sub in subs:
                if not sub:
                    continue
                found = descend(sub, acc + [(block, stmt)])
                if found is not None:
                    return found
        return None
    return descend(fn.body, [])


def check(module: Module, ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    funcs = [n for n in ast.walk(module.tree)
             if isinstance(n, (ast.FunctionDef,
                               ast.AsyncFunctionDef))]
    for fn in funcs:
        for block, i, stmt in list(_function_blocks(fn)):
            start_call = None
            var = None
            if (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and _is_start_call(stmt.value, module)):
                start_call = stmt.value
                var = stmt.targets[0].id
            elif (isinstance(stmt, ast.Expr)
                    and _is_start_call(stmt.value, module)):
                findings.append(Finding(
                    module.path, stmt.lineno, NAME,
                    'span started and dropped — nothing can settle '
                    'it (use TraceRing.note() for instant events)'))
                continue
            if start_call is None:
                continue
            tracker = _Tracker(module, var, stmt.lineno)
            path = _spine(fn, block)
            if path is None:
                continue
            # innermost block first: statements after the start.
            # A start inside a try body whose handlers/finally
            # settle the var is exception-protected from the top.
            protected = False
            if len(path) > 1:
                container = path[-2][1]
                if (isinstance(container, ast.Try)
                        and container.body is block):
                    protected = (
                        any(_settles(s, var) or _escapes(s, var)
                            for s in container.finalbody)
                        or (bool(container.handlers) and all(
                            any(_settles(s, var) or _escapes(s, var)
                                for s in h.body)
                            for h in container.handlers)))
            states = tracker.run_block(block[i + 1:], _OPEN,
                                       protected)
            # then each enclosing block's continuation after the
            # statement that contained us — flowing through a Try
            # container's orelse/finalbody first, so the canonical
            # settle-in-finally idiom resolves to SETTLED
            cur_block = block
            for enc_block, enc_stmt in reversed(path[:-1]):
                if _OPEN not in states:
                    break
                if isinstance(enc_stmt, ast.Try):
                    tails = []
                    if cur_block is enc_stmt.body:
                        tails = [enc_stmt.orelse, enc_stmt.finalbody]
                    elif cur_block is not enc_stmt.finalbody:
                        tails = [enc_stmt.finalbody]
                    for tail in tails:
                        nxt = set()
                        for s in states:
                            nxt |= tracker.run_block(tail, s, False)
                        states = nxt
                j = enc_block.index(enc_stmt)
                nxt = set()
                for s in states:
                    nxt |= tracker.run_block(enc_block[j + 1:], s,
                                             False)
                states = nxt
                cur_block = enc_block
            if _OPEN in states:
                end = getattr(fn.body[-1], 'end_lineno',
                              fn.body[-1].lineno)
                tracker._flag(end, 'can reach the end of %s() '
                              'unsettled' % (fn.name,))
            findings.extend(tracker.findings)
    return findings
