"""zkanalyze core: findings, suppressions, module loading, driver.

The style tier (tools/lint.py) asks "is this file tidy"; this tier
asks "does this file honor the concurrency and tracing contracts the
planes established" — the rules PRs 3/5/7/9 each re-derived by hand
after a violation shipped.  One checker per contract lives in a
sibling module; this module owns everything they share: the
:class:`Finding` record, the suppression syntax, source loading, and
the :func:`analyze_paths` driver `make analyze`, the ``analyze`` CLI
subcommand and tests/test_analyze.py all call.

Suppression syntax (every form REQUIRES a reason string — a bare
annotation is itself a finding):

- ``# zkanalyze: off-loop <reason>`` — same line (or the line above):
  this blocking call is known to run off the event loop (executor
  thunk, documented-blocking sync path).  Sugar for
  ``ignore[loop-blocking]``.
- ``# zkanalyze: ignore[<checker>] <reason>`` — same line (or the
  line above): suppress one checker's finding here.
- ``# zkanalyze: skip-file[<checker>] <reason>`` — anywhere in the
  file: suppress one checker for the whole file.

``--list-suppressions`` prints every annotation with its reason and
whether any finding actually hit it, so stale escapes are visible.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path

#: Bump when the JSON emission shape changes; consumers key on it.
ANALYZE_SCHEMA = 1

#: Checker registry order (stable report order).  'suppression' is
#: the core's own gate on malformed/reason-less annotations and
#: 'parse' marks unreadable/unparseable files; neither is a valid
#: annotation target.
CHECKER_NAMES = ('loop-blocking', 'await-under-lock', 'span-leak',
                 'fault-order', 'ack-order', 'drift', 'suppression',
                 'parse')
_UNSUPPRESSIBLE = ('suppression', 'parse')

_SUPPRESS_RE = re.compile(
    r'#\s*zkanalyze:\s*(?P<form>off-loop'
    r'|ignore\[(?P<ign>[a-z-]+)\]'
    r'|skip-file\[(?P<skp>[a-z-]+)\])'
    r'[ \t]*(?P<reason>.*)$')


@dataclasses.dataclass
class Finding:
    """One contract violation at ``path:line``."""

    path: str
    line: int
    checker: str
    message: str

    def format(self) -> str:
        return '%s:%d: [%s] %s' % (self.path, self.line,
                                   self.checker, self.message)

    def to_dict(self) -> dict:
        return {'file': self.path, 'line': self.line,
                'checker': self.checker, 'message': self.message}


@dataclasses.dataclass
class Suppression:
    """One parsed ``# zkanalyze:`` annotation."""

    path: str
    line: int
    checker: str
    reason: str
    file_level: bool
    used: bool = False

    def format(self) -> str:
        scope = 'file' if self.file_level else 'line'
        state = 'used' if self.used else 'UNUSED'
        return '%s:%d: [%s] %s (%s, %s)' % (
            self.path, self.line, self.checker,
            self.reason or '<no reason>', scope, state)

    def to_dict(self) -> dict:
        return {'file': self.path, 'line': self.line,
                'checker': self.checker, 'reason': self.reason,
                'file_level': self.file_level, 'used': self.used}


class Module:
    """One parsed source file plus its suppression table."""

    def __init__(self, path: str, text: str, tree: ast.AST):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree = tree
        self.suppressions: list[Suppression] = []
        self.bad_suppressions: list[Finding] = []
        self._parse_suppressions()

    def _comments(self):
        """(line, text) for every real comment token — docstrings
        that merely *mention* the annotation syntax stay inert."""
        import io
        import tokenize
        try:
            toks = tokenize.generate_tokens(
                io.StringIO(self.text).readline)
            return [(t.start[0], t.string) for t in toks
                    if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError,
                SyntaxError):
            return [(i, line) for i, line in
                    enumerate(self.lines, 1) if '#' in line]

    def _parse_suppressions(self) -> None:
        for i, line in self._comments():
            # the annotation marker is the tool name followed by a
            # colon; prose comments may mention the bare name freely
            if 'zkanalyze' + ':' not in line:
                continue
            m = _SUPPRESS_RE.search(line)
            if m is None:
                self.bad_suppressions.append(Finding(
                    self.path, i, 'suppression',
                    'unparseable zkanalyze annotation (forms: '
                    'off-loop / ignore[checker] / '
                    'skip-file[checker], each with a reason)'))
                continue
            form = m.group('form')
            if form == 'off-loop':
                checker, file_level = 'loop-blocking', False
            elif form.startswith('ignore'):
                checker, file_level = m.group('ign'), False
            else:
                checker, file_level = m.group('skp'), True
            reason = m.group('reason').strip()
            suppressible = [c for c in CHECKER_NAMES
                            if c not in _UNSUPPRESSIBLE]
            if checker not in suppressible:
                # the annotation gate and parse failures must not be
                # annotatable away
                self.bad_suppressions.append(Finding(
                    self.path, i, 'suppression',
                    'unknown checker %r (suppressible: %s)'
                    % (checker, ', '.join(suppressible))))
                continue
            if not reason:
                self.bad_suppressions.append(Finding(
                    self.path, i, 'suppression',
                    '%s suppression carries no reason' % (checker,)))
                continue
            self.suppressions.append(Suppression(
                self.path, i, checker, reason, file_level))

    def file_suppression(self, checker: str) -> Suppression | None:
        for s in self.suppressions:
            if s.file_level and s.checker == checker:
                return s
        return None

    def line_suppression(self, checker: str,
                         line: int) -> Suppression | None:
        """A line suppression covers its own line and the one below
        (annotation above a long statement)."""
        for s in self.suppressions:
            if (not s.file_level and s.checker == checker
                    and s.line in (line, line - 1)):
                return s
        return None

    def src(self, node: ast.AST) -> str:
        """Source text of a node (for receiver-name heuristics)."""
        try:
            return ast.unparse(node)
        except Exception:
            return ''


class Context:
    """Shared cross-module state (the drift checker aggregates here;
    the driver owns the lifecycle)."""

    def __init__(self, readme_text: str | None):
        self.readme_text = readme_text
        self.modules: dict[str, Module] = {}
        #: module-level ``NAME = 'str'`` constants, for resolving
        #: metric names registered through imported constants
        self.constants: dict[str, str] = {}
        #: drift-checker aggregation: see analysis/drift.py
        self.env_reads: list[tuple[str, str, int]] = []
        self.metric_regs: list[tuple[str, str, int]] = []
        self.label_uses: dict[str, dict[frozenset,
                                        tuple[str, int]]] = {}


def load_module(path: Path) -> Module | Finding:
    try:
        text = path.read_text()
    except OSError as e:
        return Finding(str(path), 0, 'parse',
                       'cannot read: %s' % (e,))
    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as e:
        return Finding(str(path), e.lineno or 0, 'parse',
                       'syntax error: %s' % (e.msg,))
    return Module(str(path), text, tree)


def iter_py_files(paths: list[str]) -> list[Path]:
    out: list[Path] = []
    for arg in paths:
        p = Path(arg)
        if p.is_dir():
            out.extend(sorted(f for f in p.rglob('*.py')
                              if '__pycache__' not in f.parts))
        else:
            out.append(p)
    return out


def find_readme(paths: list[str]) -> Path | None:
    """Locate the repo README by walking up from the first target —
    the knob/metric inventory the drift checker diffs against."""
    if not paths:
        return None
    start = Path(paths[0]).resolve()
    if start.is_file():
        start = start.parent
    for d in (start, *start.parents):
        cand = d / 'README.md'
        if cand.is_file():
            return cand
    return None


@dataclasses.dataclass
class Report:
    """One analysis run: findings (suppressions already applied),
    every parsed suppression, and the file count."""

    findings: list[Finding]
    suppressions: list[Suppression]
    nfiles: int

    def to_dict(self) -> dict:
        return {
            'schema': ANALYZE_SCHEMA,
            'files': self.nfiles,
            'findings': [f.to_dict() for f in self.findings],
            'suppressions': [s.to_dict()
                             for s in self.suppressions],
        }

    def to_json(self, indent: int | None = None) -> str:
        return json.dumps(self.to_dict(), indent=indent)


def _checkers():
    # imported here, not at module top: the checker modules import
    # this one for Finding/Module
    from . import ackorder, drift, faultorder, locks, loopblock, spans
    return ((loopblock.NAME, loopblock.check),
            (locks.NAME, locks.check),
            (spans.NAME, spans.check),
            (faultorder.NAME, faultorder.check),
            (ackorder.NAME, ackorder.check),
            (drift.NAME, drift.check))


def analyze_paths(paths: list[str],
                  readme_text: str | None = None,
                  readme_path: str | None = None) -> Report:
    """Run every checker over ``paths`` (files or directories).

    README resolution for the drift checker: explicit ``readme_text``
    wins, then ``readme_path``, then a walk up from the first target;
    with none found the README diff is skipped (the other checkers
    still run)."""
    from . import drift

    if readme_text is None:
        rp = Path(readme_path) if readme_path else find_readme(paths)
        if rp is not None and rp.is_file():
            readme_text = rp.read_text()
    ctx = Context(readme_text)
    files = iter_py_files(paths)
    modules: list[Module] = []
    findings: list[Finding] = []
    for f in files:
        loaded = load_module(f)
        if isinstance(loaded, Finding):
            findings.append(loaded)
            continue
        modules.append(loaded)
        ctx.modules[loaded.path] = loaded
    for m in modules:        # constants first: cross-module refs
        _collect_constants(m, ctx)
    checkers = _checkers()
    for m in modules:
        findings.extend(m.bad_suppressions)
        for name, check in checkers:
            fsup = m.file_suppression(name)
            if fsup is not None:
                fsup.used = True
                continue
            for f in check(m, ctx):
                sup = m.line_suppression(f.checker, f.line)
                if sup is not None:
                    sup.used = True
                    continue
                findings.append(f)
    for f in drift.finalize(ctx):
        m = ctx.modules.get(f.path)
        if m is not None:
            fsup = m.file_suppression(f.checker)
            if fsup is not None:
                fsup.used = True
                continue
            sup = m.line_suppression(f.checker, f.line)
            if sup is not None:
                sup.used = True
                continue
        findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    sups = [s for m in modules for s in m.suppressions]
    return Report(findings, sups, len(files))


def _collect_constants(module: Module, ctx: Context) -> None:
    for node in module.tree.body:
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    ctx.constants.setdefault(t.id, node.value.value)


def dotted_name(node: ast.AST) -> str | None:
    """Flatten ``a.b.c`` to ``'a.b.c'`` (None when the chain has a
    non-Name root: calls, subscripts)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return '.'.join(reversed(parts))


def import_aliases(tree: ast.AST) -> dict[str, str]:
    """Map local names to dotted origins: ``import subprocess as sp``
    -> ``sp: subprocess``; ``from time import sleep`` ->
    ``sleep: time.sleep``."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split('.')[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name != '*':
                    out[a.asname or a.name] = (
                        '%s.%s' % (node.module, a.name))
    return out


def resolve_call(node: ast.Call,
                 aliases: dict[str, str]) -> str | None:
    """Resolve a call's target to a dotted name through the module's
    import aliases (``sp.run`` -> ``subprocess.run``)."""
    name = dotted_name(node.func)
    if name is None:
        return None
    head, _, rest = name.partition('.')
    head = aliases.get(head, head)
    return '%s.%s' % (head, rest) if rest else head


def walk_no_funcs(node: ast.AST):
    """``ast.walk`` that does not descend into nested function or
    lambda bodies (their code runs at some other time, in some other
    context — not at this point of the enclosing function)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


class FuncStackVisitor(ast.NodeVisitor):
    """Visitor tracking the enclosing function chain in ``stack``
    (FunctionDef / AsyncFunctionDef / Lambda nodes, outermost
    first)."""

    def __init__(self):
        self.stack: list[ast.AST] = []

    def _push(self, node):
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _push
    visit_AsyncFunctionDef = _push
    visit_Lambda = _push
