"""Checker: fault injection happens BEFORE the cork boundary.

The rule PRs 4/6/9 each re-derived by hand: the seeded fault
injector's tx hooks (``faults.tx`` / ``faults.server_tx``) are a
*per-frame* boundary — they may truncate a frame, schedule a reset,
or take over delivery entirely — so they must see every frame before
it enters a :class:`SendPlane` cork (``.send`` / ``.send_flush``).  A
frame corked first and faulted later can reorder ahead of the
injected delivery, and the schedule stops reproducing by seed
(io/sendplane.py "Ordering contract"; server/server.py
``_write_bytes``; server/watchtable.py ``_enqueue``).

Mechanically: in any function body that calls BOTH a fault hook and a
send-plane cork entry point, every cork call must come after the
first fault-hook call in source order.  Receivers are matched by
name (``faults`` / ``fi`` / ``injector`` vs ``_tx`` / ``plane`` /
``cork``) — this is a project lint over the project's own naming
conventions, with ``# zkanalyze: ignore[fault-order] <reason>`` for
the cases it misreads.
"""

from __future__ import annotations

import ast
import re

from .core import Context, Finding, Module, walk_no_funcs

NAME = 'fault-order'

#: FaultInjector per-frame / per-event hook methods (io/faults.py).
#: ``server_rx`` is the ingress drain's per-chunk boundary: it must
#: run before any decode AND before any cork a handler might take
#: (the receive-side mirror of the tx rule).
FAULT_ATTRS = ('tx', 'rx', 'server_tx', 'server_rx', 'accept_refuse',
               'drop_push', 'fsync_fault', 'ingest_reset',
               'ingest_cut', 'before_connect',
               'crash_window_before_fsync')
_FAULT_RECV_RE = re.compile(r'(?i)(fault|injector|(^|\.)fi$)')

#: SendPlane cork entry points (io/sendplane.py).
CORK_ATTRS = ('send', 'send_flush')
_CORK_RECV_RE = re.compile(r'(?i)(_tx$|(^|[._])tx$|plane|cork)')


def _calls_in(fn: ast.AST):
    for node in walk_no_funcs(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            yield node


def check(module: Module, ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    funcs = [n for n in ast.walk(module.tree)
             if isinstance(n, (ast.FunctionDef,
                               ast.AsyncFunctionDef))]
    for fn in funcs:
        faults: list[tuple[int, int, str]] = []
        corks: list[tuple[int, int, str]] = []
        for call in _calls_in(fn):
            recv = module.src(call.func.value)
            attr = call.func.attr
            if (attr in FAULT_ATTRS
                    and _FAULT_RECV_RE.search(recv)):
                faults.append((call.lineno, call.col_offset,
                               '%s.%s' % (recv, attr)))
            elif (attr in CORK_ATTRS
                    and _CORK_RECV_RE.search(recv)):
                corks.append((call.lineno, call.col_offset,
                              '%s.%s' % (recv, attr)))
        if not faults or not corks:
            continue
        first_fault = min(faults)
        for line, col, name in sorted(corks):
            if (line, col) < (first_fault[0], first_fault[1]):
                findings.append(Finding(
                    module.path, line, NAME,
                    'cork boundary %s() precedes the fault hook '
                    '%s() at line %d — injection must screen every '
                    'frame before it corks, or the injected '
                    'delivery reorders (io/sendplane.py ordering '
                    'contract)' % (name, first_fault[2],
                                   first_fault[0])))
    return findings
