"""zkanalyze: the repo's semantic static-analysis tier.

tools/lint.py answers "is this file tidy"; this package answers
"does this code honor the plane contracts" — one AST checker per
rule the PR trail established the hard way:

- ``loop-blocking`` — blocking calls (fsync/sleep/subprocess/sync
  dials) must not run on the event loop (the PR 5 rule);
- ``await-under-lock`` — no suspension while holding a thread lock,
  no shared-attribute read-modify-write across an ``await`` (PR 3);
- ``span-leak`` — every ``TraceRing.start`` settles or escapes on
  all paths, exception edges included (PR 7);
- ``fault-order`` — fault-injection hooks screen frames BEFORE the
  send-plane cork boundary (PRs 4/6/9);
- ``drift`` — every ``ZKSTREAM_*`` knob and registered metric is in
  the README inventory; label-key sets never fork.

Entry points: ``make analyze`` / ``python tools/zkanalyze.py``
(human report), ``python -m zkstream_tpu analyze`` (JSON for
harnesses), and :func:`analyze_paths` for tests.  Suppressions
(``# zkanalyze: off-loop/ignore[..]/skip-file[..] <reason>``) are
specified in analysis/core.py and printed by
``--list-suppressions``.
"""

from .core import (ANALYZE_SCHEMA, CHECKER_NAMES, Context, Finding,
                   Module, Report, Suppression, analyze_paths,
                   find_readme)

__all__ = ['ANALYZE_SCHEMA', 'CHECKER_NAMES', 'Context', 'Finding',
           'Module', 'Report', 'Suppression', 'analyze_paths',
           'find_readme']
