"""The public client API.

``Client`` is the user-facing facade over the pool/connection/session
machinery (reference: lib/client.js:31-601): an event emitter
(``session``, ``connect``, ``disconnect``, ``expire``, ``failed``,
``close``) plus awaitable znode operations.  Where the reference's ops
take callbacks, these are coroutines; semantics are otherwise the same,
including ``create_with_empty_parents`` parent tolerance and the
deferred ``connect`` emission (the event only fires once the connection
is actually usable for requests).

Usage::

    client = Client(address='127.0.0.1', port=2181)
    client.start()
    await client.wait_connected()
    await client.create('/x', b'hello')
    data, stat = await client.get('/x')
    w = client.watcher('/x')
    w.on('dataChanged', lambda data, stat: ...)
    await client.close()
"""

from __future__ import annotations

import asyncio
import time

from .io.backoff import BackoffPolicy
from .io.connection import Backend, ZKConnection
from .io.pool import (
    DEFAULT_CONNECT_POLICY,
    DEFAULT_DECOHERENCE_INTERVAL,
    DEFAULT_POLICY,
    ConnectionPool,
    ReadPlane,
    Resolver,
    read_distribution_default,
    read_subset_default,
)
from .io.cache import CachePlane, cache_roots_default
from .io.session import ZKSession
from .io.watcher import ZKPersistentWatcher, ZKWatcher
from .io.overload import overload_enabled
from .protocol.consts import MAX_PACKET, CreateFlag
from .protocol.errors import ZKDeadlineError, ZKNotConnectedError, \
    ZKThrottledError
from .protocol.records import OPEN_ACL_UNSAFE, Stat
from .utils.aio import ambient_loop
from .utils.fsm import FSM, bind_transition_metrics
from .utils.logging import Logger
from .utils.metrics import Collector
from .utils.trace import TraceRing

METRIC_ZK_EVENT_COUNTER = 'zookeeper_events'
METRIC_ZK_DEGRADED_GAUGE = 'zookeeper_degraded'
METRIC_ZK_OP_LATENCY = 'zookeeper_op_latency_ms'

#: Default session timeout, ms (reference: lib/client.js:80-83).
DEFAULT_SESSION_TIMEOUT = 30000

#: Default per-request deadline, ms.  Every znode op either completes
#: or raises a typed :class:`ZKDeadlineError` within this budget —
#: an op must never hang silently on a dead connection.  Pass
#: ``op_timeout=None`` (or ``deadline=None`` per op) for the old
#: unbounded behavior.
DEFAULT_OP_TIMEOUT = 30000

#: Sentinel: "no per-op override, use the client default".
_USE_DEFAULT = object()


class Client(FSM):
    def __init__(self, address: str | None = None, port: int = 2181,
                 servers: list[tuple[str, int] | dict] | None = None,
                 session_timeout: int = DEFAULT_SESSION_TIMEOUT,
                 collector: Collector | None = None,
                 connect_policy: BackoffPolicy = DEFAULT_CONNECT_POLICY,
                 default_policy: BackoffPolicy = DEFAULT_POLICY,
                 decoherence_interval: int = DEFAULT_DECOHERENCE_INTERVAL,
                 shuffle_backends: bool = True,
                 seed: int | None = None,
                 log: Logger | None = None,
                 ingest=None,
                 use_native_codec: bool | None = None,
                 on_fatal=None,
                 max_spares: int = 2,
                 op_timeout: int | None = DEFAULT_OP_TIMEOUT,
                 faults=None,
                 trace: TraceRing | None = None,
                 trace_capacity: int = 256,
                 cork: bool | None = None,
                 transport: str | None = None,
                 flush_cap: int | None = None,
                 read_distribution: bool | None = None,
                 read_subset: int | None = None,
                 resolver: Resolver | None = None,
                 max_frame: int | None = None,
                 cache: bool | str | list[str] | None = None):
        if servers is None:
            assert address is not None, 'address or servers[] required'
            backends = [Backend(address, port)]
        else:
            # Accept both (address, port) pairs and {'address', 'port'}
            # dicts — the reference's servers[] takes address/port
            # objects (reference: lib/client.js:63-76).
            backends = []
            for s in servers:
                if isinstance(s, dict):
                    backends.append(Backend(s['address'],
                                            int(s.get('port', port))))
                else:
                    a, p = s
                    backends.append(Backend(a, int(p)))

        # Injectable logger, like the reference's opts.log (reference:
        # lib/client.js:34-45); components derive context-accreting
        # children from it.
        self.log = Logger(log).child(component='ZKClient')

        #: Optional shared FleetIngest (io/ingest.py): when set, this
        #: client's connections drain through the batched TPU decode
        #: pipeline instead of per-socket scalar codecs.  Many clients
        #: may share one ingest — that is the point.
        self.ingest = ingest
        #: Frame-scanner selection for this client's connections:
        #: None = auto (native if built), True = force C++, False =
        #: force pure Python (benchmarks, A/B tests).
        self.use_native_codec = use_native_codec
        #: Inbound frame cap for this client's connections (README
        #: "Overload plane"): a reply whose length prefix exceeds it
        #: raises :class:`ZKFrameTooLargeError` before any buffering.
        #: None = env resolution (``ZKSTREAM_MAX_FRAME`` / the wire
        #: default); with ``ZKSTREAM_NO_OVERLOAD=1`` the cap pins to
        #: the legacy MAX_PACKET so byte streams stay bit-identical.
        self.max_frame = (max_frame if max_frame is not None
                          else (None if overload_enabled()
                                else MAX_PACKET))
        #: Outbound write coalescing for this client's connections
        #: (io/sendplane.py): None = process default (on unless
        #: ZKSTREAM_NO_CORK=1), True/False force a path (benchmarks,
        #: A/B tests).
        self.cork = cork
        #: Early-flush cap override for this client's send planes
        #: (None = ZKSTREAM_FLUSH_CAP / the 256 KiB default).
        self.flush_cap = flush_cap
        #: Optional crash-on-bug policy override: called with the
        #: exception after session teardown instead of the loud default
        #: (loop exception handler).  See ZKSession.fatal_error.
        self.on_fatal = on_fatal

        #: Optional FaultInjector (io/faults.py): threaded to every
        #: connection this client dials; None in production.
        self.faults = faults
        #: Per-request deadline, ms (None = unbounded).  Ops exceeding
        #: it raise :class:`ZKDeadlineError` instead of hanging.
        self.op_timeout = op_timeout

        self.collector = collector if collector is not None else Collector()
        #: Batched-syscall transport tier for this client's
        #: connections (io/transport.py): None when the resolved
        #: backend is 'asyncio' (the legacy per-plane writes).
        #: ``transport=`` forces a tier ('uring'|'mmsg'|'asyncio');
        #: None = the ZKSTREAM_TRANSPORT / capability-probe default.
        from .io.transport import make_tier
        self.transport_tier = make_tier(transport,
                                        collector=self.collector,
                                        plane='client')
        self.collector.counter(METRIC_ZK_EVENT_COUNTER,
            'Total number of zookeeper events')
        #: Per-op latency distribution, labelled by opcode; recorded by
        #: _await_op on every completion path (ok, error, deadline).
        self._op_latency = self.collector.histogram(
            METRIC_ZK_OP_LATENCY,
            'Client op round-trip latency, milliseconds, by opcode')
        #: Bounded in-memory span ring (utils/trace.py): one span per
        #: op, xid-correlated through the connection and stamped with
        #: the reply zxid.  Injectable so chaos campaigns and tests can
        #: dump it on failure.
        self.trace = trace if trace is not None else TraceRing(
            trace_capacity)
        #: Optional per-op completion hook: called with the settled
        #: Span after EVERY completion path (reply, typed error,
        #: deadline), in completion order.  The chaos campaigns'
        #: history engine (io/invariants.py) subscribes here so the
        #: recorded history cannot diverge from what the client
        #: actually observed; None in production.
        self.on_op = None

        self.session_timeout = session_timeout
        self.session: ZKSession | None = None
        self.old_session: ZKSession | None = None
        self._retry_policy = default_policy
        self._seed = seed

        self.pool = ConnectionPool(
            self, backends,
            connect_policy=connect_policy,
            default_policy=default_policy,
            decoherence_interval=decoherence_interval,
            shuffle=shuffle_backends, seed=seed,
            max_spares=max_spares)

        #: Client-side read scale-out (README "Read plane"): with
        #: more than one backend, get/exists/getACL/list fan out over
        #: per-backend read sessions while writes, watches and sync
        #: stay on the primary session — zxid-gated so the session
        #: view never goes backwards (io/pool.py ReadPlane).  None =
        #: process default (``ZKSTREAM_READ_DISTRIBUTION=1`` enables).
        enabled_reads = (read_distribution_default()
                         if read_distribution is None
                         else read_distribution)
        #: Live member list (io/pool.py Resolver, README "Dynamic
        #: membership"): ``update_backends()`` adopts a post-reconfig
        #: fleet; the read plane rebalances its dialed subset on the
        #: change while the primary session drains in place.
        self.resolver = (resolver if resolver is not None
                         else Resolver(backends))
        self.resolver.on('changed',
                         lambda bs: self.pool.set_backends(bs))
        #: Read-plane subset cap: dial at most K read sessions from
        #: the live config (None = one per backend; process default
        #: via ``ZKSTREAM_READ_SUBSET``).
        subset = (read_subset_default() if read_subset is None
                  else (read_subset if read_subset > 0 else None))
        self._read_plane = (ReadPlane(self, backends, subset=subset,
                                      resolver=self.resolver)
                            if enabled_reads and len(backends) > 1
                            else None)
        #: The newest member zxid any DISTRIBUTED read has shown this
        #: client (the primary session's own floor lives in
        #: ``session.last_zxid``); :meth:`last_seen_zxid` is the max.
        self._read_floor = 0
        #: Watch-backed client cache (io/cache.py, README "Client
        #: cache plane"): ``cache=`` names the subtree root(s) to
        #: subscribe (True = '/'); None = env resolution
        #: (``ZKSTREAM_CACHE``); ``ZKSTREAM_NO_CACHE=1`` kills it.
        #: The ctor beats the env, like every other knob ladder.
        if cache is None:
            roots = cache_roots_default()
        elif cache is True:
            roots = ['/']
        elif cache is False:
            roots = None
        elif isinstance(cache, str):
            roots = [cache]
        else:
            roots = list(cache)
        self.cache = (CachePlane(self, roots,
                                 collector=self.collector)
                      if roots else None)
        self.pool.on('stateChanged', self._on_pool_state_changed)
        # Degraded-mode surface: re-emit the pool's circuit-breaker
        # edges on the client, count them, and expose the current state
        # as a pull gauge (1 = all backends failing, parked in monitor
        # mode; 0 = healthy).
        self.pool.on('degraded', lambda: self._emit_tracked('degraded'))
        self.pool.on('recovered',
                     lambda: self._emit_tracked('recovered'))
        try:
            self.collector.gauge(
                METRIC_ZK_DEGRADED_GAUGE,
                lambda: 1.0 if self.pool.degraded else 0.0,
                'Client degraded mode (1 = all backends failing)')
        except ValueError:
            # Shared collector across clients: the first registrant's
            # pool owns the series.
            pass

        # FSM observability (utils/fsm.py): transition counters + a
        # live current-state gauge for the client machine and the pool;
        # the session and every connection bind themselves.
        self.bind_fsm_metrics(self.collector, 'ZKClient')
        bind_transition_metrics(self.pool, self.collector,
                                'ConnectionPool')

        self._started = False
        super().__init__('normal')

    # -- lifecycle (reference: lib/client.js:127-215) --

    def state_normal(self, S) -> None:
        self._new_session()
        S.on(self, 'closeAsserted', lambda: S.goto_state('closing'))

    def state_closing(self, S) -> None:
        """Close the session first — its closing state drains the
        connection and sends CLOSE_SESSION, which is what deletes
        ephemerals immediately instead of at expiry — then stop the
        pool before it can redial (reference: lib/client.js:135-177
        shuts session/set/resolver down concurrently and relies on the
        session winning the race; sequencing makes it deterministic)."""

        def finish():
            self.pool.stop()
            S.goto_state('closed')

        if self.session.is_in_state('closed') or \
           self.session.is_in_state('expired'):
            finish()
            return

        def on_session_state(st):
            if st in ('closed', 'expired'):
                finish()
        S.on(self.session, 'stateChanged', on_session_state)
        self.session.close()

    def state_closed(self, S) -> None:
        self.emit('close')

    def start(self) -> None:
        """Begin connecting.  Separate from __init__ so the caller
        controls which running event loop the client binds to (the
        reference starts its resolver in the constructor)."""
        assert not self._started, 'client already started'
        self._started = True
        self.pool.start()
        if self._read_plane is not None:
            self._read_plane.start()
        if self.cache is not None:
            self.cache.start()

    async def close(self) -> None:
        """Close the session cleanly and stop the pool."""
        if self.is_in_state('closed'):
            return
        loop = ambient_loop()
        fut: asyncio.Future = loop.create_future()
        self.once('close', lambda: fut.done() or fut.set_result(None))
        self.emit('closeAsserted')
        await fut
        if self.cache is not None:
            self.cache.close()
        if self._read_plane is not None:
            await self._read_plane.close()
        if self.transport_tier is not None:
            # release the tier's ring fd with the client instead of
            # waiting on cyclic GC (the plane/entry closures keep the
            # tier in a cycle); a reused client lazily re-creates it
            self.transport_tier.close()

    def update_backends(self, backends) -> bool:
        """Adopt a new live member list (README "Dynamic
        membership"): Backend objects or (address, port) pairs.
        The read plane rebalances its dialed subset immediately; the
        primary session stays where it is until its connection dies,
        then redials against the updated list.  Returns True when the
        membership actually changed."""
        return self.resolver.update(backends)

    # -- session management (reference: lib/client.js:187-273) --

    def _new_session(self) -> None:
        if not self.is_in_state('normal'):
            return
        s = ZKSession(self.session_timeout, self.collector, log=self.log,
                      retry_policy=self._retry_policy, seed=self._seed,
                      trace=self.trace)
        prev = self.session
        carried = max(
            (prev.last_zxid if prev is not None else 0),
            (prev.gate_floor if prev is not None else 0),
            self._read_floor)
        if carried > s.gate_floor:
            # client-level floor carry: a REPLACEMENT session (the old
            # one expired) must not read below what this client has
            # already observed — on ANY of its connections, the read
            # plane's included.  The handshake presents the floor as
            # lastZxidSeen, seeding the server-side zxid read gate
            # (server/server.py ReadGate); it rides gate_floor, not
            # last_zxid, so SET_WATCHES relZxid semantics are
            # untouched.
            s.gate_floor = carried
        s.fatal_handler = self.on_fatal
        self.session = s

        def on_fatal(exc):
            # Crash-on-bug escalation from the session's self-checks
            # (missed wakeup, unmatched notification): surface as the
            # terminal 'failed' event; the session teardown follows as
            # 'expire' (reference crashes the process outright,
            # lib/zk-session.js:916-919).
            self._event_track('failed')
            self.emit('failed', exc)
        s.on('fatalError', on_fatal)

        def initial_handler(st):
            if st == 'attached':
                s.remove_listener('stateChanged', initial_handler)
                s.on('stateChanged', final_handler)
                self._emit_after_connected('session')
                self._emit_after_connected('connect')

        def final_handler(st):
            if st == 'attached':
                self._emit_after_connected('connect')
            elif st == 'detached':
                self.emit('disconnect')
            elif st == 'expired':
                self.emit('expire')
        s.on('stateChanged', initial_handler)

    def get_session(self) -> ZKSession | None:
        """The live session; a session that expired or closed is lazily
        replaced (reference: lib/client.js:264-273)."""
        if not self.is_in_state('normal'):
            return None
        if self.session.is_in_state('expired') or \
           self.session.is_in_state('closed'):
            self.old_session = self.session
            self._new_session()
        return self.session

    def _event_track(self, evt: str) -> None:
        if evt in ('session', 'connect', 'failed', 'degraded',
                   'recovered'):
            self.collector.get_collector(
                METRIC_ZK_EVENT_COUNTER).increment({'evtype': evt})

    def _emit_tracked(self, evt: str) -> None:
        self._event_track(evt)
        self.emit(evt)

    def is_degraded(self) -> bool:
        """True while the circuit breaker is open: every backend
        failed the full retry policy and the pool is parked in
        jittered monitor-mode redial."""
        return self.pool.degraded

    def _emit_after_connected(self, evt: str) -> None:
        """Defer an event until the connection can actually serve
        requests (reference: lib/client.js:237-262)."""
        conn = self.current_connection()
        if conn is None:
            return
        loop = ambient_loop()
        if conn.is_in_state('connected'):
            def fire():
                self._event_track(evt)
                self.emit(evt)
            loop.call_soon(fire)
        else:
            def on_conn_ch(cst):
                if cst == 'connected':
                    conn.remove_listener('stateChanged', on_conn_ch)
                    self._event_track(evt)
                    self.emit(evt)
            conn.on('stateChanged', on_conn_ch)

    def _on_pool_state_changed(self, st: str) -> None:
        if st == 'failed':
            def fire():
                self._event_track('failed')
                self.emit('failed', ZKNotConnectedError())
            ambient_loop().call_soon(fire)

    # -- connection access --

    def current_connection(self) -> ZKConnection | None:
        sess = self.get_session()
        if sess is None:
            return None
        return sess.get_connection()

    def is_connected(self) -> bool:
        conn = self.current_connection()
        return conn is not None and conn.is_in_state('connected')

    async def wait_connected(self, timeout: float | None = None,
                             fail_fast: bool = True) -> None:
        """Wait until the client is usable.

        Contract for ``failed``: it is an **edge event**, not a terminal
        state — it fires once when the initial retry policy exhausts on
        every backend, after which the pool keeps dialing forever in
        monitor mode (cueball's failed-state semantics, reference:
        lib/client.js:96-111) and may still recover.  With the default
        ``fail_fast=True`` this method surfaces the exhaustion as
        :class:`ZKNotConnectedError` — immediately if the pool is
        already in monitor mode, or on the ``failed`` edge while
        waiting.  With ``fail_fast=False`` policy exhaustion is ignored
        and the wait rides monitor mode until a connection lands or
        ``timeout`` expires (``asyncio.TimeoutError``)."""
        if self.is_connected():
            return
        if fail_fast and self.pool.state == 'failed':
            # 'failed' is edge-triggered; a pool already in monitor mode
            # will not re-emit it, so report the failure immediately.
            raise ZKNotConnectedError()
        loop = ambient_loop()
        fut: asyncio.Future = loop.create_future()

        def on_connect():
            if not fut.done():
                fut.set_result(None)

        def on_failed(err):
            if fail_fast and not fut.done():
                fut.set_exception(err)
        self.on('connect', on_connect)
        self.on('failed', on_failed)
        try:
            await asyncio.wait_for(fut, timeout)
        finally:
            self.remove_listener('connect', on_connect)
            self.remove_listener('failed', on_failed)

    def _conn_or_raise(self) -> ZKConnection:
        conn = self.current_connection()
        if conn is None or not conn.is_in_state('connected'):
            raise ZKNotConnectedError()
        return conn

    @staticmethod
    def _check_path(path) -> None:
        """Argument validation, matching the reference's assert-plus
        throws on bad inputs (reference: test/nasty.test.js:197-221)."""
        if not isinstance(path, str):
            raise TypeError('path must be a str, got %r' % (type(path),))
        if not path.startswith('/'):
            raise ValueError('path must start with /: %r' % (path,))

    @staticmethod
    def _check_data(data) -> None:
        if not isinstance(data, (bytes, bytearray, memoryview)):
            raise TypeError('data must be bytes, got %r' % (type(data),))

    @staticmethod
    def _check_version(version) -> None:
        # bool is an int subclass; a True/False version is always a
        # programmer error, not version 1/0.
        if not isinstance(version, int) or isinstance(version, bool):
            raise TypeError('version must be an int, got %r'
                            % (type(version),))

    # -- operations (reference: lib/client.js:318-601) --

    def _start_op(self, conn: ZKConnection, pkt: dict) -> tuple:
        """Send one traced request: the span is created before the
        write, correlated by the xid the connection assigns, and closed
        by the connection's reply/error routing (io/connection.py) with
        the reply zxid stamped on.

        A request that never makes it into the pending table (the
        connection died between the liveness check and the send) must
        not leave its span open — the ring would report a phantom
        in-flight op forever; it settles as ``abandoned`` and the
        error propagates."""
        span = self.trace.start(pkt['opcode'], pkt.get('path'))
        try:
            req = conn.request(pkt)
        except BaseException as e:
            span.finish(status='abandoned',
                        error=getattr(e, 'code', None)
                        or type(e).__name__)
            raise
        span.xid = pkt['xid']
        span.backend = conn.backend.key
        if conn.session is not None:
            # the request is already pending here, so the connection
            # settles this span on every teardown path; the getter
            # below cannot raise past it
            # zkanalyze: ignore[span-leak] plain getter; req pending
            span.session_id = conn.session.get_session_id()
        req.span = span
        return req.as_future(), span

    async def _await_op(self, fut: asyncio.Future, opcode: str,
                        path: str | None, deadline, span=None) -> dict:
        """Bound one request future by the per-request deadline.

        ``deadline`` is the per-op override in ms (``_USE_DEFAULT`` =
        the client's ``op_timeout``; ``None`` = unbounded).  On expiry
        the op fails fast with a typed :class:`ZKDeadlineError` instead
        of hanging on a dead or wedged connection; the underlying
        request is cancelled for the caller, and the connection's
        teardown paths still settle it exactly once internally.

        Every completion path (reply, error, deadline) records the
        elapsed time into the per-op latency histogram."""
        ms = self.op_timeout if deadline is _USE_DEFAULT else deadline
        t0 = time.monotonic()
        try:
            if ms is None:
                return await fut
            try:
                return await asyncio.wait_for(fut, ms / 1000.0)
            except asyncio.TimeoutError:
                if span is not None:
                    span.finish(status='deadline',
                                error='DEADLINE_EXCEEDED')
                raise ZKDeadlineError(opcode, path, ms) from None
        finally:
            self._op_latency.observe(
                (time.monotonic() - t0) * 1000.0, {'op': opcode})
            if self.on_op is not None and span is not None:
                self.on_op(span)

    # -- the read plane (README "Read plane") --

    def last_seen_zxid(self) -> int:
        """The newest member zxid this client has provably observed,
        across the primary session (write acks, reads, notifications
        — io/session.py tracks every reply header) and the read
        plane's distributed replies.  The client-side zxid gate
        compares every distributed read's reply header against it."""
        sess = self.session
        sess_z = 0 if sess is None else max(sess.last_zxid,
                                            sess.gate_floor)
        return max(sess_z, self._read_floor)

    async def _primary_request(self, pkt: dict, opcode: str,
                               path: str | None, deadline) -> dict:
        """One request on the primary connection (the legacy path):
        returns the full reply packet."""
        conn = self._conn_or_raise()
        fut, span = self._start_op(conn, pkt)
        return await self._await_op(fut, opcode, path, deadline, span)

    async def _write_op(self, pkt: dict, opcode: str,
                        path: str | None, deadline) -> dict:
        """One write on the primary connection, retrying THROTTLED
        bounces (README "Overload plane").

        An overloaded member bounces new writes with a typed
        :class:`ZKThrottledError` BEFORE proposing them — the write
        provably did not happen, so a blind resend is safe (no
        at-most-once concern, unlike a timeout).  The retry backs off
        on the client's default policy (capped exponential, full
        jitter) and gives up with the last THROTTLED error once the
        policy's attempt budget is spent.  Each attempt re-resolves
        the connection and sends a FRESH packet dict — ``_start_op``
        stamps the xid into it, and a retried xid would collide in
        the pending table."""
        backoff = None
        while True:
            conn = self._conn_or_raise()
            fut, span = self._start_op(conn, dict(pkt))
            try:
                return await self._await_op(fut, opcode, path,
                                            deadline, span)
            except ZKThrottledError:
                if backoff is None:
                    backoff = self._retry_policy.backoff(
                        seed=self._seed)
                if backoff.attempt >= self._retry_policy.retries:
                    raise
                delay_ms = backoff.next_delay()
                self.log.debug('THROTTLED %s %s; retry %d in %dms',
                               opcode, path, backoff.attempt,
                               delay_ms)
                await asyncio.sleep(delay_ms / 1000.0)

    def _note_read_floor(self, zxid: int) -> None:
        """A distributed read showed the client member state at
        ``zxid``: raise the client floor AND the session's gate
        floor, so the next handshake (migration, replacement) seeds
        the server-side ReadGate with everything this client has
        seen — on any of its connections."""
        if zxid > self._read_floor:
            self._read_floor = zxid
        sess = self.session
        if sess is not None and zxid > sess.gate_floor:
            sess.gate_floor = zxid

    async def _read_request(self, pkt: dict, opcode: str,
                            path: str | None, deadline) -> dict:
        """Route one read: through the read plane when enabled —
        zxid-gated, so a reply from a member behind this client's
        floor (re-checked at REPLY time: a write acked while the
        read was in flight raises it) is DISCARDED and the read
        re-issued on the primary connection (never surfaced stale) —
        else the primary.  Any read-session failure (typed error,
        deadline, not-connected) also falls back to the primary: the
        distributed path may add a retry's latency, never a new
        failure mode.  The primary fallback is floor-guarded too:
        when its member trails what the plane already showed this
        client (possible inside one connection — the handshake seed
        only covers floors known at attach time), a ``sync`` barrier
        catches the member up and the read re-issues once.

        The cache plane (README "Client cache plane") consults FIRST:
        a read under a subscribed, coherent subtree returns locally —
        no wire round trip at all — and every server reply that does
        go out deposits back in, read-through."""
        cache = self.cache
        if cache is not None and path is not None:
            out = cache.lookup(opcode, path)
            if out is not None:
                # a cached serve is still one observed op: it lands
                # in the span ring (and the campaign history via
                # on_op) like any server read, flagged 'cached'
                span = self.trace.start(opcode, path)
                span.detail = 'cached'
                span.finish(zxid=out.get('zxid'))
                if self.on_op is not None:
                    self.on_op(span)
                return out
        plane = self._read_plane
        if plane is not None and plane.started:
            primary = self.pool.current_backend()
            sub = plane.pick(primary.key if primary is not None
                             else None)
            if sub is not None:
                try:
                    out = await sub._primary_request(
                        dict(pkt), opcode, path, deadline)
                except (ZKNotConnectedError, ZKDeadlineError):
                    plane.fallbacks += 1
                except Exception as e:
                    from .protocol.errors import (
                        ZKError,
                        ZKProtocolError,
                    )
                    if not isinstance(e, (ZKError, ZKProtocolError,
                                          OSError)):
                        raise
                    # a spec verdict off a possibly-stale member
                    # (error replies carry no state to gate on) or
                    # connection churn: the primary's answer is the
                    # contract
                    plane.fallbacks += 1
                else:
                    if out.get('zxid', 0) >= self.last_seen_zxid():
                        plane.distributed += 1
                        self._note_read_floor(out['zxid'])
                        return out
                    plane.bounced += 1   # stale member: never surface
        out = await self._primary_request(pkt, opcode, path, deadline)
        if plane is not None \
                and out.get('zxid', 0) < self._read_floor \
                and path is not None:
            # the primary's member trails the plane's floor: sync is
            # the bounded barrier (the member applies everything the
            # leader committed — which includes every zxid any member
            # ever showed this client), then the read re-issues fresh
            plane.bounced += 1
            await self._primary_request(
                {'opcode': 'SYNC', 'path': path}, 'SYNC', path,
                deadline)
            out = await self._primary_request(pkt, opcode, path,
                                              deadline)
        if cache is not None and path is not None:
            cache.fill(opcode, path, out)
        return out

    async def ping(self, deadline=_USE_DEFAULT) -> float:
        """Round-trip a ping; resolves to the latency in ms."""
        conn = self._conn_or_raise()
        loop = ambient_loop()
        fut: asyncio.Future = loop.create_future()
        span = self.trace.start('PING')
        span.backend = conn.backend.key

        def cb(err, latency):
            if fut.done():
                return
            if err is not None:
                span.finish(status='error',
                            error=getattr(err, 'code', None)
                            or type(err).__name__)
                fut.set_exception(err)
            else:
                span.finish()
                fut.set_result(latency)
        try:
            conn.ping(cb)
        except BaseException as e:
            # never sent: settle the span (see _start_op)
            span.finish(status='abandoned',
                        error=getattr(e, 'code', None)
                        or type(e).__name__)
            raise
        return await self._await_op(fut, 'PING', None, deadline, span)

    async def list(self, path: str,
                   deadline=_USE_DEFAULT) -> tuple[list[str], Stat]:
        """Children of a znode, with its stat."""
        self._check_path(path)
        pkt = await self._read_request(
            {'opcode': 'GET_CHILDREN2', 'path': path, 'watch': False},
            'GET_CHILDREN2', path, deadline)
        return pkt['children'], pkt['stat']

    async def get(self, path: str,
                  deadline=_USE_DEFAULT) -> tuple[bytes, Stat]:
        self._check_path(path)
        pkt = await self._read_request(
            {'opcode': 'GET_DATA', 'path': path, 'watch': False},
            'GET_DATA', path, deadline)
        return pkt['data'], pkt['stat']

    async def create(self, path: str, data: bytes,
                     acl=None, flags: CreateFlag | int = 0,
                     deadline=_USE_DEFAULT) -> str:
        """Create a znode; resolves to the created path (which differs
        from the request path for SEQUENTIAL nodes)."""
        self._check_path(path)
        self._check_data(data)
        if acl is None:
            acl = list(OPEN_ACL_UNSAFE)
        pkt = await self._write_op({'opcode': 'CREATE', 'path': path,
                                    'data': data, 'acl': acl,
                                    'flags': CreateFlag(flags)},
                                   'CREATE', path, deadline)
        return pkt['path']

    async def create_with_empty_parents(self, path: str, data: bytes,
                                        acl=None,
                                        flags: CreateFlag | int = 0,
                                        deadline=_USE_DEFAULT) -> str:
        """Create a znode, creating any missing parents as plain
        persistent nodes with data b'null'; NODE_EXISTS on a parent is
        fine, on the leaf it is an error.  Options apply only to the
        leaf (reference: lib/client.js:412-481)."""
        from .protocol.errors import ZKError

        self._check_path(path)
        self._check_data(data)
        nodes = path.split('/')[1:]
        current = ''
        result = None
        for i, node in enumerate(nodes):
            current = current + '/' + node
            last = (i == len(nodes) - 1)
            try:
                result = await self.create(
                    current,
                    data if last else b'null',
                    acl=acl if last else None,
                    flags=flags if last else 0,
                    deadline=deadline)
            except ZKError as e:
                if last or e.code != 'NODE_EXISTS':
                    raise
        return result

    async def set(self, path: str, data: bytes,
                  version: int = -1, deadline=_USE_DEFAULT) -> Stat:
        """Set a znode's data; resolves to the new stat.  (The reference
        passes its callback a path field SET_DATA replies do not carry,
        lib/client.js:503-504 — the stat is the useful payload.)"""
        self._check_path(path)
        self._check_data(data)
        self._check_version(version)
        pkt = await self._write_op({'opcode': 'SET_DATA',
                                    'path': path, 'data': data,
                                    'version': version},
                                   'SET_DATA', path, deadline)
        return pkt['stat']

    async def delete(self, path: str, version: int,
                     deadline=_USE_DEFAULT) -> None:
        self._check_path(path)
        self._check_version(version)
        await self._write_op({'opcode': 'DELETE', 'path': path,
                              'version': version},
                             'DELETE', path, deadline)

    async def stat(self, path: str, deadline=_USE_DEFAULT) -> Stat:
        self._check_path(path)
        pkt = await self._read_request(
            {'opcode': 'EXISTS', 'path': path, 'watch': False},
            'EXISTS', path, deadline)
        return pkt['stat']

    async def get_acl(self, path: str, deadline=_USE_DEFAULT):
        self._check_path(path)
        pkt = await self._read_request(
            {'opcode': 'GET_ACL', 'path': path},
            'GET_ACL', path, deadline)
        return pkt['acl']

    async def sync(self, path: str, deadline=_USE_DEFAULT) -> None:
        """Flush the leader pipeline to the connected server
        (reference: lib/client.js:578-597).

        With the read plane on this is a REAL leader barrier for
        read-your-writes across sessions: the serving member applies
        everything the leader committed before replying, the reply
        header stamps that position into the session floor, and every
        later distributed read is zxid-gated above it — so state
        another session wrote before this sync can never be missed by
        a follower- or observer-served read afterwards."""
        self._check_path(path)
        await self._primary_request(
            {'opcode': 'SYNC', 'path': path}, 'SYNC', path, deadline)

    async def multi(self, ops: list, deadline=_USE_DEFAULT) -> list:
        """One all-or-nothing MULTI transaction (opcode 14): ``ops``
        is a list of sub-op dicts — ``{'op': 'create', 'path', 'data',
        'acl'?, 'flags'?}``, ``{'op': 'delete', 'path', 'version'?}``,
        ``{'op': 'set_data', 'path', 'data', 'version'?}``,
        ``{'op': 'check', 'path', 'version'}`` — applied as ONE server
        transaction sharing one WAL record and one group-fsync slot
        (server/store.py ``ZKDatabase.multi``).  Resolves to the
        per-op results in order (created path / new Stat / None);
        raises :class:`~.protocol.errors.ZKMultiError` when the batch
        was rejected — then NO sub-op was applied.

        :meth:`transaction` is the builder-style sugar over this."""
        from .protocol.errors import ZKMultiError
        from .protocol.records import MULTI_OPS

        wire_ops = []
        for op in ops:
            name = op.get('op')
            if name not in MULTI_OPS:
                raise ValueError('unsupported multi sub-op %r'
                                 % (name,))
            self._check_path(op['path'])
            sub = {'op': name, 'path': op['path']}
            if name == 'create':
                self._check_data(op.get('data', b''))
                sub['data'] = op.get('data', b'')
                sub['acl'] = (list(op['acl']) if op.get('acl')
                              else list(OPEN_ACL_UNSAFE))
                sub['flags'] = CreateFlag(op.get('flags', 0))
            elif name == 'set_data':
                self._check_data(op['data'])
                self._check_version(op.get('version', -1))
                sub['data'] = op['data']
                sub['version'] = op.get('version', -1)
            else:                     # delete / check
                self._check_version(op.get('version', -1))
                sub['version'] = op.get('version', -1)
            wire_ops.append(sub)
        pkt = await self._write_op({'opcode': 'MULTI',
                                    'ops': wire_ops},
                                   'MULTI', None, deadline)
        results = pkt['results']
        if any(r['op'] == 'error' for r in results):
            raise ZKMultiError(results)
        out: list = []
        for r in results:
            if r['op'] == 'create':
                out.append(r['path'])
            elif r['op'] == 'set_data':
                out.append(r['stat'])
            else:
                out.append(None)
        return out

    def transaction(self) -> 'Transaction':
        """A builder for one MULTI transaction::

            t = client.transaction()
            t.create('/a', b'x').set('/b', b'y').delete('/old')
            results = await t.commit()
        """
        return Transaction(self)

    def watcher(self, path: str) -> ZKWatcher:
        self._check_path(path)
        sess = self.get_session()
        if sess is None:
            # The client is closing or closed.
            raise ZKNotConnectedError()
        return sess.watcher(path)

    async def add_watch(self, path: str, recursive: bool = False,
                        deadline=_USE_DEFAULT) -> ZKPersistentWatcher:
        """Arm a persistent watch (ADD_WATCH, opcode 106) on ``path``
        — ``recursive=True`` for PERSISTENT_RECURSIVE, matching the
        whole subtree.  Resolves to the session's
        :class:`~.io.watcher.ZKPersistentWatcher` emitter: unlike
        :meth:`watcher`'s one-shot engine it survives fires with no
        re-arm read, and it replays across reconnects via
        SET_WATCHES2.  The registration is made BEFORE the round
        trip, so even if the arm races a disconnect the next
        reconnect's replay arms it — the returned emitter is live
        either way (the raised error tells the caller the first arm
        did not confirm)."""
        self._check_path(path)
        sess = self.get_session()
        if sess is None:
            raise ZKNotConnectedError()
        w = sess.persistent_watcher(path, recursive)
        await self._primary_request(
            {'opcode': 'ADD_WATCH', 'path': path,
             'mode': 1 if recursive else 0},
            'ADD_WATCH', path, deadline)
        return w

    def remove_persistent_watch(self, path: str) -> None:
        """Drop a persistent registration client-side.  The
        server-side subscription dies with the connection's next
        reconnect (it is simply not replayed); there is no wire op
        to remove it eagerly, matching the reference's lack of
        checkWatches support."""
        self._check_path(path)
        sess = self.get_session()
        if sess is not None:
            sess.drop_persistent_watcher(path)


class Transaction:
    """Builder sugar over :meth:`Client.multi` (the kazoo/Curator
    transaction shape): queue sub-ops, then ``await commit()`` — the
    whole batch applies as one server transaction or not at all."""

    def __init__(self, client: Client):
        self._client = client
        self.ops: list[dict] = []

    def create(self, path: str, data: bytes = b'', acl=None,
               flags: CreateFlag | int = 0) -> 'Transaction':
        self.ops.append({'op': 'create', 'path': path, 'data': data,
                         'acl': acl, 'flags': flags})
        return self

    def set(self, path: str, data: bytes,
            version: int = -1) -> 'Transaction':
        self.ops.append({'op': 'set_data', 'path': path, 'data': data,
                         'version': version})
        return self

    def delete(self, path: str, version: int = -1) -> 'Transaction':
        self.ops.append({'op': 'delete', 'path': path,
                         'version': version})
        return self

    def check(self, path: str, version: int) -> 'Transaction':
        self.ops.append({'op': 'check', 'path': path,
                         'version': version})
        return self

    async def commit(self, deadline=_USE_DEFAULT) -> list:
        return await self._client.multi(self.ops, deadline=deadline)
