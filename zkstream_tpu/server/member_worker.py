"""One symmetric ensemble-member OS process (server/election.py).

Unlike tests/process_member_worker.py's fixed ``leader``/``follower``
roles, a *member* has no pre-assigned role: it recovers whatever its
WAL directory holds, votes with the recovered (epoch, zxid) pair, and
ends up leading or following — re-electing on every leader loss —
until killed.  Spawned by the process-tier election harness
(``run_process_schedule``) and tests/test_process_ensemble.py.

Usage::

    python member_worker.py ID WAL_DIR CLIENT_PORT ELECTION_PORT \
        [--observer] [PEER_ID:HOST:PORT[:observer] ...]

Prints ``READY <client_port> <election_port>`` once the member serves
clients under its first resolved role.  ``--observer`` makes this
member a non-voting read-serving replica (README "Read plane"); a
peer spec suffixed ``:observer`` marks that PEER as one, so the
voting total this member elects against excludes it.
``ZKSTREAM_MEMBER_SYNC`` picks the WAL fsync policy (default
``tick``).

Each member keeps a black-box flight recorder
(utils/blackbox.py) in its WAL_DIR: when the harness SIGKILLs this
process, the harvest pass lifts the durable frames — last mntr
counters, tick phases, span tail — back into the schedule's merged
timeline, and ``python -m zkstream_tpu blackbox WAL_DIR`` renders
them by hand.  ``ZKSTREAM_NO_BLACKBOX=1`` disables the recorder,
``ZKSTREAM_BLACKBOX_MS`` its cadence.
"""

from __future__ import annotations

import asyncio
import os
import sys


def main() -> int:
    # keep jax fully out of the picture, same as the test workers:
    # the server stack is pure asyncio and must not touch a possibly
    # wedged accelerator plugin via the image's site hook
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if root not in sys.path:
        sys.path.insert(0, root)
    from zkstream_tpu.server.election import run_member

    # a read-plane member may serve up to a million sessions (`make
    # bench-million`): lift the soft fd limit as far as the host
    # allows, and name the binding constraint when it can't
    # (utils/fdlimit.py — ZKServer.start does the same against its
    # admission ceiling)
    from zkstream_tpu.utils import fdlimit
    need = int(os.environ.get('ZKSTREAM_MEMBER_FDS', '0') or 0)
    fdlimit.raise_nofile(need + 256 if need else None)
    if need:
        err = fdlimit.headroom_error(need)
        if err:
            print('member %s fd headroom: %s'
                  % (sys.argv[1], err), file=sys.stderr)

    member_id = int(sys.argv[1])
    wal_dir = sys.argv[2]
    client_port = int(sys.argv[3])
    election_port = int(sys.argv[4])
    rest = sys.argv[5:]
    observer = '--observer' in rest
    peers = []
    voter_ids = [] if observer else [member_id]
    observer_ids = [member_id] if observer else []
    for spec in rest:
        if spec == '--observer':
            continue
        parts = spec.split(':')
        pid, host, port = parts[0], parts[1], parts[2]
        if len(parts) < 4 or parts[3] != 'observer':
            voter_ids.append(int(pid))
        else:
            observer_ids.append(int(pid))
        peers.append((int(pid), host, int(port)))
    voters = len(voter_ids)
    sync = os.environ.get('ZKSTREAM_MEMBER_SYNC', 'tick')
    asyncio.run(run_member(member_id, wal_dir, client_port,
                           election_port, peers, sync=sync,
                           observer=observer, voters=voters,
                           voter_ids=sorted(voter_ids),
                           observer_ids=sorted(observer_ids)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
