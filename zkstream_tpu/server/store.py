"""The in-process ZooKeeper server's data model.

The reference tests against a real ZooKeeper JVM spawned as a child
process (reference: test/zkserver.js) — unavailable here, so this module
implements the server-side semantics the client exercises: the znode
tree with full Stat bookkeeping, zxid allocation, session lifecycle with
expiry timers and ephemeral cleanup, sequential-node numbering, and
change events that per-connection watch tables subscribe to.

Replication model (the quorum analogue): one ``ZKDatabase`` is the
**leader** — it validates and sequences every write, allocates zxids,
and appends each committed transaction to an in-order commit log.  Each
ensemble follower serves reads from its own ``ReplicaStore``, a separate
znode tree fed by that log with injectable lag — so a follower can be
*behind* the leader and serve a genuinely stale read, which is what
gives the client's ``sync`` op observable meaning (reference semantics:
test/multi-node.test.js:107-165 — a follower may lag until sync).
Sessions stay leader-global (in real ZK they are quorum state tracked
by the leader), so a session survives its serving member dying as long
as the client resumes it anywhere within the timeout, and ephemeral
cleanup is itself a sequence of logged deletes that replicate like any
other write.

Both leader and replicas mutate their trees through the shared
``NodeTree._apply_*`` primitives, so a replayed transaction produces a
byte-identical Stat on every member.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import secrets
import threading
import time

from ..protocol.consts import CreateFlag
from ..protocol.records import ACL, OPEN_ACL_UNSAFE, Stat
# entry_zxid rides the traced commit/apply hot paths; persist.py
# imports this module only lazily, so the top-level import is safe
from .persist import entry_zxid
from ..utils.events import EventEmitter
from ..utils.aio import ambient_loop

log = logging.getLogger('zkstream_tpu.server.store')


class ZKOpError(Exception):
    """A server-side operation failure, named by protocol error code."""

    def __init__(self, code: str):
        super().__init__(code)
        self.code = code


@dataclasses.dataclass
class Znode:
    data: bytes = b''
    acl: tuple = OPEN_ACL_UNSAFE
    czxid: int = 0
    mzxid: int = 0
    pzxid: int = 0
    ctime: int = 0
    mtime: int = 0
    version: int = 0
    cversion: int = 0
    aversion: int = 0
    ephemeral_owner: int = 0
    children: set = dataclasses.field(default_factory=set)
    #: Monotonic sequential-suffix counter (real ZK derives this from
    #: cversion; an explicit counter keeps numbering stable across
    #: deletes).  Leader-only: sequential names are resolved before a
    #: create is logged, so replicas never consult it.
    seq: int = 0

    def stat(self) -> Stat:
        return Stat(czxid=self.czxid, mzxid=self.mzxid, ctime=self.ctime,
                    mtime=self.mtime, version=self.version,
                    cversion=self.cversion, aversion=self.aversion,
                    ephemeralOwner=self.ephemeral_owner,
                    dataLength=len(self.data),
                    numChildren=len(self.children), pzxid=self.pzxid)


@dataclasses.dataclass
class ZKServerSession:
    id: int
    passwd: bytes
    timeout: int
    ephemerals: set = dataclasses.field(default_factory=set)
    expired: bool = False
    closed: bool = False
    #: The server connection currently serving this session, if any.
    owner: object = None
    expiry_handle: asyncio.TimerHandle | None = None
    #: The newest member zxid this session has provably observed — the
    #: max of every reply header it was sent plus the ``lastZxidSeen``
    #: it presented at each handshake.  The zxid read gate
    #: (server/server.py ReadGate) refuses to serve this session's
    #: reads from a member behind this floor: the session view must
    #: never go backwards (analysis/linearize.py check_session_reads).
    #: In-process ensembles share the session OBJECT across members,
    #: so the floor survives migration by construction; cross-process
    #: members learn it from the handshake.
    last_zxid: int = 0
    #: When this member last FORWARDED a touch for this session to
    #: its leader (monotonic seconds; cross-process members only).
    #: Touch forwarding is rate-limited to a fraction of the session
    #: timeout — real ZK's learner ping cadence — because a
    #: per-request touch RPC would make the leader the read plane's
    #: bottleneck (server/replication.py RemoteLeader.touch_session).
    last_touch_fwd: float = 0.0


def parent_path(path: str) -> str:
    idx = path.rfind('/')
    return path[:idx] if idx > 0 else '/'


def validate_path(path: str) -> None:
    if not path.startswith('/'):
        raise ZKOpError('BAD_ARGUMENTS')
    if path != '/' and path.endswith('/'):
        raise ZKOpError('BAD_ARGUMENTS')
    if '//' in path:
        raise ZKOpError('BAD_ARGUMENTS')


def durable_sessions(sessions: dict) -> dict:
    """A session table's durable form — the ONE definition of what a
    format-3 snapshot stamps, a mirror seeds and a promotion seats
    (server/persist.py, server/replication.py):
    ``{sid: (passwd, timeout)}``, live sessions only."""
    return {sid: (s.passwd, s.timeout) for sid, s in sessions.items()
            if not s.expired and not s.closed}


def _copy_znode(node: 'Znode | None') -> 'Znode | None':
    """A rollback-grade copy: every scalar field plus a fresh children
    set (data bytes and the ACL tuple are immutable and may alias)."""
    if node is None:
        return None
    return dataclasses.replace(node, children=set(node.children))


class NodeTree(EventEmitter):
    """A znode tree plus the deterministic transaction-apply primitives
    shared by the leader and every replica — one code path mutates all
    members' trees, so replayed state cannot drift.

    Change events (for per-connection watch tables):
    ``created(path, zxid)``, ``deleted(path, zxid)``,
    ``dataChanged(path, zxid)``, ``childrenChanged(path, zxid)``.
    ``zxid`` is the last transaction applied to THIS tree (== the
    leader's on a caught-up member, behind it on a lagging one).
    """

    #: Optional utils/trace.TraceRing — the owning member's span ring
    #: (server/server.py wires it): the leader database records a
    #: ``COMMIT`` span per txn, a replica an ``APPLY`` span per
    #: replayed entry, so a write's cross-member path is traceable by
    #: zxid.  Class-level None keeps the no-tracing hot path a single
    #: attribute test.
    trace = None

    #: When set (``ZKDatabase.multi``), change events buffer here
    #: instead of dispatching: a speculative multi apply must not fire
    #: watches it may roll back.  Class-level None keeps the normal
    #: emit path a single attribute test.
    _event_buf = None

    def __init__(self) -> None:
        super().__init__()
        self.nodes: dict[str, Znode] = {'/': Znode()}
        self.zxid = 0

    def emit(self, event: str, *args) -> None:
        buf = self._event_buf
        if buf is not None:
            buf.append((event, args))
            return
        super().emit(event, *args)

    # -- snapshot (late-joining replica bootstrap) --

    def snapshot(self) -> dict:
        """An image of the tree and its position — what a late-joining
        replica installs before replaying the log tail (real ZK's
        follower resync; server/replication.py).  The image ALIASES the
        live tree: the one caller pickles it onto the wire in the same
        synchronous tick, so a defensive deep copy would only duplicate
        an arbitrarily large tree for nothing.  An in-process consumer
        that intends to retain it must copy it itself."""
        return {'zxid': self.zxid, 'nodes': self.nodes}

    def install(self, snap: dict) -> None:
        """Replace this tree with a snapshot image.  The image is
        adopted, not copied — it arrives freshly unpickled from the
        replication socket (or a WAL snapshot file, server/persist.py)
        and is private to this replica."""
        self.nodes = snap['nodes']
        self.zxid = snap['zxid']

    # -- transaction apply (leader commit path + replica replay) --

    def apply_entry(self, entry: tuple) -> None:
        """Apply one self-contained commit-log entry to this tree —
        the single replay dispatch shared by replica catch-up
        (:class:`ReplicaStore`) and WAL recovery (server/persist.py),
        so a replayed transaction produces a byte-identical Stat on
        every member *and* after a restart from disk."""
        op = entry[0]
        if op == 'create':
            _, path, data, acl, eph_owner, zxid, now = entry
            self._apply_create(path, data, acl, eph_owner, zxid, now)
        elif op == 'delete':
            self._apply_delete(entry[1], entry[2])
        elif op == 'set_data':
            _, path, data, zxid, now = entry
            self._apply_set_data(path, data, zxid, now)
        elif op == 'multi':
            # ONE all-or-nothing transaction: the subs apply in order,
            # guarded by zxid so a replay over a fuzzy image (WAL
            # recovery) skips the prefix the image already holds —
            # a torn multi RECORD never reaches here at all (the CRC
            # frame covers the whole batch, server/persist.py)
            for sub in entry[1]:
                if entry_zxid(sub) > self.zxid:
                    self.apply_entry(sub)
        elif op in ('session', 'session_close'):
            # session control records ride the commit log (a follower
            # mirror must carry the table for failover) but never
            # touch the tree
            self._apply_session(entry)
        elif op == 'reconfig':
            # membership control record: rides the commit log so every
            # mirror carries the config for failover, consumes a zxid
            # (the joint window is bounded by sequenced records), but
            # never touches the tree
            self.zxid = max(self.zxid, entry[6])
            self._apply_reconfig(entry)
        else:  # pragma: no cover - log entries are produced above
            raise AssertionError('unknown log entry %r' % (op,))

    def _apply_session(self, entry: tuple) -> None:
        """Session-record hook.  A plain tree (WAL recovery target)
        and an in-process replica (the shared leader database already
        owns the table) ignore them; the cross-process mirror's
        replica overrides this to maintain its leader-handle table
        (server/replication.py RemoteReplicaStore)."""

    def _apply_reconfig(self, entry: tuple) -> None:
        """Reconfig-record hook, same shape as :meth:`_apply_session`:
        ignored by a plain tree and an in-process replica (the shared
        leader database owns the config); the cross-process mirror's
        replica overrides it so a promoted follower inherits the
        membership config — including an in-progress joint window —
        from its replicated log (server/replication.py)."""

    def _apply_create(self, path: str, data: bytes, acl: tuple,
                      ephemeral_owner: int, zxid: int, now: int) -> None:
        node = Znode(data=data, acl=acl, czxid=zxid, mzxid=zxid,
                     pzxid=zxid, ctime=now, mtime=now,
                     ephemeral_owner=ephemeral_owner)
        self.nodes[path] = node
        ppath = parent_path(path)
        parent = self.nodes[ppath]
        parent.children.add(path.rsplit('/', 1)[1])
        parent.cversion += 1
        parent.pzxid = zxid
        self.zxid = zxid
        self.emit('created', path, zxid)
        self.emit('childrenChanged', ppath, zxid)

    def _apply_delete(self, path: str, zxid: int) -> Znode:
        node = self.nodes.pop(path)
        ppath = parent_path(path)
        parent = self.nodes.get(ppath)
        if parent is not None:
            parent.children.discard(path.rsplit('/', 1)[1])
            parent.cversion += 1
            parent.pzxid = zxid
        self.zxid = zxid
        self.emit('deleted', path, zxid)
        self.emit('childrenChanged', ppath, zxid)
        return node

    def _apply_set_data(self, path: str, data: bytes, zxid: int,
                        now: int) -> Znode:
        node = self.nodes[path]
        node.data = data
        node.version += 1
        node.mzxid = zxid
        node.mtime = now
        self.zxid = zxid
        self.emit('dataChanged', path, zxid)
        return node

    # -- reads (serve from this member's view) --

    def get_data(self, path: str) -> tuple[bytes, Stat]:
        node = self.nodes.get(path)
        if node is None:
            raise ZKOpError('NO_NODE')
        return node.data, node.stat()

    def exists(self, path: str) -> Stat:
        node = self.nodes.get(path)
        if node is None:
            raise ZKOpError('NO_NODE')
        return node.stat()

    def get_children(self, path: str) -> tuple[list[str], Stat]:
        node = self.nodes.get(path)
        if node is None:
            raise ZKOpError('NO_NODE')
        return sorted(node.children), node.stat()

    def get_acl(self, path: str) -> tuple[list[ACL], Stat]:
        node = self.nodes.get(path)
        if node is None:
            raise ZKOpError('NO_NODE')
        return list(node.acl), node.stat()


class ZKDatabase(NodeTree):
    """The leader: validates and sequences writes, allocates zxids,
    owns the session table, and appends every committed transaction to
    ``log`` (emitting ``committed`` for replicas to consume).

    Extra events beyond :class:`NodeTree`'s:
    ``sessionExpired(session_id)``, ``committed()``.
    """

    def __init__(self) -> None:
        super().__init__()
        self.sessions: dict[int, ZKServerSession] = {}
        #: Leadership epoch (server/election.py): a fencing token, not
        #: a zxid component.  0 until the first election; bumped by the
        #: winning member (``bump_epoch``), persisted as a WAL control
        #: record so a restart recovers it, stamped on every
        #: replication push and forwarded-write ack so stale-epoch
        #: traffic is rejectable instead of silently merged.
        self.epoch = 0
        #: The commit log: every mutation, in zxid order, as a
        #: self-contained entry a :class:`ReplicaStore` can replay.
        #: Only kept once a replica attaches — a standalone server
        #: must not retain every payload for the process lifetime —
        #: and truncated as all replicas apply (``log[0]`` is absolute
        #: index ``log_base``), so a long-running ensemble does not
        #: grow memory without bound either.
        self.log: list[tuple] = []
        self.log_base = 0
        #: The zxid the retained log is contiguous *after*: every txn
        #: with zxid > log_start_zxid is in ``log``.  Maintained so a
        #: follower recovering from its own WAL (server/persist.py)
        #: can rejoin with its recovered zxid as the catch-up base —
        #: shipped only the tail — instead of a full snapshot fetch.
        self.log_start_zxid = 0
        #: Optional write-ahead log (server/persist.py): when set,
        #: ``_commit`` appends every txn BEFORE its ack can leave.
        self.wal = None
        #: While a MULTI is applying, committed sub-entries collect
        #: here instead of reaching the WAL/log — on success the whole
        #: batch commits as ONE ('multi', subs) record sharing one
        #: group-fsync slot; on failure it rolls back untraced.
        self._multi_buf: list | None = None
        #: MULTI counters (mntr rows zk_multi_*).
        self.multi_batches = 0
        self.multi_subops = 0
        self._replicas: list['ReplicaStore'] = []
        #: Dynamic membership (reconfig control records).  ``None``
        #: voter_ids = never configured: the boot-time shape stands
        #: and quorum math stays count-based (the legacy path, bit-
        #: identical to pre-reconfig behavior).  During a joint window
        #: ``old_voter_ids`` holds C_old — quorum-commit and elections
        #: need majorities of BOTH sets until the final record commits.
        self.config_version = 0
        self.voter_ids: tuple | None = None
        self.old_voter_ids: tuple | None = None
        self.observer_ids: tuple = ()
        #: completed membership changes (mntr zk_reconfig_total)
        self.reconfig_total = 0
        #: epoch of the last completed VOTER change — the at-most-one-
        #: membership-change-per-epoch guard (invariant 7 extension)
        self.reconfig_epoch = -1
        #: hook called with (phase, entry) after each reconfig record
        #: commits — the owner (ZKEnsemble / run_member) repoints the
        #: QuorumGate voter sets, election tallies and client resolver
        self.on_config_change = None
        # Like real ZK's (timestamp << 24) seed, masked into int64 range.
        self._next_session = ((int(time.time() * 1000) << 24)
                              & 0x7fffffffffff0000)

    # -- zxid / time --

    def next_zxid(self) -> int:
        self.zxid += 1
        return self.zxid

    @staticmethod
    def now_ms() -> int:
        return int(time.time() * 1000)

    def catch_up(self) -> None:
        """The leader is always caught up (uniform member interface)."""

    def sync_flush(self) -> None:
        """The SYNC op's barrier — trivial on the leader."""

    def bump_epoch(self, epoch: int) -> None:
        """Adopt a new leadership epoch (the winning member of an
        election calls this before serving a single write).  The bump
        is a WAL *control* record — logged and fsynced like a txn so a
        restarted member recovers the epoch it was fenced at — but it
        never enters the replication ``log``: replicas learn epochs
        from the stamp on every push, and control records must not
        shift the log's index arithmetic."""
        if epoch <= self.epoch:
            raise ValueError('epoch must increase: %d -> %d'
                             % (self.epoch, epoch))
        self.epoch = epoch
        if self.wal is not None:
            self.wal.append(('epoch', epoch, self.zxid))
            # the fence must be durable before it can be trusted: a
            # deposed-then-restarted leader that lost the bump would
            # come back believing its stale epoch
            self.wal.sync_for_flush()

    # -- dynamic membership (reconfig control records) --

    def install_config(self, cfg: dict) -> None:
        """Adopt a membership config wholesale — the boot-time shape
        (ZKEnsemble), a WAL-recovered one (server/persist.py), or a
        promoted mirror's replicated one (server/replication.py)."""
        self.config_version = cfg.get('version', 0)
        voters = cfg.get('voters')
        self.voter_ids = tuple(voters) if voters is not None else None
        old = cfg.get('old_voters')
        self.old_voter_ids = tuple(old) if old else None
        self.observer_ids = tuple(cfg.get('observers') or ())

    def config_snapshot(self) -> dict | None:
        """The membership config in its durable form — what a format-3
        snapshot stamps and recovery adopts (server/persist.py); None
        until the ensemble is configured (legacy images stay
        byte-compatible)."""
        if self.voter_ids is None:
            return None
        return {'version': self.config_version,
                'phase': ('joint' if self.old_voter_ids is not None
                          else 'final'),
                'voters': self.voter_ids,
                'old_voters': self.old_voter_ids,
                'observers': self.observer_ids}

    def joint_config(self) -> tuple | None:
        """(C_old, C_new) while a joint window stands, else None."""
        if self.old_voter_ids is None:
            return None
        return (self.old_voter_ids, self.voter_ids)

    def propose_reconfig(self, new_voters, observers=None) -> tuple:
        """Begin a membership change: commit the phase-'joint' WAL
        CONTROL record installing C_old+C_new.  From this record's
        commit until :meth:`commit_reconfig`'s final record, quorum
        commit and elections must hold majorities of BOTH voter sets
        (server/replication.py QuorumGate, server/election.py).  An
        observer-only change (voter set unchanged) has no quorum
        implications and commits a single 'final' record directly.
        Returns the committed entry."""
        if self.voter_ids is None:
            raise ValueError('ensemble has no installed config')
        if self.old_voter_ids is not None:
            raise ValueError(
                'reconfig already in progress (config version %d is '
                'joint)' % (self.config_version,))
        new_voters = tuple(new_voters)
        observers = (tuple(observers) if observers is not None
                     else self.observer_ids)
        voters_change = set(new_voters) != set(self.voter_ids)
        if voters_change and self.reconfig_epoch == self.epoch:
            # at most one voter-set change per epoch (invariant 7
            # extension): a second change must wait for an epoch bump
            raise ValueError(
                'voter set already changed in epoch %d'
                % (self.epoch,))
        if voters_change and not new_voters:
            raise ValueError('cannot reconfig to an empty voter set')
        old = self.voter_ids
        phase = 'joint' if voters_change else 'final'
        if self.trace is not None:
            self.trace.note('RECONFIG', zxid=self.zxid, kind='server',
                            detail='propose v%d %s'
                            % (self.config_version + 1, phase))
        self.config_version += 1
        if voters_change:
            self.old_voter_ids = old
        self.voter_ids = new_voters
        self.observer_ids = observers
        zxid = self.next_zxid()
        entry = ('reconfig', self.config_version, phase,
                 tuple(old) if voters_change else (), new_voters,
                 observers, zxid)
        # the config governs from APPEND, not commit (joint
        # consensus): the hook re-derives the quorum/ballot sets
        # BEFORE the record commits, so the joint record itself must
        # clear majorities of both configs — and a just-promoted
        # voter's ack of this very record is counted, not fenced
        hook = self.on_config_change
        if hook is not None:
            hook(phase, entry)
        self._commit(entry)
        if self.trace is not None:
            self.trace.note('RECONFIG', zxid=zxid, kind='server',
                            detail='%s v%d voters=%s'
                            % (phase, self.config_version,
                               ','.join(map(str, new_voters))))
        if not voters_change:
            self.reconfig_total += 1
        return entry

    def commit_reconfig(self) -> tuple:
        """Close the joint window: commit the phase-'final' record —
        C_new alone governs from here, and removed members can neither
        ack a quorum nor win a ballot.  A leader promoted over a WAL
        holding an in-progress joint record calls this to finish the
        interrupted reconfig (server/election.py run_member)."""
        if self.old_voter_ids is None:
            raise ValueError('no reconfig in progress')
        self.old_voter_ids = None
        self.config_version += 1
        zxid = self.next_zxid()
        entry = ('reconfig', self.config_version, 'final', (),
                 self.voter_ids, self.observer_ids, zxid)
        # same append-time rule as propose_reconfig: C_new alone
        # governs the final record's own commit
        hook = self.on_config_change
        if hook is not None:
            hook('final', entry)
        self._commit(entry)
        self.reconfig_total += 1
        self.reconfig_epoch = self.epoch
        if self.trace is not None:
            self.trace.note('RECONFIG', zxid=zxid, kind='server',
                            detail='commit v%d voters=%s'
                            % (self.config_version,
                               ','.join(map(str, self.voter_ids))))
        return entry

    def attach_replica_at_tail(self, replica) -> int:
        """Attach a replica that is bootstrapped from a snapshot (the
        cross-process late join, server/replication.py): it needs no
        history before the current log tail — the tree image carries
        the effects of everything already committed, including
        transactions from before replication began that were never
        logged — so unlike :meth:`attach_replica` it may join at any
        time.  Returns the absolute log index the snapshot is current
        through (the joiner's starting ``applied``)."""
        if not self._replicas and not self.log:
            # the log starts recording at this attach: it is
            # contiguous only after the current position
            self.log_start_zxid = self.zxid
        self._replicas.append(replica)
        return self.log_end()

    def attach_replica_resync(self, replica, have_zxid: int
                              ) -> int | None:
        """Attach a follower that recovered its tree from disk at
        ``have_zxid`` (server/persist.py): when the retained log still
        covers that position, the follower needs only the tail — its
        recovered zxid is the catch-up base, no snapshot fetch.
        Returns the absolute log index to ship from, or None when the
        log no longer (or never) covers ``have_zxid`` and the caller
        must fall back to the snapshot bootstrap."""
        pos = self.index_after_zxid(have_zxid)
        if pos is None:
            return None
        # session control records carry the zxid current at their
        # edge: ones logged at exactly ``have_zxid`` AFTER the
        # rejoiner's last mirrored txn are invisible to the zxid
        # bisect — walk the position back over them (re-shipping a
        # session record the rejoiner did hold is idempotent)
        while pos > self.log_base:
            e = self.log[pos - 1 - self.log_base]
            if e[0] in ('session', 'session_close') \
                    and entry_zxid(e) == have_zxid:
                pos -= 1
            else:
                break
        self._replicas.append(replica)
        return pos

    def index_after_zxid(self, have_zxid: int) -> int | None:
        """Absolute log index of the first retained entry with zxid >
        ``have_zxid``; None when the retained log does not cover that
        position (truncated past it, never recorded, or the caller is
        ahead of this leader)."""
        if have_zxid < self.log_start_zxid or have_zxid > self.zxid:
            return None
        lo, hi = 0, len(self.log)
        while lo < hi:
            mid = (lo + hi) // 2
            if entry_zxid(self.log[mid]) <= have_zxid:
                lo = mid + 1
            else:
                hi = mid
        return self.log_base + lo

    #: Truncate the applied-everywhere log prefix in chunks (a del of
    #: a list prefix is O(surviving entries) — amortize it).
    LOG_TRUNC_CHUNK = 256

    def attach_replica(self, replica: 'ReplicaStore') -> None:
        """Called by :class:`ReplicaStore` — from here on, committed
        transactions are retained in ``log`` for replay.  Must happen
        before the first transaction: a replica cannot replay history
        that was never kept."""
        if self.zxid != 0:
            raise ValueError(
                'replica attached after %d transactions; the commit '
                'log only starts recording at attach' % (self.zxid,))
        self._replicas.append(replica)

    def log_end(self) -> int:
        """Absolute index one past the newest log entry."""
        return self.log_base + len(self.log)

    def recover_from_disk(self) -> None:
        """Rebuild this database's state from its WAL directory — the
        in-process analogue of a leader process dying and restarting
        (``ZKServer.restart(from_disk=True)``).  Sessions recovered
        LIVE from the WAL (durable session records + the snapshot's
        table) are re-seated with fresh expiry clocks — a client
        resuming inside the timeout keeps its session and its
        ephemerals; only dead sessions' ephemerals are reaped, by
        logged deletes.  Standalone/leader only: attached replicas
        hold live trees this reload would silently diverge from."""
        from .persist import (
            reap_orphan_ephemerals,
            recover_state,
            restore_sessions,
        )

        wal = self.wal
        assert wal is not None, 'recover_from_disk needs a WAL'
        assert not self._replicas, \
            'recover_from_disk is standalone/leader-rebuild only'
        wal.close()
        rec = recover_state(wal.dir)
        for sess in self.sessions.values():
            if sess.expiry_handle is not None:
                sess.expiry_handle.cancel()
                sess.expiry_handle = None
        self.sessions.clear()
        self.nodes = rec.nodes
        self.zxid = rec.zxid
        self.epoch = max(self.epoch, rec.epoch)
        if rec.config is not None:
            self.install_config(rec.config)
        self.log.clear()
        self.log_base = 0
        self.log_start_zxid = rec.zxid
        # the SAME WriteAheadLog object reopens: collector-bound
        # gauges/histograms and the fault injector stay live on it
        wal.reopen()
        restore_sessions(self, rec.sessions)
        reap_orphan_ephemerals(self)

    def _commit(self, entry: tuple) -> None:
        if self._multi_buf is not None:
            # speculative MULTI apply: held until the whole batch
            # commits (or rolls back) — nothing reaches the WAL, the
            # replication log or a trace ring from inside the batch
            self._multi_buf.append(entry)
            return
        if self.trace is not None \
                and entry[0] not in ('session', 'session_close',
                                     'reconfig'):
            # session control records are edges, not transactions:
            # they consume no zxid, so a COMMIT span would break the
            # zxid-keyed chain (and stamp zxid 0 on a fresh database);
            # reconfig records get their own RECONFIG span chain
            # (propose -> joint -> commit) instead
            if entry[0] == 'multi':
                self.trace.note('COMMIT', None,
                                zxid=entry_zxid(entry), kind='server',
                                detail='multi', batch=len(entry[1]))
            else:
                self.trace.note('COMMIT', entry[1],
                                zxid=entry_zxid(entry), kind='server',
                                detail=entry[0])
        # durability first: the WAL append precedes the 'committed'
        # emit (and therefore every replica push and — because the
        # handler corks the ack after this returns — every ack byte)
        if self.wal is not None:
            self.wal.append(entry)
        if self._replicas:
            self.log.append(entry)
            self.emit('committed')
            self._truncate_applied()
        else:
            # nothing attached: the entry is not retained, so the log
            # is only contiguous after this point (a stale prefix from
            # a detached replica era would otherwise read as coverage)
            if self.log:
                self.log_base += len(self.log)
                self.log.clear()
            self.log_start_zxid = self.zxid

    def _truncate_applied(self) -> None:
        """Drop the log prefix every attached replica has applied —
        those entries can never be replayed again (``applied`` only
        advances), so retaining them would grow a long-running
        ensemble's memory without bound."""
        floor = min(r.applied for r in self._replicas)
        if floor - self.log_base >= self.LOG_TRUNC_CHUNK:
            self.log_start_zxid = entry_zxid(
                self.log[floor - self.log_base - 1])
            del self.log[:floor - self.log_base]
            self.log_base = floor

    # -- session lifecycle --

    def create_session(self, timeout: int) -> ZKServerSession:
        self._next_session += 1
        sess = ZKServerSession(id=self._next_session,
                               passwd=secrets.token_bytes(16),
                               timeout=timeout)
        self.sessions[sess.id] = sess
        self.touch_session(sess)
        # durable sessions: the edge is a WAL control record AND a
        # replicated log entry (a follower's mirror must carry the
        # table so a promoted leader keeps every session).  It rides
        # the zxid current at the edge — consuming none — and
        # recovery replays it by log index (server/persist.py).
        self._commit(('session', sess.id, sess.passwd, sess.timeout,
                      self.zxid))
        log.debug('created session %016x timeout %d', sess.id, timeout)
        return sess

    def session_snapshot(self) -> dict:
        """The live session table in its durable form — what a fuzzy
        snapshot stamps (server/persist.py format 3)."""
        return durable_sessions(self.sessions)

    def resume_session(self, session_id: int,
                       passwd: bytes) -> ZKServerSession | None:
        sess = self.sessions.get(session_id)
        if sess is None or sess.expired or sess.closed:
            return None
        if sess.passwd != passwd:
            return None
        self.touch_session(sess)
        return sess

    def touch_session(self, sess: ZKServerSession) -> None:
        """Reset the session's expiry clock; called on every packet the
        ensemble sees from it."""
        if sess.expiry_handle is not None:
            sess.expiry_handle.cancel()
        loop = ambient_loop()
        sess.expiry_handle = loop.call_later(
            sess.timeout / 1000.0, lambda: self.expire_session(sess.id))

    def expire_session(self, session_id: int) -> None:
        sess = self.sessions.get(session_id)
        if sess is None or sess.expired or sess.closed:
            return
        sess.expired = True
        if sess.expiry_handle is not None:
            sess.expiry_handle.cancel()
            sess.expiry_handle = None
        log.info('session %016x expired', session_id)
        # the edge is logged BEFORE the ephemeral deletes it causes:
        # a crash between them recovers a dead session whose orphans
        # the recovery reap replays
        self._commit(('session_close', session_id, self.zxid,
                      'expire'))
        self._reap_ephemerals(sess)
        self.emit('sessionExpired', session_id)

    def close_session(self, session_id: int) -> None:
        sess = self.sessions.get(session_id)
        if sess is None or sess.closed:
            return
        sess.closed = True
        if sess.expiry_handle is not None:
            sess.expiry_handle.cancel()
            sess.expiry_handle = None
        log.debug('session %016x closed', session_id)
        self._commit(('session_close', session_id, self.zxid,
                      'close'))
        self._reap_ephemerals(sess)

    def _reap_ephemerals(self, sess: ZKServerSession) -> None:
        # Deepest-first so children go before parents.
        for path in sorted(sess.ephemerals, key=len, reverse=True):
            if path in self.nodes:
                try:
                    self.delete(path, -1)
                except ZKOpError:
                    log.warning('could not reap ephemeral %s', path)
        sess.ephemerals.clear()

    # -- znode writes (validate, sequence, apply, commit) --

    def create(self, path: str, data: bytes, acl, flags: CreateFlag,
               session: ZKServerSession | None = None) -> str:
        validate_path(path)
        if path == '/':
            raise ZKOpError('NODE_EXISTS')
        parent = self.nodes.get(parent_path(path))
        if parent is None:
            raise ZKOpError('NO_NODE')
        if parent.ephemeral_owner != 0:
            raise ZKOpError('NO_CHILDREN_FOR_EPHEMERALS')

        if flags & CreateFlag.SEQUENTIAL:
            path = '%s%010d' % (path, parent.seq)
            parent.seq += 1
        if path in self.nodes:
            raise ZKOpError('NODE_EXISTS')

        eph_owner = 0
        if flags & CreateFlag.EPHEMERAL:
            if session is None:
                raise ZKOpError('BAD_ARGUMENTS')
            eph_owner = session.id
            session.ephemerals.add(path)
        acl_t = tuple(acl) if acl else OPEN_ACL_UNSAFE
        zxid = self.next_zxid()
        now = self.now_ms()
        self._apply_create(path, data, acl_t, eph_owner, zxid, now)
        self._commit(('create', path, data, acl_t, eph_owner, zxid, now))
        return path

    def delete(self, path: str, version: int) -> None:
        validate_path(path)
        node = self.nodes.get(path)
        if node is None:
            raise ZKOpError('NO_NODE')
        if node.children:
            raise ZKOpError('NOT_EMPTY')
        if version >= 0 and version != node.version:
            raise ZKOpError('BAD_VERSION')

        zxid = self.next_zxid()
        node = self._apply_delete(path, zxid)
        if node.ephemeral_owner:
            sess = self.sessions.get(node.ephemeral_owner)
            if sess is not None:
                sess.ephemerals.discard(path)
        self._commit(('delete', path, zxid))

    def set_data(self, path: str, data: bytes, version: int) -> Stat:
        validate_path(path)
        node = self.nodes.get(path)
        if node is None:
            raise ZKOpError('NO_NODE')
        if version >= 0 and version != node.version:
            raise ZKOpError('BAD_VERSION')
        zxid = self.next_zxid()
        node = self._apply_set_data(path, data, zxid, self.now_ms())
        self._commit(('set_data', path, node.data, zxid, node.mtime))
        return node.stat()

    def check(self, path: str, version: int) -> None:
        """The CHECK sub-op (MULTI-only, like real ZK): version guard
        with no mutation and no log entry."""
        validate_path(path)
        node = self.nodes.get(path)
        if node is None:
            raise ZKOpError('NO_NODE')
        if version >= 0 and version != node.version:
            raise ZKOpError('BAD_VERSION')

    # -- MULTI: one all-or-nothing transaction ------------------------

    def multi(self, ops: list, session: ZKServerSession | None = None
              ) -> list:
        """Apply ``ops`` (sub-op dicts: create / delete / set_data /
        check) as ONE transaction: all of them commit as a single
        ('multi', subs) log entry — one WAL record, one group-fsync
        slot, one replication push element — or none of them touch
        the tree at all.

        The apply is speculative-with-undo rather than
        validate-then-apply: each sub-op runs through the exact
        single-op path (so validation can never diverge from it) with
        change events buffered and commits intercepted; the first
        failure rolls the applied prefix back — pre-copied nodes and
        parents restored in reverse order, zxid rewound, buffered
        events dropped — and every position reports an error result
        (the failing op its real code, the rest
        RUNTIME_INCONSISTENCY, real ZK's multi error shape).  On
        success the buffered events fire in apply order."""
        if not ops:
            return []
        start_zxid = self.zxid
        buf: list[tuple] = []
        events: list = []
        undo: list = []
        results: list = []
        failure: tuple[int, str] | None = None
        self._multi_buf = buf
        self._event_buf = events
        try:
            for op in ops:
                name = op.get('op')
                path = op.get('path', '')
                saved = (_copy_znode(self.nodes.get(path)),
                         _copy_znode(self.nodes.get(
                             parent_path(path) if path else '/')))
                n_before = len(buf)
                try:
                    if name == 'create':
                        made = self.create(
                            path, op.get('data', b''), op.get('acl'),
                            CreateFlag(op.get('flags', 0)), session)
                        results.append({'op': 'create', 'path': made})
                    elif name == 'delete':
                        self.delete(path, op.get('version', -1))
                        results.append({'op': 'delete'})
                    elif name == 'set_data':
                        stat = self.set_data(path, op['data'],
                                             op.get('version', -1))
                        results.append({'op': 'set_data',
                                        'stat': stat})
                    elif name == 'check':
                        self.check(path, op.get('version', -1))
                        results.append({'op': 'check'})
                    else:
                        raise ZKOpError('BAD_ARGUMENTS')
                except ZKOpError as e:
                    failure = (len(results), e.code)
                    break
                if len(buf) > n_before:
                    undo.append((buf[-1], saved))
        finally:
            self._multi_buf = None
            self._event_buf = None
        if failure is not None:
            self._rollback_multi(undo, start_zxid)
            idx, code = failure
            return [{'op': 'error',
                     'err': code if i == idx
                     else 'RUNTIME_INCONSISTENCY'}
                    for i in range(len(ops))]
        if buf:
            self.multi_batches += 1
            self.multi_subops += len(buf)
            self._commit(('multi', tuple(buf)))
            for ev, args in events:
                self.emit(ev, *args)
        return results

    def _rollback_multi(self, undo: list, start_zxid: int) -> None:
        """Reverse an applied MULTI prefix: each step restores the
        node/parent copies captured just before its sub-op, newest
        first, then the zxid rewinds — byte-identical to never having
        applied (no event fired, nothing logged)."""
        for entry, (node_copy, parent_copy) in reversed(undo):
            op = entry[0]
            path = entry[1]
            ppath = parent_path(path)
            if op == 'create':
                self.nodes.pop(path, None)
                if parent_copy is not None:
                    self.nodes[ppath] = parent_copy
                if entry[4]:
                    sess = self.sessions.get(entry[4])
                    if sess is not None:
                        sess.ephemerals.discard(path)
            elif op == 'delete':
                if node_copy is not None:
                    self.nodes[path] = node_copy
                    if node_copy.ephemeral_owner:
                        sess = self.sessions.get(
                            node_copy.ephemeral_owner)
                        if sess is not None:
                            sess.ephemerals.add(path)
                if parent_copy is not None:
                    self.nodes[ppath] = parent_copy
            else:
                assert op == 'set_data', op
                if node_copy is not None:
                    self.nodes[path] = node_copy
        self.zxid = start_zxid


class ReplicaStore(NodeTree):
    """One follower's local view of the tree, fed by the leader's
    commit log.

    ``lag`` controls replication delay:

    - ``0`` (default): apply synchronously at commit — a perfect
      network; every existing single-tick visibility expectation holds;
    - ``> 0``: apply each transaction ``lag`` seconds after commit —
      a follower that genuinely trails the leader;
    - ``None``: apply only on :meth:`catch_up` (the ``sync`` op or a
      write through this member) — a deterministically stale follower
      for tests.

    Watch locality falls out naturally: a server connection's watch
    tables subscribe to its member's store, so a watch on a lagging
    follower fires when THAT member applies the transaction, exactly
    like a real follower committing behind the leader.
    """

    def __init__(self, leader: ZKDatabase, lag: float | None = 0.0):
        super().__init__()
        self.leader = leader
        self.lag = lag
        #: ABSOLUTE index (leader.log_base frame) of the next entry to
        #: apply; only ever advances, which is what lets the leader
        #: truncate the applied-everywhere prefix
        self.applied = 0
        #: Serializes :meth:`_apply_until`: normally every apply runs
        #: on the member's event loop, but the cross-process replica's
        #: blocking control-channel RPCs are legitimately driven from
        #: another thread (run_in_executor — the sync barrier in the
        #: chaos campaign, test harnesses), and its piggyback triggers
        #: catch_up on THAT thread while an events-channel push can
        #: trigger it on the loop; an unguarded read-modify-write of
        #: ``applied`` would skip or double-apply an entry.
        self._apply_lock = threading.Lock()
        try:
            leader.attach_replica(self)
        except ValueError:
            # the leader already has history — e.g. it was recovered
            # from its WAL (server/persist.py) before this follower
            # existed: bootstrap from an image at the current
            # position, exactly like a cross-process late joiner.
            # The image is deep-copied (pickle roundtrip, same as the
            # wire would do): an in-process replica must not alias
            # the leader's live tree or lag would be unobservable.
            import pickle
            pos = leader.attach_replica_at_tail(self)
            self.install({'zxid': leader.zxid,
                          'nodes': pickle.loads(
                              pickle.dumps(leader.nodes))})
            self.applied = pos
        leader.on('committed', self._on_commit)

    @property
    def epoch(self) -> int:
        """The leadership epoch this replica's feed runs at — the
        leader's (or mirror's) accepted epoch; what a mirror WAL
        snapshot stamps (server/persist.py format 2)."""
        return getattr(self.leader, 'epoch', 0)

    def session_snapshot(self) -> dict:
        """The session table a mirror WAL snapshot stamps (format 3):
        the leader handle's — the shared database in process, the
        replicated mirror table cross-process — in durable form."""
        sessions = getattr(self.leader, 'sessions', None)
        return durable_sessions(sessions) if sessions else {}

    def _on_commit(self) -> None:
        if self.lag is None:
            return
        if self.lag <= 0:
            self._apply_until(self.leader.log_end())
        else:
            ambient_loop().call_later(
                self.lag, self._apply_until, self.leader.log_end())

    def _apply_until(self, target: int) -> None:
        """Apply log entries up to absolute index ``target``
        (idempotent: a timer firing after a ``catch_up`` already passed
        it is a no-op, so application order is always log order; the
        lock keeps that true when an off-loop control-channel thread
        races an on-loop events push — see ``_apply_lock``)."""
        ldr = self.leader
        with self._apply_lock:
            while self.applied < min(target, ldr.log_end()):
                self._apply_one(ldr.log[self.applied - ldr.log_base])
                self.applied += 1

    #: Optional quorum-commit ack hook (server/replication.py
    #: QuorumGate): called with this replica's zxid after every
    #: applied entry — the in-process ensemble's piggybacked
    #: applied-zxid vote.  Class-level None keeps the no-quorum hot
    #: path a single attribute test.
    on_applied = None

    def _apply_one(self, entry: tuple) -> None:
        self.apply_entry(entry)
        if self.trace is not None:
            self.trace.note('APPLY',
                            entry[1] if isinstance(entry[1], str)
                            else None,
                            zxid=entry_zxid(entry), kind='server',
                            detail=entry[0])
        cb = self.on_applied
        if cb is not None:
            cb(self.zxid)

    def catch_up(self) -> None:
        """Apply everything committed so far — what a write through
        this member does so its author can read their own write."""
        self._apply_until(self.leader.log_end())

    def detach(self) -> None:
        """Unhook from the leader's commit feed — the observer-leave
        half of a membership change (README "Dynamic membership"):
        no further entries are pushed to this replica, and its
        ``applied`` floor stops pinning the leader's log truncation.
        Idempotent."""
        ldr = self.leader
        ldr.remove_listener('committed', self._on_commit)
        try:
            ldr._replicas.remove(self)
        except ValueError:
            pass

    def sync_flush(self) -> None:
        """The ``sync`` op's barrier: for an in-process replica the
        leader's log IS the committed history, so this is
        ``catch_up``; the cross-process replica overrides it to fetch
        first (server/replication.py)."""
        self.catch_up()
