"""The in-process ZooKeeper server's data model.

The reference tests against a real ZooKeeper JVM spawned as a child
process (reference: test/zkserver.js) — unavailable here, so this module
implements the server-side semantics the client exercises: the znode
tree with full Stat bookkeeping, zxid allocation, session lifecycle with
expiry timers and ephemeral cleanup, sequential-node numbering, and
change events that per-connection watch tables subscribe to.

One ``ZKDatabase`` can back several listening servers at once, which is
how the 3-node-ensemble failover tests run without a real quorum: the
servers share committed state (as a ZAB quorum would) while sessions and
watches keep their real locality semantics — a watch lives on the
connection that set it; a session survives its server dying as long as
the client resumes it anywhere within the timeout.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import secrets
import time

from ..protocol.consts import CreateFlag
from ..protocol.records import ACL, OPEN_ACL_UNSAFE, Stat
from ..utils.events import EventEmitter
from ..utils.aio import ambient_loop

log = logging.getLogger('zkstream_tpu.server.store')


class ZKOpError(Exception):
    """A server-side operation failure, named by protocol error code."""

    def __init__(self, code: str):
        super().__init__(code)
        self.code = code


@dataclasses.dataclass
class Znode:
    data: bytes = b''
    acl: tuple = OPEN_ACL_UNSAFE
    czxid: int = 0
    mzxid: int = 0
    pzxid: int = 0
    ctime: int = 0
    mtime: int = 0
    version: int = 0
    cversion: int = 0
    aversion: int = 0
    ephemeral_owner: int = 0
    children: set = dataclasses.field(default_factory=set)
    #: Monotonic sequential-suffix counter (real ZK derives this from
    #: cversion; an explicit counter keeps numbering stable across
    #: deletes).
    seq: int = 0

    def stat(self) -> Stat:
        return Stat(czxid=self.czxid, mzxid=self.mzxid, ctime=self.ctime,
                    mtime=self.mtime, version=self.version,
                    cversion=self.cversion, aversion=self.aversion,
                    ephemeralOwner=self.ephemeral_owner,
                    dataLength=len(self.data),
                    numChildren=len(self.children), pzxid=self.pzxid)


@dataclasses.dataclass
class ZKServerSession:
    id: int
    passwd: bytes
    timeout: int
    ephemerals: set = dataclasses.field(default_factory=set)
    expired: bool = False
    closed: bool = False
    #: The server connection currently serving this session, if any.
    owner: object = None
    expiry_handle: asyncio.TimerHandle | None = None


def parent_path(path: str) -> str:
    idx = path.rfind('/')
    return path[:idx] if idx > 0 else '/'


def validate_path(path: str) -> None:
    if not path.startswith('/'):
        raise ZKOpError('BAD_ARGUMENTS')
    if path != '/' and path.endswith('/'):
        raise ZKOpError('BAD_ARGUMENTS')
    if '//' in path:
        raise ZKOpError('BAD_ARGUMENTS')


class ZKDatabase(EventEmitter):
    """Committed state shared by every server of a (simulated) ensemble.

    Change events (for watch tables): ``created(path, zxid)``,
    ``deleted(path, zxid)``, ``dataChanged(path, zxid)``,
    ``childrenChanged(path, zxid)``, ``sessionExpired(session_id)``.
    """

    def __init__(self) -> None:
        super().__init__()
        self.nodes: dict[str, Znode] = {'/': Znode()}
        self.zxid = 0
        self.sessions: dict[int, ZKServerSession] = {}
        # Like real ZK's (timestamp << 24) seed, masked into int64 range.
        self._next_session = ((int(time.time() * 1000) << 24)
                              & 0x7fffffffffff0000)

    # -- zxid / time --

    def next_zxid(self) -> int:
        self.zxid += 1
        return self.zxid

    @staticmethod
    def now_ms() -> int:
        return int(time.time() * 1000)

    # -- session lifecycle --

    def create_session(self, timeout: int) -> ZKServerSession:
        self._next_session += 1
        sess = ZKServerSession(id=self._next_session,
                               passwd=secrets.token_bytes(16),
                               timeout=timeout)
        self.sessions[sess.id] = sess
        self.touch_session(sess)
        log.debug('created session %016x timeout %d', sess.id, timeout)
        return sess

    def resume_session(self, session_id: int,
                       passwd: bytes) -> ZKServerSession | None:
        sess = self.sessions.get(session_id)
        if sess is None or sess.expired or sess.closed:
            return None
        if sess.passwd != passwd:
            return None
        self.touch_session(sess)
        return sess

    def touch_session(self, sess: ZKServerSession) -> None:
        """Reset the session's expiry clock; called on every packet the
        ensemble sees from it."""
        if sess.expiry_handle is not None:
            sess.expiry_handle.cancel()
        loop = ambient_loop()
        sess.expiry_handle = loop.call_later(
            sess.timeout / 1000.0, lambda: self.expire_session(sess.id))

    def expire_session(self, session_id: int) -> None:
        sess = self.sessions.get(session_id)
        if sess is None or sess.expired or sess.closed:
            return
        sess.expired = True
        if sess.expiry_handle is not None:
            sess.expiry_handle.cancel()
            sess.expiry_handle = None
        log.info('session %016x expired', session_id)
        self._reap_ephemerals(sess)
        self.emit('sessionExpired', session_id)

    def close_session(self, session_id: int) -> None:
        sess = self.sessions.get(session_id)
        if sess is None or sess.closed:
            return
        sess.closed = True
        if sess.expiry_handle is not None:
            sess.expiry_handle.cancel()
            sess.expiry_handle = None
        log.debug('session %016x closed', session_id)
        self._reap_ephemerals(sess)

    def _reap_ephemerals(self, sess: ZKServerSession) -> None:
        # Deepest-first so children go before parents.
        for path in sorted(sess.ephemerals, key=len, reverse=True):
            if path in self.nodes:
                try:
                    self.delete(path, -1)
                except ZKOpError:
                    log.warning('could not reap ephemeral %s', path)
        sess.ephemerals.clear()

    # -- znode operations --

    def create(self, path: str, data: bytes, acl, flags: CreateFlag,
               session: ZKServerSession | None = None) -> str:
        validate_path(path)
        if path == '/':
            raise ZKOpError('NODE_EXISTS')
        parent = self.nodes.get(parent_path(path))
        if parent is None:
            raise ZKOpError('NO_NODE')
        if parent.ephemeral_owner != 0:
            raise ZKOpError('NO_CHILDREN_FOR_EPHEMERALS')

        if flags & CreateFlag.SEQUENTIAL:
            path = '%s%010d' % (path, parent.seq)
            parent.seq += 1
        if path in self.nodes:
            raise ZKOpError('NODE_EXISTS')

        zxid = self.next_zxid()
        now = self.now_ms()
        node = Znode(data=data, acl=tuple(acl) if acl else OPEN_ACL_UNSAFE,
                     czxid=zxid, mzxid=zxid, pzxid=zxid,
                     ctime=now, mtime=now)
        if flags & CreateFlag.EPHEMERAL:
            if session is None:
                raise ZKOpError('BAD_ARGUMENTS')
            node.ephemeral_owner = session.id
            session.ephemerals.add(path)
        self.nodes[path] = node
        parent.children.add(path.rsplit('/', 1)[1])
        parent.cversion += 1
        parent.pzxid = zxid

        self.emit('created', path, zxid)
        self.emit('childrenChanged', parent_path(path), zxid)
        return path

    def delete(self, path: str, version: int) -> None:
        validate_path(path)
        node = self.nodes.get(path)
        if node is None:
            raise ZKOpError('NO_NODE')
        if node.children:
            raise ZKOpError('NOT_EMPTY')
        if version >= 0 and version != node.version:
            raise ZKOpError('BAD_VERSION')

        zxid = self.next_zxid()
        del self.nodes[path]
        ppath = parent_path(path)
        parent = self.nodes.get(ppath)
        if parent is not None:
            parent.children.discard(path.rsplit('/', 1)[1])
            parent.cversion += 1
            parent.pzxid = zxid
        if node.ephemeral_owner:
            sess = self.sessions.get(node.ephemeral_owner)
            if sess is not None:
                sess.ephemerals.discard(path)

        self.emit('deleted', path, zxid)
        self.emit('childrenChanged', ppath, zxid)

    def set_data(self, path: str, data: bytes, version: int) -> Stat:
        validate_path(path)
        node = self.nodes.get(path)
        if node is None:
            raise ZKOpError('NO_NODE')
        if version >= 0 and version != node.version:
            raise ZKOpError('BAD_VERSION')
        zxid = self.next_zxid()
        node.data = data
        node.version += 1
        node.mzxid = zxid
        node.mtime = self.now_ms()
        self.emit('dataChanged', path, zxid)
        return node.stat()

    def get_data(self, path: str) -> tuple[bytes, Stat]:
        node = self.nodes.get(path)
        if node is None:
            raise ZKOpError('NO_NODE')
        return node.data, node.stat()

    def exists(self, path: str) -> Stat:
        node = self.nodes.get(path)
        if node is None:
            raise ZKOpError('NO_NODE')
        return node.stat()

    def get_children(self, path: str) -> tuple[list[str], Stat]:
        node = self.nodes.get(path)
        if node is None:
            raise ZKOpError('NO_NODE')
        return sorted(node.children), node.stat()

    def get_acl(self, path: str) -> tuple[list[ACL], Stat]:
        node = self.nodes.get(path)
        if node is None:
            raise ZKOpError('NO_NODE')
        return list(node.acl), node.stat()
