"""The in-process asyncio ZooKeeper server.

Speaks the same wire protocol as the client through the symmetric
``PacketCodec(server=True)`` — the capability the reference's stream
codec advertises for building fake test servers
(reference: lib/zk-streams.js:28,70-71,84-85) but cannot actually
deliver (its reply encoder is missing).  This one is complete enough to
run the whole client test suite against: handshake with session
create/resume, the full request set, one-shot server-side watches with
correct locality, SET_WATCHES catch-up by relZxid, and session
migration between ensemble members.

``ZKEnsemble`` runs N servers on localhost as a simulated quorum: one
leader ``ZKDatabase`` sequences every write into a commit log, and each
follower serves reads/watches from its own ``ReplicaStore`` replaying
that log with injectable lag — so followers can genuinely trail the
leader, stale reads are possible, and the ``sync`` op has observable
meaning (see store.py).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time

from ..protocol.consts import MAX_PACKET, XID_NOTIFICATION, CreateFlag
from ..protocol.errors import ZKFrameTooLargeError, ZKProtocolError
from ..io.ingress import METRIC_RECV_SYSCALLS, make_plane, \
    rx_buf_default
from ..io.overload import OverloadConfig, OverloadPlane, \
    overload_enabled
from ..io.sendplane import SendPlane
from ..protocol.framing import PacketCodec, resolve_frame_cap
from ..utils.aio import set_nodelay
from ..utils.metrics import TickLedger
from ..utils.trace import TRACE_SCHEMA, TraceRing, server_trace_default
from .store import ReplicaStore, ZKDatabase, ZKOpError, ZKServerSession
from .watchtable import WatchTable, watchtable_default

log = logging.getLogger('zkstream_tpu.server')

#: ZooKeeper four-letter admin words this server answers (raw bytes,
#: no length prefix, sent as a connection's very first payload).
#: ``trce`` is this stack's own: the member's span ring as JSON
#: (trace_schema-stamped), so ``timeline --live`` can merge rings
#: scraped from OS-process members.
ADMIN_WORDS = frozenset((b'ruok', b'mntr', b'stat', b'srvr', b'trce'))

#: The dynamic-membership admin channel (README "Dynamic membership"):
#: ``rcfg <action> [args]\n`` — four-letter-word framing (raw bytes as
#: the connection's first payload) but argument-bearing, so the word
#: buffers through its newline before dispatch.  Leader-only; replies
#: one text line and closes, mntr-style.
RECONFIG_WORD = b'rcfg'

METRIC_RECONFIG = 'zookeeper_reconfig_ms'
RECONFIG_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                    100.0, 250.0, 1000.0)


def _csv(members) -> str:
    """Member-id list as the admin/mntr text form ('-' when empty)."""
    return ','.join(str(m) for m in members) or '-'


def _parse_members(s: str) -> tuple:
    """Inverse of :func:`_csv` for ``rcfg`` argument lists."""
    if s == '-':
        return ()
    return tuple(int(x) for x in s.split(',') if x != '')


def _config_desc(voters, old_voters, observers, phase) -> str:
    """The one-line member inventory ``zk_config_members`` carries."""
    desc = 'voters=%s' % (_csv(voters),)
    if old_voters:
        desc += ' old_voters=%s' % (_csv(old_voters),)
    if observers:
        desc += ' observers=%s' % (_csv(observers),)
    return desc + ' phase=%s' % (phase,)

#: Member span-ring capacity: deep enough to hold a campaign's recent
#: window (decode + per-txn chain + fan-out), fixed memory.
MEMBER_RING_CAPACITY = 512

# ---------------------------------------------------------------------
# The zxid read gate: session-consistent reads off non-leader members.
# ---------------------------------------------------------------------

METRIC_READ_GATE_WAIT = 'zookeeper_read_gate_wait_ms'
READ_GATE_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                     25.0, 50.0, 100.0, 250.0)

#: How long a gated read may block waiting for this member to apply
#: the session's floor before it BOUNCES (a typed CONNECTION_LOSS the
#: client retries on a fresher member) — the read plane's analogue of
#: the quorum gate's degrade window: a parked replica must delay
#: reads, never wedge them (``ZKSTREAM_READ_GATE_WAIT_MS``).
DEFAULT_READ_GATE_WAIT_MS = 100.0


def read_gate_enabled() -> bool:
    """Global kill switch (``ZKSTREAM_NO_READ_GATE=1``): the ungated
    read path stays available as the env-gated validator arm — the
    one ``analysis/linearize.py check_session_reads`` exists to
    catch."""
    return os.environ.get('ZKSTREAM_NO_READ_GATE') != '1'


def read_gate_wait_ms() -> float:
    try:
        v = float(os.environ.get('ZKSTREAM_READ_GATE_WAIT_MS', ''))
    except ValueError:
        return DEFAULT_READ_GATE_WAIT_MS
    return v if v > 0 else DEFAULT_READ_GATE_WAIT_MS


def observers_default() -> int:
    """Default observer count for a new ``ZKEnsemble``
    (``ZKSTREAM_OBSERVERS``)."""
    try:
        n = int(os.environ.get('ZKSTREAM_OBSERVERS', ''))
    except ValueError:
        return 0
    return max(0, n)


class ReadGate:
    """Session-consistent follower/observer reads (README "Read
    plane"): a read must never show a session state OLDER than what
    the session has already observed.  Every reply header stamps the
    serving member's applied zxid into ``session.last_zxid`` (the
    handshake's ``lastZxidSeen`` seeds it), and a read arriving at a
    member whose store trails that floor parks here — re-dispatched
    the moment the member's replica applies through the floor, or
    bounced with a typed CONNECTION_LOSS after ``wait_ms`` so the
    client can retry on a fresher member.  Leader-view members
    (``store is db``) are always current and never gate.

    Observability: ``zk_read_zxid_gate_blocks`` / ``_bounces`` mntr
    rows, the ``zookeeper_read_gate_wait_ms`` histogram, and a
    READ_GATE span per gated read in the member's trace ring."""

    def __init__(self, server: 'ZKServer', collector=None,
                 wait_ms: float | None = None):
        self.server = server
        self.wait_ms = (wait_ms if wait_ms is not None
                        else read_gate_wait_ms())
        self.blocks = 0
        self.bounces = 0
        #: parked reads: [floor, conn, pkt, t0, timer_handle]
        self._pending: list = []
        self._store = None
        self._hist = None
        if collector is not None:
            self._hist = collector.histogram(
                METRIC_READ_GATE_WAIT,
                'Zxid read-gate wait before serve or bounce, ms',
                buckets=READ_GATE_BUCKETS)

    def defer(self, conn, pkt: dict, floor: int) -> None:
        """Park one read whose serving member trails the session
        floor.  The store-event subscription (one listener set per
        member, armed lazily) re-dispatches it when the replica
        applies through the floor; the timer bounds the wait."""
        self.blocks += 1
        self._subscribe()
        from ..utils.aio import ambient_loop
        entry = [floor, conn, pkt, time.perf_counter(), None]
        entry[4] = ambient_loop().call_later(
            self.wait_ms / 1000.0, self._bounce, entry)
        self._pending.append(entry)

    # -- store following (survives repoint) --

    def _subscribe(self) -> None:
        store = self.server.store
        if self._store is store:
            return
        self._unsubscribe()
        self._store = store
        for ev in ('created', 'deleted', 'dataChanged',
                   'childrenChanged'):
            store.on(ev, self._on_store_event)

    def _unsubscribe(self) -> None:
        if self._store is None:
            return
        for ev in ('created', 'deleted', 'dataChanged',
                   'childrenChanged'):
            self._store.remove_listener(ev, self._on_store_event)
        self._store = None

    def _on_store_event(self, _path, _zxid) -> None:
        if self._pending:
            self._drain()

    def _settle(self, entry, *, bounced: bool) -> None:
        floor, conn, pkt, t0, timer = entry
        if timer is not None:
            timer.cancel()
        dur_ms = (time.perf_counter() - t0) * 1000.0
        if self._hist is not None:
            self._hist.observe(dur_ms)
        trace = self.server.trace
        if trace is not None:
            trace.note('READ_GATE', pkt.get('path'), zxid=floor,
                       kind='server',
                       detail='bounce' if bounced else 'block',
                       duration_ms=round(dur_ms, 3))

    def _drain(self) -> None:
        """Re-dispatch every parked read the member has caught up
        past, in arrival order (runs inside the store's apply, the
        same dispatch point as watch fan-out)."""
        z = self.server.store.zxid
        ready = [e for e in self._pending if e[0] <= z]
        if not ready:
            return
        self._pending = [e for e in self._pending if e[0] > z]
        for entry in ready:
            self._settle(entry, bounced=False)
            conn, pkt = entry[1], entry[2]
            if conn.closed:
                continue
            conn._handle_request(pkt)

    def _bounce(self, entry) -> None:
        """The bounded wait expired with the member still behind: a
        typed CONNECTION_LOSS reply — outcome-unknown to the client's
        ambiguity accounting, retryable on a fresher member — never a
        stale payload."""
        if entry not in self._pending:
            return
        self._pending.remove(entry)
        entry[4] = None              # the timer IS this callback
        self.bounces += 1
        self._settle(entry, bounced=True)
        conn, pkt = entry[1], entry[2]
        if not conn.closed:
            conn._reply(pkt['xid'], pkt['opcode'],
                        err='CONNECTION_LOSS')

    def reset(self) -> None:
        """Drop every parked read (repoint/stop: the connections are
        being closed; their sessions re-dial and retry)."""
        pending, self._pending = self._pending, []
        for entry in pending:
            if entry[4] is not None:
                entry[4].cancel()
        self._unsubscribe()


class ServerConnection:
    """One accepted client socket: handshake, request dispatch, and this
    connection's watch tables."""

    def __init__(self, server: 'ZKServer', reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.server = server
        self.db = server.db          # the leader: writes + sessions
        self.store = server.store    # this member's view: reads + watches
        self.reader = reader
        self.writer = writer
        self.codec = PacketCodec(server=True,
                                 max_frame=server.max_frame)
        self.session: ZKServerSession | None = None
        #: One-shot watch tables, local to this connection (they die
        #: with the server, exactly like real ZK's).  With the server's
        #: WatchTable enabled (the default) these dicts are the
        #: per-connection view of the same registrations the table's
        #: reverse index holds — always mutate both through the
        #: ``_arm_*``/``_disarm_*`` helpers.
        self.data_watches: dict[str, bool] = {}
        self.child_watches: dict[str, bool] = {}
        #: Persistent watches (ADD_WATCH, opcode 106): path -> True
        #: when the subscription is PERSISTENT_RECURSIVE.  These
        #: survive fires — nothing in the dispatch path pops them —
        #: and mirror the WatchTable's persistent/recursive reverse
        #: indexes exactly like the one-shot dicts above.
        self.persistent_watches: dict[str, bool] = {}
        self.closed = False
        self._subscribed = False
        #: Sharded fan-out state (server/watchtable.py): notifications
        #: buffered for this connection within the current tick, and
        #: the shard this connection drains through.
        self._fanout_buf: list[bytes] = []
        self._fanout_shard = 0
        #: First-bytes buffer for four-letter admin word detection: a
        #: real ZK handshake starts with a 4-byte big-endian length
        #: (0x00 0x00 0x00 0x2c-ish), which can never collide with an
        #: ASCII admin word, so the first four bytes decide the
        #: connection's fate exactly once.
        self._admin_buf = b''
        self._admin_checked = False
        #: Sharded-ingress state (io/ingress.py): the owning plane
        #: (None on the single-loop validator path), this
        #: connection's accept shard — the affinity key its watch
        #: fan-out shard reuses — and the raw fd + dirty flag the
        #: shard's batched receive drain keys on.
        self._ingress = None
        self._ingress_shard: int | None = None
        self._rx_fd = -1
        self._rx_dirty = False
        self._rx_skip = False
        #: Overload-plane state (io/overload.py): rx paused (reader
        #: removed / validator loop parked) after an inflight storm,
        #: the validator's resume event, the notification-drop
        #: episode marker, and the eviction reason (None = never
        #: evicted).
        self._rx_paused = False
        self._rx_resume: asyncio.Event | None = None
        self._notif_dropping = False
        self.evicted: str | None = None
        #: Outbound cork (io/sendplane.py): replies and notifications
        #: of one event-loop tick leave as a single writer.write (a
        #: pipelined request batch is answered with one segment) —
        #: or, when the server carries a batched transport tier
        #: (io/transport.py), as this connection's slice of the
        #: tick's ONE batched submission across every dirty
        #: connection.  When the leader database carries a WAL, the
        #: plane gates on it: corked acks wait (in order) for the
        #: off-loop group fsync covering their txns, so no ack byte
        #: reaches the transport before its txn is on disk and the
        #: event loop never blocks on the device (server/persist.py
        #: sync='tick').  With a quorum gate attached the barrier is
        #: the CommitBarrier composition: the same corked tick also
        #: waits for a majority of mirrors to hold the txns — one
        #: wait covers both halves (server/replication.py).
        self._tx = SendPlane(self._tx_write, enabled=server.cork,
                             max_bytes=server.flush_cap,
                             collector=server.collector, plane='server',
                             barrier=server.ack_barrier,
                             ledger=server.ledger,
                             tier=server.transport_tier,
                             transport_fn=lambda: getattr(
                                 self.writer, 'transport', None))

    @property
    def session_id(self):
        """This connection's session id, None before the handshake —
        what OVERLOAD trace spans name the victim by."""
        sess = self.session
        return sess.id if sess is not None else None

    # -- wire helpers --

    def _tx_write(self, data: bytes) -> None:
        try:
            self.writer.write(data)
        except (ConnectionError, RuntimeError):
            pass

    def _write_bytes(self, data: bytes) -> None:
        if self.closed:
            return
        # notifications buffered by the watch table this tick must
        # leave before any later reply: the wire never shows a reply
        # overtaking an earlier notification (ZooKeeper's watch-
        # before-read-result guarantee)
        if self._fanout_buf:
            self._drain_fanout()
        fi = self.server.faults
        if fi is not None and fi.server_tx(self, data,
                                           pre=self._tx.flush_hard):
            return   # the injector took over delivery (split/delay/RST)
        self._tx.send(data)

    def _drain_fanout(self) -> None:
        """Move this connection's buffered (already fault-screened)
        notifications into the send plane, joined, in event order."""
        buf = self._fanout_buf
        if not buf:
            return
        data = buf[0] if len(buf) == 1 else b''.join(buf)
        buf.clear()          # the list object is reused across ticks
        self._tx.send(data)

    def _preflush_fanout(self) -> None:
        """Fault-injection pre-flush: everything this connection has
        corked — buffered notifications AND the plane — hits the wire
        before an injected delivery, so a faulted frame cannot
        reorder (the send plane's boundary rule)."""
        self._drain_fanout()
        self._tx.flush_hard()

    def _send(self, pkt: dict) -> None:
        if self.closed:
            return
        self.server.packets_sent += 1
        self._write_bytes(self.codec.encode(pkt))

    def _reply(self, xid: int, opcode: str, err: str = 'OK',
               **body) -> None:
        if self.server.drop_replies:
            return
        if self.server.drop_pings and opcode == 'PING':
            return
        # the header zxid is this MEMBER's last applied transaction —
        # a lagging follower honestly reports its own position
        z = self.store.zxid
        sess = self.session
        if sess is not None and z > sess.last_zxid:
            # the session has now SEEN this member state: the zxid
            # read gate's floor (ReadGate) advances with every reply
            sess.last_zxid = z
        pkt = {'xid': xid, 'zxid': z, 'err': err, 'opcode': opcode}
        pkt.update(body)
        self._send(pkt)

    def notify(self, ntype: str, path: str, zxid: int,
               persistent: bool = False) -> None:
        """Send one watch notification directly (the SET_WATCHES
        catch-up path; event-driven fan-out goes through the server's
        WatchTable instead).  The bytes come from the server-owned
        encode cache/memo, shared across subscribers.

        ``persistent=True`` applies the persistent-subscriber
        overload contract: the soft watermark EVICTS instead of
        dropping (a silent gap would wedge a watch-backed cache
        stale — io/overload.py ``allow_persistent_notification``)."""
        if self.closed:
            return
        ov = self.server.overload
        if ov is not None:
            # soft tx watermark: a stalled one-shot subscriber loses
            # watch notifications (the legally lossy channel) before
            # it can bloat the member; a stalled PERSISTENT
            # subscriber is evicted instead — never a silent gap;
            # the hard watermark evicts either outright
            if persistent:
                if not ov.allow_persistent_notification(self):
                    return
            elif not ov.allow_notification(self):
                return
            if ov.check_tx(self):
                return
        self.server.packets_sent += 1
        self._write_bytes(
            self.server.encode_notification(ntype, path, zxid))

    # -- watch dispatch (store change events -> this connection) --

    def _subscribe(self) -> None:
        if self._subscribed:
            return
        self._subscribed = True
        if self.server.watch_table is not None:
            # table mode (default): the server's one listener set per
            # store consults the reverse index; this connection only
            # joins a fan-out shard
            self.server.watch_table.add_conn(self)
            return
        # emitter fallback (ZKSTREAM_NO_WATCHTABLE=1): per-connection
        # store listeners, each event filtered against this
        # connection's own dicts — the validator path.  Node-change
        # events come from THIS member's store (a watch on a lagging
        # follower fires when the follower applies the transaction).
        self.store.on('created', self._on_created)
        self.store.on('deleted', self._on_deleted)
        self.store.on('dataChanged', self._on_data_changed)
        self.store.on('childrenChanged', self._on_children_changed)

    def _unsubscribe(self) -> None:
        if not self._subscribed:
            return
        self._subscribed = False
        if self.server.watch_table is not None:
            self.server.watch_table.remove_conn(self)
            return
        self.store.remove_listener('created', self._on_created)
        self.store.remove_listener('deleted', self._on_deleted)
        self.store.remove_listener('dataChanged', self._on_data_changed)
        self.store.remove_listener('childrenChanged',
                                   self._on_children_changed)

    def _on_created(self, path: str, zxid: int) -> None:
        if self.data_watches.pop(path, None):
            self.notify('CREATED', path, zxid)
        if self._persistent_hit(path, False):
            self.notify('CREATED', path, zxid, persistent=True)

    def _on_deleted(self, path: str, zxid: int) -> None:
        if self.data_watches.pop(path, None):
            self.notify('DELETED', path, zxid)
        if self.child_watches.pop(path, None):
            self.notify('DELETED', path, zxid)
        if self._persistent_hit(path, False):
            self.notify('DELETED', path, zxid, persistent=True)

    def _on_data_changed(self, path: str, zxid: int) -> None:
        if self.data_watches.pop(path, None):
            self.notify('DATA_CHANGED', path, zxid)
        if self._persistent_hit(path, False):
            self.notify('DATA_CHANGED', path, zxid, persistent=True)

    def _on_children_changed(self, path: str, zxid: int) -> None:
        if self.child_watches.pop(path, None):
            self.notify('CHILDREN_CHANGED', path, zxid)
        # recursive subscribers never get CHILDREN_CHANGED: they see
        # the child's own CREATED/DELETED instead (upstream semantics)
        if self._persistent_hit(path, True):
            self.notify('CHILDREN_CHANGED', path, zxid, persistent=True)

    def _persistent_hit(self, path: str, exact_only: bool) -> bool:
        """Emitter-fallback persistent-watch match: True when this
        connection holds a persistent watch on ``path`` itself, or —
        unless ``exact_only`` — a PERSISTENT_RECURSIVE watch on any
        ancestor.  Never consumes: the subscription survives fires."""
        pw = self.persistent_watches
        if not pw:
            return False
        if path in pw:
            if exact_only:
                # CHILDREN_CHANGED goes only to exact PERSISTENT
                # subscriptions, not recursive ones
                return not pw[path]
            return True
        if exact_only:
            return False
        p = path
        while len(p) > 1:
            i = p.rfind('/')
            p = p[:i] if i > 0 else '/'
            if pw.get(p):
                return True
        return False

    # -- watch arming (both paths: connection dict + table index) --

    def _arm_data(self, path: str) -> None:
        if path not in self.data_watches:
            self.data_watches[path] = True
            if self.server.watch_table is not None:
                self.server.watch_table.arm('data', path, self)

    def _arm_child(self, path: str) -> None:
        if path not in self.child_watches:
            self.child_watches[path] = True
            if self.server.watch_table is not None:
                self.server.watch_table.arm('child', path, self)

    def _disarm_data(self, path: str) -> None:
        if self.data_watches.pop(path, None):
            if self.server.watch_table is not None:
                self.server.watch_table.disarm('data', path, self)

    def _disarm_child(self, path: str) -> None:
        if self.child_watches.pop(path, None):
            if self.server.watch_table is not None:
                self.server.watch_table.disarm('child', path, self)

    def _arm_persistent(self, path: str, recursive: bool) -> None:
        prev = self.persistent_watches.get(path)
        if prev is recursive:
            return
        if prev is not None:
            # mode change (PERSISTENT <-> PERSISTENT_RECURSIVE):
            # re-home the subscription in the other reverse index
            self._disarm_persistent(path)
        self.persistent_watches[path] = recursive
        if self.server.watch_table is not None:
            self.server.watch_table.arm_persistent(path, self, recursive)

    def _disarm_persistent(self, path: str) -> None:
        recursive = self.persistent_watches.pop(path, None)
        if recursive is not None:
            if self.server.watch_table is not None:
                self.server.watch_table.disarm_persistent(
                    path, self, recursive)

    # -- lifecycle --

    async def run(self) -> None:
        """The single-loop validator's receive pump (the sharded
        ingress plane never calls this — its per-shard batched drain
        feeds :meth:`feed` directly)."""
        rx_buf = self.server.rx_buf
        ctr = self.server._recv_ctr
        labels = self.server._recv_labels
        try:
            while not self.closed:
                if self._rx_paused:
                    # inflight throttle (io/overload.py): park the
                    # pump instead of reading — the kernel buffer
                    # fills and TCP pushes back on the client
                    gate = self._rx_resume = asyncio.Event()
                    await gate.wait()
                    self._rx_resume = None
                    continue
                data = await self.reader.read(rx_buf)
                if not data:
                    break
                if ctr is not None:
                    # the rx-direction syscall accounting's validator
                    # arm: one wakeup, one read per connection
                    ctr.increment(labels)
                if not self.feed(data):
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            self.close()

    def feed(self, data: bytes) -> bool:
        """Decode + dispatch one received chunk (any byte offset: the
        codec accumulates partial frames).  Both receive paths end
        here — the validator's ``read()`` loop above and the ingress
        plane's batched drain.  Returns False when the connection is
        done (admin word served, undecodable input).

        Fault injection happens HERE, per connection-chunk, BEFORE
        any decode — the receive-side mirror of the send plane's
        before-the-cork rule: an injected split/delay/reset perturbs
        this connection's stream identically on every rx backend."""
        fi = self.server.faults
        if fi is not None and fi.server_rx(self, data):
            return True   # the injector took over delivery
        return self._feed(data)

    def _feed(self, data: bytes) -> bool:
        """The injector-free half of :meth:`feed` (fault gates
        deliver their delayed segments through this, so a faulted
        chunk is never re-screened)."""
        if not self._admin_checked:
            # ZooKeeper four-letter words arrive raw (no length
            # prefix) as the connection's first bytes.
            self._admin_buf += data
            if len(self._admin_buf) < 4:
                return True
            self._admin_checked = True
            word = self._admin_buf[:4]
            if word == RECONFIG_WORD:
                # argument-bearing admin word: keep buffering until
                # the line's newline arrives (re-arming the check so
                # the next chunk lands back here)
                if b'\n' not in self._admin_buf:
                    self._admin_checked = False
                    return True
                line = self._admin_buf.split(b'\n', 1)[0]
                self._handle_reconfig(
                    line[4:].decode('utf-8', 'replace').strip())
                # keep the connection open: unlike the synchronous
                # words, the reply may await a quorum — the handler
                # task writes it and closes
                return True
            if word in ADMIN_WORDS:
                self._handle_admin(word.decode('ascii'))
                return False
            # not an admin word: replay everything buffered
            # through the normal codec path
            data, self._admin_buf = self._admin_buf, b''
        # the tick ledger's decode_apply phase covers the
        # whole decode + dispatch burst (store apply and WAL
        # append included; nested sync/flush phases subtract)
        ledger = self.server.ledger
        if ledger is not None:
            ledger.enter('decode_apply')
        try:
            try:
                pkts = self.codec.decode(data)
            except ZKFrameTooLargeError as e:
                # the jute.maxbuffer analogue: the length prefix is
                # rejected BEFORE the frame buffers; the close is a
                # traced, typed eviction, not a silent drop
                ov = self.server.overload
                if ov is not None:
                    ov.evict(self, 'frame_too_large',
                             buffered=e.length)
                else:
                    log.debug('server: oversized frame: %s', e)
                return False
            except ZKProtocolError as e:
                log.debug('server: undecodable input: %s', e)
                return False
            ov = self.server.overload
            if ov is not None and pkts:
                # an inflight storm — one drain decoding a whole
                # pipelined burst — pauses this connection's rx
                ov.after_drain(self, len(pkts))
            trace = self.server.trace
            if trace is not None and pkts and not (
                    len(pkts) == 1
                    and pkts[0].get('opcode') == 'PING'):
                # bare keepalive pings skip the ring: at fleet
                # scale they are most batches, and recording
                # them would wash the txn chains out of the
                # bounded window (and cost a span per ping)
                trace.note('SRV_DECODE', kind='server',
                           batch=len(pkts), nbytes=len(data))
            # Outstanding accounting is batch-scoped: a
            # pipelined read delivers N requests at once, and
            # every one is outstanding until its handler
            # replies.  (Handlers are synchronous today, so a
            # concurrent mntr scrape observes nonzero only
            # across a handler that awaits — e.g. via an
            # injected fault gate — but the accounting stays
            # correct if handlers ever grow await points.)
            self.server.outstanding += len(pkts)
            remaining = len(pkts)
            try:
                for pkt in pkts:
                    self.server.packets_received += 1
                    if self.codec.handshaking:
                        self._handle_connect(pkt)
                    else:
                        self._handle_request(pkt)
                    self.server.outstanding -= 1
                    remaining -= 1
                    if self.closed:
                        break
            finally:
                # a close/raise mid-batch must still retire
                # the unhandled remainder from the gauge
                self.server.outstanding -= remaining
        finally:
            if ledger is not None:
                ledger.exit()
        ov = self.server.overload
        if ov is not None and not self.closed:
            # the validator twin of the ingress drain's hard-watermark
            # boundary (io/ingress.py): a reply backlog that outgrew
            # ZKSTREAM_TX_HARD evicts here too — a pipelined reader
            # that stops draining must not bloat the member just
            # because this server runs without the sharded ingress
            if ov.check_tx(self):
                return False
        return True

    def _handle_admin(self, word: str) -> None:
        """Serve one four-letter admin word: raw text reply, then
        close — real ZK's mntr/ruok/stat/srvr contract.  Synchronous:
        ``transport.close`` flushes the buffered reply before the FIN
        on both receive paths."""
        text = self.server.admin_text(word)
        try:
            self.writer.write(text.encode('utf-8'))
        except (ConnectionError, RuntimeError):
            pass
        self.close()

    def _handle_reconfig(self, args: str) -> None:
        """Serve one ``rcfg`` admin line.  Unlike the synchronous
        four-letter words, ``apply`` awaits the joint-quorum commit —
        so the handler runs as a task; reply text, then close."""
        async def _run() -> None:
            try:
                text = await self.server.reconfig_admin(args)
            except Exception as e:
                text = 'error %s\n' % (e,)
            if self.closed:
                return
            try:
                self.writer.write(text.encode('utf-8'))
            except (ConnectionError, RuntimeError):
                pass
            self.close()
        from ..utils.aio import ambient_loop
        self._rcfg_task = ambient_loop().create_task(_run())

    def close(self) -> None:
        if self.closed:
            return
        # corked replies (e.g. the CLOSE_SESSION ack) and buffered
        # notifications must beat the FIN — and their durability
        # barrier, taken synchronously
        self._drain_fanout()
        self._tx.flush_hard()
        self.closed = True
        self._unsubscribe()
        if self._ingress is not None:
            self._ingress.forget(self)
        if self.session is not None and self.session.owner is self:
            self.session.owner = None
        self.server.conns.discard(self)
        try:
            self.writer.close()
        except (ConnectionError, RuntimeError):
            pass

    def abort(self) -> None:
        """The evicting close (io/overload.py): DISCARD everything
        buffered for this connection and reset the transport —
        flushing into the wedged socket is exactly how the bloat
        happened, so unlike :meth:`close` nothing is drained."""
        if self.closed:
            return
        self.closed = True
        self._fanout_buf.clear()
        self._tx.reset()
        self._unsubscribe()
        if self._ingress is not None:
            self._ingress.forget(self)
        if self.session is not None and self.session.owner is self:
            self.session.owner = None
        self.server.conns.discard(self)
        gate = self._rx_resume
        if gate is not None:
            gate.set()      # the parked validator pump exits its loop
        try:
            t = getattr(self.writer, 'transport', None)
            if t is not None:
                t.abort()
            else:
                self.writer.close()
        except (ConnectionError, RuntimeError):
            pass

    # -- handshake (session create / resume / migrate) --

    def _handle_connect(self, pkt: dict) -> None:
        timeout = pkt['timeOut']
        if pkt['sessionId'] == 0:
            sess = self.db.create_session(timeout)
        else:
            sess = self.db.resume_session(pkt['sessionId'], pkt['passwd'])
            if sess is None:
                # Unknown/expired session: zero id tells the client its
                # session is gone.
                self._send({'protocolVersion': 0, 'timeOut': timeout,
                            'sessionId': 0, 'passwd': b'\x00' * 16})
                self.codec.handshaking = False
                return
            # Session migration: drop the previous serving connection.
            if sess.owner is not None and sess.owner is not self:
                sess.owner.close()
        # the handshake's lastZxidSeen seeds the zxid read-gate floor:
        # what this session observed through OTHER members (or a
        # previous session of the same client) must not be readable
        # backwards here — the cross-process half of the session-view
        # contract (in-process members share the session object)
        seen = pkt.get('lastZxidSeen', 0)
        if seen > sess.last_zxid:
            sess.last_zxid = seen
        sess.owner = self
        self.session = sess
        self._send({'protocolVersion': 0, 'timeOut': sess.timeout,
                    'sessionId': sess.id, 'passwd': sess.passwd})
        self.codec.handshaking = False
        self._subscribe()

    # -- request dispatch --

    def _handle_request(self, pkt: dict) -> None:
        if self.session is None or self.session.expired:
            self._reply(pkt['xid'], pkt['opcode'], err='SESSION_EXPIRED')
            return
        self.db.touch_session(self.session)
        op = pkt['opcode']
        xid = pkt['xid']
        try:
            handler = getattr(self, '_op_' + op.lower(), None)
            if handler is None:
                self._reply(xid, op, err='UNIMPLEMENTED')
                return
            handler(pkt)
        except ZKOpError as e:
            # Failed reads with a watch flag still arm existence watches
            # where the protocol says so (handled inside the op); other
            # failures just carry the code.
            self._reply(xid, op, err=e.code)

    def _op_ping(self, pkt: dict) -> None:
        self._reply(pkt['xid'], 'PING')

    def _check_fence(self) -> None:
        """Epoch fence (server/election.py): a deposed member — one
        still serving at an epoch the quorum has moved past — must
        bounce writes with a typed error, never apply them."""
        fence = self.server.fence
        if fence is not None and fence():
            raise ZKOpError('EPOCH_FENCED')

    def _check_throttle(self, op: str) -> None:
        """Global memory watermark (io/overload.py): a member whose
        aggregate tx backlog crossed ``ZKSTREAM_MEM_SOFT`` is in
        degraded mode — new writes bounce with the typed THROTTLED
        error (definite failure, nothing applied; the client backs
        off and retries) while reads keep flowing."""
        ov = self.server.overload
        if ov is not None and ov.write_throttled():
            ov.count_throttled(op)
            raise ZKOpError('THROTTLED')

    def _gated(self, pkt: dict) -> bool:
        """True when the zxid read gate parked this read: the serving
        member's replica trails what this session has already seen, so
        serving now could show the session an older state.  The gate
        re-dispatches the packet once the replica catches up, or
        bounces it after the bounded wait (ReadGate).  Leader-view
        members are always current; ``ZKSTREAM_NO_READ_GATE=1`` keeps
        the ungated path as the env-gated validator arm."""
        gate = self.server.read_gate
        if gate is None or self.store is self.db:
            return False
        floor = self.session.last_zxid
        if self.store.zxid >= floor:
            return False
        gate.defer(self, pkt, floor)
        return True

    def _op_create(self, pkt: dict) -> None:
        self._check_fence()
        self._check_throttle('CREATE')
        path = self.db.create(pkt['path'], pkt['data'], pkt['acl'],
                              CreateFlag(pkt['flags']), self.session)
        # a write through this member catches its store up through the
        # transaction (real ZK: the follower commits before replying),
        # so the author can always read their own write here
        self.store.catch_up()
        self._reply(pkt['xid'], 'CREATE', path=path)

    def _op_delete(self, pkt: dict) -> None:
        self._check_fence()
        self._check_throttle('DELETE')
        self.db.delete(pkt['path'], pkt['version'])
        self.store.catch_up()
        self._reply(pkt['xid'], 'DELETE')

    def _op_get_data(self, pkt: dict) -> None:
        if self._gated(pkt):
            return
        try:
            data, stat = self.store.get_data(pkt['path'])
        except ZKOpError:
            raise
        if pkt.get('watch'):
            self._arm_data(pkt['path'])
        self._reply(pkt['xid'], 'GET_DATA', data=data, stat=stat)

    def _op_set_data(self, pkt: dict) -> None:
        self._check_fence()
        self._check_throttle('SET_DATA')
        stat = self.db.set_data(pkt['path'], pkt['data'], pkt['version'])
        self.store.catch_up()
        self._reply(pkt['xid'], 'SET_DATA', stat=stat)

    def _op_exists(self, pkt: dict) -> None:
        if self._gated(pkt):
            return
        try:
            stat = self.store.exists(pkt['path'])
        except ZKOpError:
            # EXISTS with watch on a missing node arms an existence
            # watch that fires CREATED later.
            if pkt.get('watch'):
                self._arm_data(pkt['path'])
            raise
        if pkt.get('watch'):
            self._arm_data(pkt['path'])
        self._reply(pkt['xid'], 'EXISTS', stat=stat)

    def _op_get_children(self, pkt: dict) -> None:
        if self._gated(pkt):
            return
        children, stat = self.store.get_children(pkt['path'])
        if pkt.get('watch'):
            self._arm_child(pkt['path'])
        self._reply(pkt['xid'], 'GET_CHILDREN', children=children)

    def _op_get_children2(self, pkt: dict) -> None:
        if self._gated(pkt):
            return
        children, stat = self.store.get_children(pkt['path'])
        if pkt.get('watch'):
            self._arm_child(pkt['path'])
        self._reply(pkt['xid'], 'GET_CHILDREN2', children=children,
                    stat=stat)

    def _op_get_acl(self, pkt: dict) -> None:
        if self._gated(pkt):
            return
        acl, stat = self.store.get_acl(pkt['path'])
        self._reply(pkt['xid'], 'GET_ACL', acl=acl, stat=stat)

    def _op_multi(self, pkt: dict) -> None:
        """One all-or-nothing MULTI transaction (opcode 14): the
        whole batch is ONE leader transaction — one WAL record, one
        group-fsync slot, one replication push element (store.py
        ``ZKDatabase.multi``).  The reply always decodes a result
        body: a rejected batch carries per-op error results (the
        failing op's code, RUNTIME_INCONSISTENCY elsewhere) with NO
        sub-op applied."""
        self._check_fence()
        self._check_throttle('MULTI')
        results = self.db.multi(pkt['ops'], self.session)
        self.store.catch_up()
        self._reply(pkt['xid'], 'MULTI', results=results)

    def _op_sync(self, pkt: dict) -> None:
        # Flush replication: this member applies everything the leader
        # has committed before replying, so a read issued after the
        # sync reply cannot see state older than the sync point —
        # the guarantee the reference test relies on
        # (multi-node.test.js:107-165).  sync_flush, not catch_up: a
        # cross-process member must fetch the leader's log first.
        self.store.sync_flush()
        self._reply(pkt['xid'], 'SYNC')

    def _op_close_session(self, pkt: dict) -> None:
        self.db.close_session(self.session.id)
        self._reply(pkt['xid'], 'CLOSE_SESSION')
        self.close()

    def _op_set_watches(self, pkt: dict) -> None:
        """Re-arm watches after reconnect, sending catch-up
        notifications for anything that moved past relZxid."""
        self._replay_one_shot(pkt['relZxid'], pkt['events'])
        self._reply(pkt['xid'], 'SET_WATCHES')

    def _op_set_watches2(self, pkt: dict) -> None:
        """SET_WATCHES2 (opcode 107): the five-list replay — the
        legacy three one-shot kinds plus ``persistent`` and
        ``persistentRecursive``.  Persistent re-arms always succeed
        (the subscription survives the reconnect); the catch-up nudge
        tells the subscriber its gap, so a watch-backed cache knows to
        refetch rather than trust its pre-disconnect contents."""
        rel = pkt['relZxid']
        events = pkt['events']
        self._replay_one_shot(rel, events)
        z = self.store.zxid
        for path in events.get('persistent', ()):
            self._arm_persistent(path, False)
            node = self.store.nodes.get(path)
            if node is None:
                self.notify('DELETED', path, z, persistent=True)
            elif node.mzxid > rel:
                self.notify('DATA_CHANGED', path, node.mzxid,
                            persistent=True)
        for path in events.get('persistentRecursive', ()):
            self._arm_persistent(path, True)
            # a subtree gap cannot be replayed per-node without a
            # change journal; one nudge at the subtree root marks the
            # whole span dirty and the subscriber refetches
            if z > rel:
                self.notify('DATA_CHANGED', path, z, persistent=True)
        self._reply(pkt['xid'], 'SET_WATCHES2')

    def _op_add_watch(self, pkt: dict) -> None:
        """ADD_WATCH (opcode 106): arm a persistent (mode 0) or
        persistent-recursive (mode 1) watch.  Unlike every other watch
        arm, this one is not a side effect of a read — it is its own
        round trip, and it survives fires without re-arm."""
        mode = pkt['mode']
        if mode not in (0, 1):
            raise ZKOpError('BAD_ARGUMENTS')
        self._arm_persistent(pkt['path'], mode == 1)
        self._reply(pkt['xid'], 'ADD_WATCH')

    def _replay_one_shot(self, rel: int, events: dict) -> None:
        # catch-up decisions run against THIS member's view: a node
        # change the member has not applied yet fires later through the
        # re-armed watch table when the replica applies it
        z = self.store.zxid
        for path in events.get('dataChanged', ()):
            node = self.store.nodes.get(path)
            if node is None:
                self.notify('DELETED', path, z)
            elif node.mzxid > rel:
                # moved past relZxid: the catch-up notification IS the
                # one-shot fire — it consumes any pre-existing arm
                # instead of re-arming
                self._disarm_data(path)
                self.notify('DATA_CHANGED', path, node.mzxid)
            else:
                self._arm_data(path)
        for path in events.get('createdOrDestroyed', ()):
            node = self.store.nodes.get(path)
            if node is None:
                # Missing node: the watcher may have seen it alive, so
                # send DELETED (real ZK does the same for exist watches
                # — it cannot know the node never existed either).
                self.notify('DELETED', path, z)
            elif node.czxid > rel:
                self.notify('CREATED', path, node.czxid)
            else:
                self._arm_data(path)
        for path in events.get('childrenChanged', ()):
            node = self.store.nodes.get(path)
            if node is None:
                self.notify('DELETED', path, z)
            elif node.pzxid > rel:
                self._disarm_child(path)
                self.notify('CHILDREN_CHANGED', path, node.pzxid)
            else:
                self._arm_child(path)


class ZKServer:
    """One listening endpoint — a quorum member.  Writes and sessions
    go to the leader ``db``; reads and watches are served from this
    member's ``store`` (the leader's own tree for a standalone server
    or the ensemble leader, a :class:`~.store.ReplicaStore` for a
    follower)."""

    def __init__(self, db: ZKDatabase | None = None,
                 host: str = '127.0.0.1', port: int = 0,
                 store=None, cork: bool | None = None,
                 collector=None, durability: str | None = None,
                 wal_dir: str | None = None,
                 watchtable: bool | None = None,
                 fanout_shards: int | None = None,
                 member: str | None = None,
                 trace: bool | None = None,
                 transport: str | None = None,
                 flush_cap: int | None = None,
                 ingress_shards: int | None = None,
                 ingress_backend: str | None = None,
                 blackbox: bool | None = None,
                 blackbox_dir: str | None = None,
                 overload: bool | None = None,
                 overload_config: OverloadConfig | None = None,
                 max_frame: int | None = None):
        #: Durability plane (server/persist.py).  When this server
        #: owns its database (``db=None``) and a WAL directory is
        #: resolved — the ``wal_dir`` argument or ``ZKSTREAM_WAL_DIR``
        #: — the database is recovered from disk and every committed
        #: txn is logged before its ack; ``durability`` picks the
        #: fsync policy ('always' | 'tick' | 'never', default 'tick').
        #: ``ZKSTREAM_NO_WAL=1`` is the global kill switch.  An
        #: ensemble attaches its WAL once on the shared database
        #: instead (ZKEnsemble); followers carry none.
        self._owns_wal = False
        if db is None:
            from .persist import (
                default_wal_dir,
                open_wal_database,
                wal_enabled,
            )
            resolved = wal_dir or default_wal_dir()
            if resolved and wal_enabled():
                db = open_wal_database(resolved,
                                       sync=durability or 'tick',
                                       collector=collector)
                self._owns_wal = True
            else:
                db = ZKDatabase()
        self.db = db
        self.store = store if store is not None else self.db
        self.host = host
        self.port = port
        #: This member's id within its ensemble ('0' standalone /
        #: leader; ZKEnsemble numbers its members) — the label every
        #: span on this member's ring carries, and what the merged
        #: timeline names it by.
        self.member = member if member is not None else '0'
        #: The server-side trace plane (utils/trace.py): this member's
        #: bounded span ring plus the per-tick phase ledger
        #: (utils/metrics.TickLedger).  None = process default
        #: (``ZKSTREAM_NO_SERVER_TRACE=1`` disables), True/False
        #: force — the A/B knob `bench.py --traceov` pairs on.
        enabled_trace = (server_trace_default() if trace is None
                         else trace)
        self.trace = (TraceRing(MEMBER_RING_CAPACITY,
                                member=self.member)
                      if enabled_trace else None)
        self.ledger = TickLedger(collector) if enabled_trace else None
        if enabled_trace:
            if self.store is self.db:
                # leader/standalone member: the shared database's
                # COMMIT spans, the WAL's append/fsync spans and its
                # loop-blocking sync time all belong to this ring
                self.db.trace = self.trace
                wal = getattr(self.db, 'wal', None)
                if wal is not None:
                    wal.trace = self.trace
                    wal.ledger = self.ledger
            else:
                # follower: the replica's APPLY spans land here (the
                # RemoteReplicaStore of an OS-process follower
                # included — same attribute)
                self.store.trace = self.trace
        #: Outbound write coalescing for accepted connections
        #: (io/sendplane.py): None = process default, True/False force.
        self.cork = cork
        #: Early-flush cap for accepted connections' planes (None =
        #: ZKSTREAM_FLUSH_CAP / the 256 KiB default).
        self.flush_cap = flush_cap
        #: Optional utils/metrics.Collector: when set, accepted
        #: connections record their flush-batch-size histograms here.
        self.collector = collector
        #: Batched-syscall transport tier (io/transport.py): one
        #: submission queue shared by every accepted connection's
        #: send plane — a corked tick's replies and fan-out flushes
        #: leave in ONE batched syscall chain on the uring backend
        #: (one writev per dirty conn, submitted in one C call, on
        #: mmsg).  None when the resolved backend is 'asyncio' (the
        #: env-gated validator: ZKSTREAM_TRANSPORT=asyncio).
        #: ``transport=`` forces a tier like the cork/codec knobs.
        from ..io.transport import make_tier
        self.transport_tier = make_tier(transport, collector=collector,
                                        plane='server',
                                        ledger=self.ledger)
        #: Shared-nothing ingress (io/ingress.py): N accept shards,
        #: each draining its dirty connections' bytes in ONE batched
        #: receive per busy tick, replacing the per-connection
        #: ``reader.read`` task wakeup.  None = the single-loop
        #: validator (``ingress_shards=1`` / ``ZKSTREAM_INGRESS_
        #: SHARDS=1`` / a resolved ``asyncio`` backend via
        #: ``ZKSTREAM_INGRESS``), which keeps ``asyncio.start_server``
        #: exactly as before.  ``rx_buf`` is the receive-buffer size
        #: both paths read with (``ZKSTREAM_RX_BUF``, formerly the
        #: hardcoded 65536).
        self.rx_buf = rx_buf_default()
        self.ingress = make_plane(self, ingress_shards,
                                  ingress_backend,
                                  collector=collector)
        #: rx-direction syscall accounting for the validator path
        #: (the ingress plane counts its own drains): one increment
        #: per ``reader.read`` wakeup, same metric, same label keys.
        self._recv_ctr = None
        self._recv_labels = {'plane': 'server', 'backend': 'asyncio'}
        if collector is not None:
            self._recv_ctr = collector.counter(
                METRIC_RECV_SYSCALLS,
                'Receive submissions issued by the ingress plane, by '
                'plane and backend')
        self._server: asyncio.base_events.Server | None = None
        self.conns: set[ServerConnection] = set()
        #: Fault-injection knobs for tests: swallow pings (forces the
        #: client's ping-timeout path) or swallow every reply (forces
        #: in-flight requests to hang until teardown).
        self.drop_pings = False
        self.drop_replies = False
        #: Optional seeded FaultInjector (io/faults.py): accept-loop
        #: refusals and reply-path splits/delays/mid-frame resets.
        self.faults = None
        #: one-slot encode cache for the emitter-fallback notification
        #: path ((type, path, zxid), wire bytes), filled via the
        #: dedicated connection-independent codec below (the bytes are
        #: shared across subscribers, so no per-connection codec may
        #: encode them); the watch table replaces it with a per-tick
        #: memo (server/watchtable.py)
        self._notif_cache: tuple[tuple, bytes] | None = None
        self._notif_codec = PacketCodec(server=True)
        self._notif_codec.handshaking = False
        #: The serving plane's sharded watch fan-out
        #: (server/watchtable.py): a reverse (kind, path) → subscriber
        #: index consulted once per store event, with per-shard corked
        #: notification flushes.  None = process default
        #: (``ZKSTREAM_NO_WATCHTABLE=1`` falls back to the
        #: per-connection emitter path), True/False force.
        enabled = watchtable_default() if watchtable is None \
            else watchtable
        if fanout_shards is None and self.ingress is not None:
            # ingress affinity: one fan-out shard per accept shard,
            # so a connection's arms, fan-out buffer and send-plane
            # cork all key off the shard that drains it
            fanout_shards = self.ingress.nshards
        self.watch_table = WatchTable(self, shards=fanout_shards,
                                      collector=collector) \
            if enabled else None
        #: Session expiry is dispatched once per member through the
        #: session's ``owner`` pointer (the session-id → connection
        #: map the database already maintains) — O(1) per expiry, not
        #: one callback per connection.
        self.db.on('sessionExpired', self._on_session_expired)
        #: Introspection counters for the four-letter admin words
        #: (mntr/stat/srvr): requests decoded, replies/notifications
        #: sent, and requests decoded but not yet replied (batch-
        #: scoped: a pipelined read's whole batch counts until each
        #: member's handler returns).
        self.packets_received = 0
        self.packets_sent = 0
        self.outstanding = 0
        #: Election plane (server/election.py).  ``role`` is this
        #: member's current quorum role (leader | follower |
        #: electing); ``fence`` an optional callable — True while this
        #: member is deposed at a stale epoch, making every write
        #: through it bounce with a typed EPOCH_FENCED error instead
        #: of being applied against history the quorum moved past.
        #: ``elections`` counts role resolutions on THIS member;
        #: ``elections_ref`` (set by an ElectionCoordinator) supplies
        #: the ensemble-wide count the mntr row prefers.
        self.role = 'leader' if self.store is self.db else 'follower'
        self.fence = None
        self.elections = 0
        self.elections_ref = None
        #: Quorum-commit gate (server/replication.py QuorumGate):
        #: when attached, accepted connections' acks gate on it
        #: ALONGSIDE the WAL's group fsync (CommitBarrier) — a corked
        #: tick waits once for both.  A ZKEnsemble wires one shared
        #: gate over its follower stores; the OS-process leader wires
        #: its ReplicationService's.  None = fsync-only barrier (the
        #: standalone / validator arm).
        self.quorum = None
        #: Zxid read gate (README "Read plane"): reads through this
        #: member park until its replica has applied everything the
        #: session already observed, or bounce after the bounded wait
        #: — the session view never goes backwards
        #: (analysis/linearize.py check_session_reads is the
        #: acceptance).  None = ``ZKSTREAM_NO_READ_GATE=1``, the
        #: env-gated ungated validator the checker must catch.
        self.read_gate = (ReadGate(self, collector=collector)
                          if read_gate_enabled() else None)
        #: The overload plane (io/overload.py): admission caps +
        #: handshake pacer, the per-connection inflight rx throttle,
        #: tx watermarks with slow-consumer eviction, and the global
        #: memory watermark that bounces writes THROTTLED.  None =
        #: ``ZKSTREAM_NO_OVERLOAD=1`` (or ``overload=False``), the
        #: validator arm with the pre-overload byte-stream — which is
        #: why ``max_frame`` pins to MAX_PACKET when the plane is off.
        enabled_ov = (overload_enabled() if overload is None
                      else overload)
        self.max_frame = (resolve_frame_cap(max_frame) if enabled_ov
                          else MAX_PACKET)
        self.overload = (OverloadPlane(self, cfg=overload_config,
                                       collector=collector)
                         if enabled_ov else None)
        #: Per-instance listen backlog (shadows the class default):
        #: ``ZKSTREAM_LISTEN_BACKLOG`` > the kernel's somaxconn clamp
        #: > the class default — see the note at the class attribute.
        self.BACKLOG = self._resolve_backlog()
        #: ``zookeeper_reconfig_ms`` histogram (lazy: registered on
        #: the first membership change this member drives, so the
        #: steady-state metric inventory is unchanged when dynamic
        #: membership is never exercised).
        self._rcfg_hist = None
        #: The ``zk_uptime_ms`` epoch (construction, like real ZK's
        #: server start).
        self._started_at = time.monotonic()
        #: The black-box plane (utils/blackbox.py): a crash-durable
        #: flight recorder co-tenant in this member's WAL directory —
        #: only a member with one has somewhere durable to write.
        #: ``blackbox=`` forces on/off (``ZKSTREAM_NO_BLACKBOX=1`` is
        #: the process default / kill switch); ``blackbox_dir=`` gives
        #: a member without its own WAL (ensemble followers share the
        #: leader's log; OS-process members own a wal_dir either way)
        #: a ring of its own.
        from ..utils.blackbox import (
            BlackBoxRecorder,
            blackbox_enabled,
            slow_op_ms,
        )
        enabled_bb = (blackbox_enabled() if blackbox is None
                      else blackbox)
        bb_dir = blackbox_dir
        if bb_dir is None and self._owns_wal:
            bb_dir = self.db.wal.dir
        self.blackbox = (BlackBoxRecorder(bb_dir, member=self.member,
                                          server=self,
                                          collector=collector)
                         if enabled_bb and bb_dir else None)
        if self.blackbox is not None and self.trace is not None:
            # the slow-op digest: spans settled on this member's ring
            # at/over the threshold get their causal chain persisted
            self.trace.slow_ms = slow_op_ms()
            self.trace.on_slow = self.blackbox.slow_span

    @property
    def ack_barrier(self):
        """What accepted connections' send planes gate acks on: the
        database's WAL (group fsync), composed with the quorum gate
        when one is attached — ack-order contract: no reply byte may
        reach the transport before BOTH have cleared."""
        wal = getattr(self.db, 'wal', None)
        q = self.quorum
        if q is not None and q.enabled:
            from .replication import CommitBarrier
            return CommitBarrier(wal, q)
        return wal

    def encode_notification(self, ntype: str, path: str,
                            zxid: int) -> bytes:
        """Wire bytes for one notification, shared across subscribers:
        the watch table's per-tick memo when the table is on, the
        legacy depth-1 cache on the emitter fallback."""
        if self.watch_table is not None:
            return self.watch_table.encode(ntype, path, zxid)
        key = (ntype, path, zxid)
        cache = self._notif_cache
        if cache is not None and cache[0] == key:
            return cache[1]
        data = self._notif_codec.encode(
            {'xid': XID_NOTIFICATION, 'zxid': zxid, 'err': 'OK',
             'opcode': 'NOTIFICATION', 'type': ntype,
             'state': 'SYNC_CONNECTED', 'path': path})
        self._notif_cache = (key, data)
        return data

    def _on_session_expired(self, session_id: int) -> None:
        """One callback per member per expiry: the expiring session's
        ``owner`` pointer names the serving connection directly, so no
        connection scan happens (and members not serving the session
        do nothing)."""
        sess = self.db.sessions.get(session_id)
        owner = getattr(sess, 'owner', None)
        if owner is not None and owner in self.conns:
            owner.close()
            return
        if sess is None:
            # a mirror that already dropped the entry (cross-process
            # member): fall back to the scan — rare, never hot
            for c in list(self.conns):
                if c.session is not None and c.session.id == session_id:
                    c.close()

    #: Listen backlog: the asyncio default (100) drops handshakes
    #: under a thundering herd of reconnects at fleet scale.  The old
    #: default here (1024) was set against Python-client waves; the C
    #: loadgen's measured handshake storms arrive faster than one
    #: accept sweep drains, so the default now matches the kernel's
    #: own clamp (``net.core.somaxconn``, 4096 on the profiled host —
    #: anything above it is silently truncated anyway).  Override
    #: with ``ZKSTREAM_LISTEN_BACKLOG``; PROFILE.md round 19 has the
    #: wave numbers this was re-derived from.
    BACKLOG = 1024

    @staticmethod
    def _resolve_backlog() -> int:
        env = os.environ.get('ZKSTREAM_LISTEN_BACKLOG')
        if env:
            try:
                return max(1, int(env))
            except ValueError:
                pass
        try:
            with open('/proc/sys/net/core/somaxconn') as f:
                return max(ZKServer.BACKLOG, int(f.read().strip()))
        except (OSError, ValueError):
            return ZKServer.BACKLOG

    async def start(self) -> 'ZKServer':
        # Million-session enabler: lift the soft fd limit to what the
        # admitted-connection ceiling needs, and say WHICH limit binds
        # when the host cap wins (never a bare EMFILE mid-accept).
        from ..utils import fdlimit
        max_conns = (self.overload.cfg.max_conns
                     if self.overload is not None else None)
        if max_conns:
            fdlimit.raise_nofile(max_conns + 256)
            err = fdlimit.headroom_error(max_conns)
            if err:
                log.warning('%s (admission ceiling %d will shed '
                            'above the fd fit)', err, max_conns)
        if self.blackbox is not None:
            self.blackbox.start(asyncio.get_running_loop())
        if self.ingress is not None:
            # sharded ingress: per-shard SO_REUSEPORT listeners (or
            # the dispatcher handoff) + batched receive drains; the
            # single-loop asyncio.start_server path below stays the
            # env-gated validator
            self.ingress.start(self.host, self.port)
            self.port = self.ingress.port
            log.info('ZK server listening on %s:%d (%d ingress '
                     'shards, %s)', self.host, self.port,
                     self.ingress.nshards, self.ingress.backend)
            return self
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port,
            backlog=self.BACKLOG)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info('ZK server listening on %s:%d', self.host, self.port)
        return self

    def note_shed(self, reason: str) -> None:
        """Account one pre-adoption shed: traced span + metric — the
        bookkeeping half every shed path shares (the validator's
        :meth:`shed_client` below and the ingress plane's RST shed,
        io/ingress.py)."""
        if self.trace is not None:
            self.trace.note('OVERLOAD', kind='server',
                            detail='shed:%s' % (reason,))
        if self.overload is not None:
            self.overload.count_shed(reason)

    def shed_client(self, writer: asyncio.StreamWriter,
                    reason: str) -> None:
        """Shed one just-accepted client: account it, then abort the
        transport (RST, no FIN handshake to babysit) — never the old
        bare ``transport.abort()`` with no trace or metric."""
        self.note_shed(reason)
        try:
            writer.transport.abort()
        except (ConnectionError, RuntimeError):
            pass

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        if self.faults is not None and self.faults.accept_refuse():
            # Injected accept-loop refusal: the member is listening
            # but sheds this client (overload / half-dead member).
            self.shed_client(writer, 'accept_refuse')
            return
        ov = self.overload
        if ov is not None:
            why = ov.admit(len(self.conns))
            if why is not None:
                self.shed_client(writer, why)
                return
            delay = ov.pace_delay()
            if delay > 0.0:
                # handshake pacer: over-window accepts adopt late,
                # flattening a dial wave into a trickle
                await asyncio.sleep(delay)
                if not self.listening:
                    self.shed_client(writer, 'pacer_shutdown')
                    return
        set_nodelay(writer)
        conn = ServerConnection(self, reader, writer)
        self.conns.add(conn)
        await conn.run()

    async def stop(self) -> None:
        """Kill the server: stop listening and sever every connection.
        Sessions live in the database and keep their expiry clocks
        running — exactly what a crashed ensemble member looks like.
        A WAL this server opened itself is closed (final fsync, fd
        released) — ``restart`` reopens it; an ensemble's shared WAL
        belongs to the ensemble (ZKEnsemble.stop)."""
        if self.ingress is not None:
            # listeners first: no accept can land between severing
            # the fleet and releasing the port
            self.ingress.stop()
        if self.read_gate is not None:
            self.read_gate.reset()   # parked reads die with the conns
        for conn in list(self.conns):
            conn.close()
        self.conns.clear()
        if self._server is not None:
            self._server.close()
            # In Python >= 3.12.1 wait_closed also waits for all client
            # handlers to return, so connections must be severed first.
            await self._server.wait_closed()
            self._server = None
        if self.ingress is not None:
            # the sharded twin of wait_closed: every severed
            # connection's transport teardown has run before stop()
            # returns, so an in-process peer observes the close
            await self.ingress.wait_closed()
        if self.blackbox is not None:
            # clean stop: cancel the cadence, drain queued frames and
            # flush one fsynced final frame (a SIGKILL never gets
            # here — the ring's torn tail is that story)
            self.blackbox.stop()
        if self._owns_wal and not self.db.wal.closed:
            self.db.wal.close()
        if self.transport_tier is not None:
            # release the tier's io_uring fd + mmaps with the server:
            # connection/plane/entry closures hold the tier in
            # reference cycles, so GC-time release is unreliable at
            # chaos-campaign churn rates.  restart() lazily
            # re-creates the ring on the next submission.
            self.transport_tier.close()

    async def restart(self, from_disk: bool = False) -> 'ZKServer':
        """Bring a killed member back on its old port; a rejoining
        member first applies everything the leader committed while it
        was down, like a real follower resync.

        ``from_disk=True`` models the harsher death: the process (not
        just the listener) died, so RAM is gone and the member comes
        back from its write-ahead log — newest valid snapshot plus
        the replayed tail (server/persist.py).  Standalone/leader
        only; it requires a WAL and drops every session, exactly like
        a real restart."""
        assert self._server is None and (
            self.ingress is None or not self.ingress.running), \
            'server still running'
        if from_disk:
            assert self.store is self.db, \
                'restart-from-disk rebuilds the leader database'
            self.db.recover_from_disk()
        elif self.db.wal is not None and self.db.wal.closed:
            self.db.wal.reopen()     # stop() closed it with the member
        self.store.catch_up()
        if self.blackbox is not None:
            self.blackbox.start(asyncio.get_running_loop())
        if self.ingress is not None:
            self.ingress.start(self.host, self.port)
            return self
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port,
            backlog=self.BACKLOG)
        return self

    @property
    def address(self) -> tuple[str, int]:
        return (self.host, self.port)

    @property
    def listening(self) -> bool:
        """True while this member accepts connections — on whichever
        receive path it runs (the sharded ingress plane or the
        single-loop validator's asyncio server).  The election
        coordinator's liveness probe reads this."""
        return (self._server is not None
                or (self.ingress is not None and self.ingress.running))

    # -- four-letter admin words (ruok / mntr / stat / srvr) --

    def watch_count(self) -> int:
        """Armed one-shot watches across this member's connections —
        the watch table's maintained counter (O(1) per scrape); the
        emitter fallback keeps the legacy O(connections) sum."""
        if self.watch_table is not None:
            return self.watch_table.count
        return sum(len(c.data_watches) + len(c.child_watches)
                   for c in self.conns)

    def persistent_watch_count(self) -> int:
        """Armed PERSISTENT (non-recursive) watches on this member."""
        if self.watch_table is not None:
            return self.watch_table.persistent_count
        return sum(sum(1 for r in c.persistent_watches.values()
                       if not r)
                   for c in self.conns)

    def recursive_watch_count(self) -> int:
        """Armed PERSISTENT_RECURSIVE watches on this member."""
        if self.watch_table is not None:
            return self.watch_table.recursive_count
        return sum(sum(1 for r in c.persistent_watches.values() if r)
                   for c in self.conns)

    def mode(self) -> str:
        return 'standalone' if self.store is self.db else 'follower'

    def current_epoch(self) -> int:
        """The leadership epoch this member serves under (the shared
        database's for in-process members, the mirror's accepted
        epoch for an OS-process follower)."""
        return getattr(self.db, 'epoch', 0)

    def elections_total(self) -> int:
        ref = self.elections_ref
        return ref.elections if ref is not None else self.elections

    def repoint(self, db, store=None, role: str | None = None) -> None:
        """Leadership failover (server/election.py): swap this
        member's backing database/store while the listener keeps its
        port.  Every accepted connection is closed — its session and
        watch state belonged to the dead leader; clients reconnect,
        resume or re-create sessions, and SET_WATCHES re-arms — and
        the event subscriptions (session expiry, watch-table store
        listeners, trace wiring) move to the new storage."""
        for conn in list(self.conns):
            conn.close()
        self.conns.clear()
        if self.read_gate is not None:
            # parked reads belonged to the closed connections; the
            # gate re-follows the new store lazily
            self.read_gate.reset()
        self.db.remove_listener('sessionExpired',
                                self._on_session_expired)
        self.db = db
        self.store = store if store is not None else db
        self.db.on('sessionExpired', self._on_session_expired)
        if self.watch_table is not None:
            self.watch_table.rebind_store(self.store)
        if self.trace is not None:
            if self.store is self.db:
                self.db.trace = self.trace
                wal = getattr(self.db, 'wal', None)
                if wal is not None:
                    wal.trace = self.trace
                    wal.ledger = self.ledger
            else:
                self.store.trace = self.trace
        if role is not None:
            self.role = role
        else:
            self.role = ('leader' if self.store is self.db
                         else 'follower')

    # -- dynamic membership (README "Dynamic membership") --

    def _installed_config(self) -> dict | None:
        """The membership config this member can see: the database's
        own (leader / in-process members sharing it), else the one
        mirrored over replication (an OS-process follower's
        RemoteLeader)."""
        db = self.db
        if getattr(db, 'voter_ids', None) is not None:
            return db.config_snapshot()
        return getattr(getattr(self.store, 'leader', None),
                       'config', None)

    def reconfig_status(self) -> str:
        """One ``rcfg status`` reply line — answerable by any member,
        like the four-letter words."""
        cfg = self._installed_config()
        if cfg is None:
            return 'version=0 phase=static voters=- observers=-\n'
        return 'version=%d phase=%s voters=%s observers=%s\n' % (
            cfg['version'], cfg.get('phase') or 'final',
            _csv(cfg['voters']), _csv(cfg.get('observers') or ()))

    def _observe_reconfig(self, t0: float) -> None:
        if self.collector is not None and self._rcfg_hist is None:
            self._rcfg_hist = self.collector.histogram(
                METRIC_RECONFIG,
                'Membership reconfiguration latency (propose through '
                'commit), ms', buckets=RECONFIG_BUCKETS)
        if self._rcfg_hist is not None:
            self._rcfg_hist.observe(
                (time.perf_counter() - t0) * 1000.0)

    async def reconfig_admin(self, args: str) -> str:
        """Serve one ``rcfg`` admin line against this member.

        Actions: ``status`` (any member) · ``propose <voters-csv>
        [<observers-csv>]`` (leader-only: land the reconfig record —
        for a voter change that is the JOINT record, and this call
        deliberately stops there, which is what lets a chaos schedule
        SIGKILL the ensemble mid-joint) · ``commit`` (leader-only:
        finish an open joint window) · ``apply <voters-csv>
        [<observers-csv>]`` (leader-only: propose, await the joint
        record's quorum, commit, await the final record's quorum).
        Observer lists default to the current observers minus any
        member promoted into the new voter set."""
        parts = args.split()
        action = parts[0] if parts else 'status'
        if action == 'status':
            return self.reconfig_status()
        db = self.db
        if self.role != 'leader' \
                or not hasattr(db, 'propose_reconfig') \
                or (self.fence is not None and self.fence()):
            # a RemoteLeader handle has no propose_reconfig either:
            # followers answer status only, real-ZK style
            return 'error not leader\n'
        t0 = time.perf_counter()
        try:
            if action == 'commit':
                entry = db.commit_reconfig()
                self._observe_reconfig(t0)
                return 'committed version=%d voters=%s\n' % (
                    entry[1], _csv(entry[4]))
            if action not in ('propose', 'apply'):
                return 'error unknown action %r\n' % (action,)
            if len(parts) < 2:
                return 'error %s needs a voter list\n' % (action,)
            voters = _parse_members(parts[1])
            observers = (_parse_members(parts[2]) if len(parts) > 2
                         else tuple(i for i in db.observer_ids
                                    if i not in voters))
            entry = db.propose_reconfig(voters, observers)
        except ValueError as e:
            return 'error %s\n' % (e,)
        if action == 'propose' or entry[2] == 'final':
            self._observe_reconfig(t0)
            return '%s version=%d phase=%s zxid=0x%x\n' % (
                'proposed' if action == 'propose' else 'applied',
                entry[1], entry[2], entry[6])
        # apply, joint phase: both configs must majority-hold the
        # joint record before the final record may land
        q = self.quorum
        if q is not None and q.enabled:
            await q.wait(entry[6])
        final = db.commit_reconfig()
        if q is not None and q.enabled:
            await q.wait(final[6])
        self._observe_reconfig(t0)
        return 'applied version=%d voters=%s\n' % (
            final[1], _csv(final[4]))

    def monitor_stats(self) -> list[tuple[str, object]]:
        """The ``mntr`` key/value inventory (ordered), real-ZK key
        names where an equivalent exists."""
        ephemerals = sum(len(s.ephemerals)
                         for s in self.db.sessions.values())
        data_size = sum(len(n.data)
                        for n in self.store.nodes.values())
        wal = getattr(self.db, 'wal', None)
        wal_rows = [] if wal is None else [
            ('zk_wal_sync', wal.sync),
            ('zk_wal_last_index', wal.next_index),
            ('zk_wal_fsyncs', wal.fsyncs),
            ('zk_wal_sync_errors', wal.sync_errors),
            ('zk_wal_snapshots', wal.snapshots_taken),
        ]
        # quorum-commit rows (server/replication.py QuorumGate): the
        # majority floor, degraded (quorum-unconfirmed) releases and
        # epoch-fenced stale acks
        q = self.quorum
        quorum_rows = [] if q is None or not q.enabled else [
            ('zk_quorum_members', q.total),
            ('zk_quorum_zxid', '0x%x' % (q.quorum_zxid_floor,)),
            ('zk_quorum_degraded', q.degraded_releases),
            ('zk_quorum_stale_acks', q.stale_acks),
        ]
        # dynamic-membership rows (README "Dynamic membership"): the
        # installed config's version, member inventory and the count
        # of completed reconfigurations
        cfg = self._installed_config()
        config_rows = [] if cfg is None else [
            ('zk_config_version', cfg['version']),
            ('zk_config_members', _config_desc(
                cfg['voters'], cfg.get('old_voters'),
                cfg.get('observers') or (),
                cfg.get('phase') or 'final')),
            ('zk_reconfig_total',
             getattr(self.db, 'reconfig_total', 0)),
        ]
        # zxid read-gate rows (README "Read plane"): reads parked
        # until this member caught up, and parked reads bounced to a
        # fresher member after the bounded wait
        rg = self.read_gate
        gate_rows = [] if rg is None else [
            ('zk_read_zxid_gate_blocks', rg.blocks),
            ('zk_read_zxid_gate_bounces', rg.bounces),
        ]
        # MULTI rows: batches applied and mean batch width
        batches = getattr(self.db, 'multi_batches', 0)
        subops = getattr(self.db, 'multi_subops', 0)
        multi_rows = [
            ('zk_multi_batches', batches),
            ('zk_multi_batch_size',
             round(subops / batches, 2) if batches else 0),
        ]
        # the tick ledger + trace-ring rows (the per-tick plane
        # decomposition, README "Causal tracing"): tick count, each
        # phase's per-tick p99, and how often the bounded span ring
        # wrapped
        tick_rows: list[tuple[str, object]] = []
        if self.trace is not None:
            tick_rows.append(('zk_trace_ring_dropped',
                              self.trace.dropped))
        # black-box plane rows (utils/blackbox.py): the slow-op count
        # is ALWAYS present (0 with the recorder off — the clean-
        # schedule invariant asserts on it either way); frame/byte
        # rows only when a recorder is actually writing
        bb = self.blackbox
        blackbox_rows: list[tuple[str, object]] = [
            ('zk_slow_ops_total', 0 if bb is None else bb.slow_ops),
        ]
        if bb is not None:
            blackbox_rows += [
                ('zk_blackbox_frames', bb.frames),
                ('zk_blackbox_bytes', bb.bytes_written),
            ]
        if self.ledger is not None:
            tick_rows.append(('zk_tick_count', self.ledger.ticks))
            for phase in TickLedger.PHASES:
                p99 = self.ledger.phase_p99(phase)
                if p99 is not None:
                    tick_rows.append(
                        ('zk_tick_phase_ms_p99{phase="%s"}' % (phase,),
                         round(p99, 4)))
        return [
            ('zk_version', 'zkstream_tpu'),
            ('zk_uptime_ms',
             int((time.monotonic() - self._started_at) * 1000)),
            ('zk_server_state', self.mode()),
            ('zk_member_role', self.role),
            ('zk_epoch', self.current_epoch()),
            ('zk_elections_total', self.elections_total()),
            ('zk_znode_count', len(self.store.nodes)),
            ('zk_watch_count', self.watch_count()),
            ('zk_persistent_watches', self.persistent_watch_count()),
            ('zk_recursive_watches', self.recursive_watch_count()),
            ('zk_outstanding_requests', self.outstanding),
            ('zk_num_alive_connections', len(self.conns)),
            ('zk_packets_received', self.packets_received),
            ('zk_packets_sent', self.packets_sent),
            ('zk_ephemerals_count', ephemerals),
            ('zk_approximate_data_size', data_size),
            ('zk_sessions', len(self.db.sessions)),
            ('zk_session_table_size',
             sum(1 for s in self.db.sessions.values()
                 if not s.expired and not s.closed)),
            ('zk_zxid', '0x%x' % (self.store.zxid,)),
            ('zk_fanout_shards',
             0 if self.watch_table is None
             else self.watch_table.nshards),
            ('zk_transport_backend',
             'asyncio' if self.transport_tier is None
             else self.transport_tier.backend),
            ('zk_ingress_shards',
             1 if self.ingress is None else self.ingress.nshards),
            ('zk_ingress_backend',
             'asyncio' if self.ingress is None
             else self.ingress.backend),
        ] + self._ingress_census_rows() \
            + (self.overload.mntr_rows()
               if self.overload is not None else []) \
            + multi_rows + gate_rows \
            + quorum_rows + config_rows + tick_rows + blackbox_rows \
            + wal_rows

    def _ingress_census_rows(self) -> list[tuple[str, object]]:
        """Per-shard connection census (sharded ingress only): how
        evenly the kernel (SO_REUSEPORT) or the dispatcher spread the
        fleet across accept shards."""
        if self.ingress is None:
            return []
        return [('zk_ingress_shard_conns{shard="%d"}' % (i,), n)
                for i, n in enumerate(self.ingress.shard_census())]

    def admin_text(self, word: str) -> str:
        """Render one four-letter word's reply text."""
        if word == 'ruok':
            return 'imok'
        if word == 'mntr':
            return ''.join('%s\t%s\n' % kv
                           for kv in self.monitor_stats())
        if word == 'trce':
            # this member's span ring as JSON — the scrape `timeline
            # --live` merges across members (schema-stamped; an
            # OS-process member answers it like any admin word)
            import json
            return json.dumps({
                'trace_schema': TRACE_SCHEMA,
                'member': self.member,
                'dropped': (0 if self.trace is None
                            else self.trace.dropped),
                'spans': ([] if self.trace is None
                          else self.trace.dump()),
            }) + '\n'
        if word in ('stat', 'srvr'):
            lines = ['Zookeeper version: zkstream_tpu (in-process)']
            if word == 'stat':
                lines.append('Clients:')
                for c in self.conns:
                    sid = c.session.id if c.session is not None else 0
                    peer = c.writer.get_extra_info('peername')
                    addr = ('%s:%d' % (peer[0], peer[1])
                            if peer else 'unknown')
                    lines.append(' /%s[1](sid=0x%x)' % (addr, sid))
                lines.append('')
            lines += [
                'Latency min/avg/max: 0/0/0',
                'Received: %d' % (self.packets_received,),
                'Sent: %d' % (self.packets_sent,),
                'Connections: %d' % (len(self.conns),),
                'Outstanding: %d' % (self.outstanding,),
                'Zxid: 0x%x' % (self.store.zxid,),
                'Mode: %s' % (self.mode(),),
                'Node count: %d' % (len(self.store.nodes),),
            ]
            return '\n'.join(lines) + '\n'
        raise ValueError('unknown admin word %r' % (word,))


class ZKEnsemble:
    """N quorum members on localhost (reference analogue:
    test/multi-node.test.js's three real servers on distinct ports).
    Member 0 is the leader; members 1.. are followers, each with its
    own :class:`~.store.ReplicaStore` replaying the leader's commit
    log.  With the default ``lag=0`` replication is synchronous (a
    perfect network); ``set_lag`` makes a follower genuinely trail the
    leader — stale reads included — which is what gives ``sync`` its
    meaning (tests/test_multi_node.py drives both regimes)."""

    def __init__(self, count: int = 3, host: str = '127.0.0.1',
                 lag: float | None = 0.0,
                 wal_dir: str | None = None,
                 durability: str | None = None,
                 collector=None, wal_segment_bytes: int | None = None,
                 watchtable: bool | None = None,
                 election: bool | None = None,
                 heartbeat_ms: int | None = None,
                 seed: int | None = None,
                 transport: str | None = None,
                 quorum: bool | None = None,
                 ingress_shards: int | None = None,
                 observers: int | None = None):
        #: One WAL for the whole ensemble, attached to the shared
        #: leader database (followers hold replica views of the same
        #: history; a per-member log would just write it N times).
        #: With a wal_dir the ensemble RECOVERS from it — a fresh
        #: ZKEnsemble over yesterday's directory is restart-from-disk.
        if wal_dir:
            from .persist import open_wal_database, wal_enabled
            if wal_enabled():
                kw = {}
                if wal_segment_bytes is not None:
                    kw['segment_bytes'] = wal_segment_bytes
                self.db = open_wal_database(
                    wal_dir, sync=durability or 'tick',
                    collector=collector, **kw)
            else:
                self.db = ZKDatabase()
        else:
            self.db = ZKDatabase()
        #: Quorum-commit gate built BEFORE the follower stores: its
        #: push-time stamp must run ahead of the stores' synchronous
        #: applies on the 'committed' edge, or every zk_quorum_ack_ms
        #: sample would measure the gap to the NEXT commit instead.
        #: The read scale-out plane (README "Read plane"): the VOTING
        #: membership is members ``0..count-1``; ``observers`` extra
        #: members receive the same replication feed and serve
        #: reads/watches/sessions but never vote, never count toward
        #: the quorum-commit majority, and never win an election — so
        #: read capacity scales without widening the write quorum.
        self.voters = count
        self.observer_count = (observers if observers is not None
                               else observers_default())
        #: Construction parameters retained for runtime membership
        #: changes (README "Dynamic membership"): a joining member is
        #: built exactly like a boot-time one.
        self._host = host
        self._lag = lag
        self._watchtable = watchtable
        self._transport = transport
        self._ingress_shards = ingress_shards
        self._collector = collector
        #: Black-box co-tenancy: every member (followers and
        #: observers included — they carry no WAL of their own) gets
        #: a per-member flight-recorder ring in the ensemble's one
        #: wal_dir; distinct member ids keep the files apart.
        self._blackbox_dir = wal_dir if (wal_dir and self.db.wal
                                         is not None) else None
        #: Quorum-commit: the ack barrier's membership is the VOTERS
        #: alone — attaching observers must not widen (or shrink) the
        #: majority a write waits for.
        from .replication import QuorumGate
        self.quorum = QuorumGate(self.db, count, enabled=quorum,
                                 collector=collector)
        if self.quorum.enabled:
            self.db.on('committed',
                       lambda: self.quorum.note_pushed(self.db.zxid))
        self.servers = [
            ZKServer(self.db, host=host,
                     store=None if i == 0 else ReplicaStore(self.db,
                                                            lag=lag),
                     watchtable=watchtable, member=str(i),
                     transport=transport,
                     ingress_shards=ingress_shards,
                     blackbox_dir=self._blackbox_dir)
            for i in range(count + self.observer_count)]
        for s in self.servers[count:]:
            # an observer owns its own replica, watch table and
            # ingress shards (notification fan-out and receive drain
            # scale with the observer fleet), but its role never
            # changes: elections are the voters' business
            s.role = 'observer'
        #: Quorum leader election (server/election.py): on by default;
        #: ``election=False`` / ``ZKSTREAM_NO_ELECTION=1`` keeps the
        #: static member-0 leader as the env-gated validator path.
        #: The coordinator probes leader liveness on a jittered
        #: backoff and elects the highest (epoch, zxid, member) among
        #: live, unpartitioned VOTERS when a quorum is reachable —
        #: observers never enter a ballot.
        from .election import ElectionCoordinator, election_enabled
        enabled_election = (election_enabled() if election is None
                            else election)
        self.election = (ElectionCoordinator(
            self.servers, self.db, heartbeat_ms=heartbeat_ms,
            seed=seed, collector=collector, voters=count)
            if enabled_election else None)
        #: Quorum-commit wiring (server/replication.py QuorumGate,
        #: constructed above the servers list): the leader's ack
        #: gates on a majority of follower stores having applied the
        #: txn, alongside the WAL's group fsync — on by default at
        #: >= 2 members (``quorum=False`` / ``ZKSTREAM_NO_QUORUM=1``
        #: keeps the fsync-only barrier as the A/B validator arm).
        #: Each follower store's apply hook is its piggybacked
        #: applied-zxid vote.
        if self.quorum.enabled:
            gate = self.quorum
            for s in self.servers:
                s.quorum = gate
            for i in range(1, count):
                self.servers[i].store.on_applied = (
                    lambda z, v='member:%d' % i:
                    gate.note_ack(v, z, self.db.epoch))
            # QUORUM_ACK spans land on the founding leader's ring
            gate.trace = self.servers[0].trace
        #: Dynamic membership (README "Dynamic membership"): the boot
        #: config installs as version 0 unless WAL recovery already
        #: adopted a later one; from here on the database's
        #: config-change hook re-derives the quorum gate's NAMED
        #: voter sets and the election coordinator's ballot sets on
        #: every reconfig record — joint phase included, where both
        #: planes require majorities of BOTH configs.
        if self.db.voter_ids is None:
            self.db.install_config({
                'version': 0, 'phase': 'final',
                'voters': tuple(range(count)),
                'old_voters': None,
                'observers': tuple(range(
                    count, count + self.observer_count)),
            })
        self.db.on_config_change = (
            lambda phase, entry: self._config_changed())
        self._config_changed()

    def _config_changed(self) -> None:
        """Re-derive every membership consumer from the database's
        installed config: the quorum gate's named voter sets (member
        0's vote is the shared database itself — its store IS the db,
        always current, so ``leader_key`` stays ``member:0`` whoever
        holds the leader role), the election coordinator's ballot
        sets, and the ensemble's voting-member count."""
        db = self.db
        if db.voter_ids is None:
            return
        self.voters = len(db.voter_ids)
        old = db.old_voter_ids
        if self.quorum.enabled:
            self.quorum.total = (max(len(db.voter_ids), len(old))
                                 if old is not None
                                 else len(db.voter_ids))
            self.quorum.set_config(
                {'member:%d' % i for i in db.voter_ids},
                ({'member:%d' % i for i in old}
                 if old is not None else None),
                leader_key='member:0')
        if self.election is not None:
            self.election.set_config(
                set(db.voter_ids),
                set(old) if old is not None else None)

    @property
    def leader_idx(self) -> int:
        """The current leader member's index (0 on the static path)."""
        return 0 if self.election is None else self.election.leader_idx

    def install_faults(self, injector) -> None:
        """Install one seeded FaultInjector on every member (the chaos
        campaign's server-side fault source)."""
        for s in self.servers:
            s.faults = injector

    def set_lag(self, idx: int, lag: float | None) -> None:
        """Change follower ``idx``'s replication lag (0 = synchronous,
        seconds = timed delay, None = hold until sync/write)."""
        store = self.servers[idx].store
        if not isinstance(store, ReplicaStore):
            raise ValueError('member %d is the leader' % (idx,))
        store.lag = lag

    async def start(self) -> 'ZKEnsemble':
        for s in self.servers:
            await s.start()
        if self.election is not None:
            self.election.start()
        return self

    async def stop(self) -> None:
        """Full-ensemble death: every member stops and the WAL (when
        configured) is closed — a fresh ZKEnsemble over the same
        ``wal_dir`` is the restart-from-disk path."""
        if self.election is not None:
            self.election.stop()
        self.quorum.close()
        for s in self.servers:
            await s.stop()
        # full-ensemble death: in-flight expiry timers die with it —
        # one firing after the WAL below closes would try to log the
        # session_close edge into a closed log (the read plane's
        # per-backend read sessions made this race common at teardown)
        for sess in self.db.sessions.values():
            if sess.expiry_handle is not None:
                sess.expiry_handle.cancel()
                sess.expiry_handle = None
        if self.db.wal is not None:
            self.db.wal.close()

    async def kill(self, idx: int) -> None:
        await self.servers[idx].stop()

    async def restart(self, idx: int) -> None:
        """Bring a killed member back on its old port; a rejoining
        follower first syncs with the leader, like a real one — and
        with election on, an ex-leader rejoins the CURRENT epoch as a
        follower, never as the leader it once was."""
        await self.servers[idx].restart()
        db = self.db
        if db.voter_ids is not None:
            is_voter = idx in db.voter_ids or (
                db.old_voter_ids is not None
                and idx in db.old_voter_ids)
        else:
            is_voter = idx < self.voters
        if not is_voter:
            self.servers[idx].role = 'observer'
        elif self.election is not None:
            self.election.note_restart(idx)

    def addresses(self) -> list[tuple[str, int]]:
        return [s.address for s in self.servers]

    # -- runtime membership changes (README "Dynamic membership") --

    def _spawn_member(self) -> 'ZKServer':
        """Build one joining member exactly like a boot-time one: a
        fresh replica bootstraps from a live snapshot of the shared
        database (the attach-at-tail path — the ensemble has
        history), wired to the shared quorum gate."""
        idx = len(self.servers)
        s = ZKServer(self.db, host=self._host,
                     store=ReplicaStore(self.db, lag=self._lag),
                     watchtable=self._watchtable, member=str(idx),
                     transport=self._transport,
                     ingress_shards=self._ingress_shards,
                     blackbox_dir=self._blackbox_dir)
        if self.quorum.enabled:
            s.quorum = self.quorum
        if self.election is not None:
            el = self.election
            s.elections_ref = el
            s.fence = (lambda i=idx: i in el.deposed)
        self.servers.append(s)
        return s

    async def add_observer(self) -> int:
        """Observer JOIN under traffic: a new member starts serving a
        snapshot-bootstrapped replica, then a single final-phase
        reconfig record (no quorum implications) makes the join
        durable and visible — client resolvers rebalance on the
        config-change notification.  Returns the new index."""
        s = self._spawn_member()
        s.role = 'observer'
        idx = len(self.servers) - 1
        await s.start()
        self.observer_count += 1
        db = self.db
        db.propose_reconfig(db.voter_ids, db.observer_ids + (idx,))
        return idx

    async def remove_observer(self, idx: int) -> None:
        """Observer LEAVE: the reconfig record announces the removal
        first (resolvers rebalance away), then the member drains —
        open connections close, parking their in-flight read
        sessions for client-side migration — and its replica
        detaches from the commit feed."""
        s = self.servers[idx]
        if s.role != 'observer':
            raise ValueError('member %d is a voter' % (idx,))
        db = self.db
        if idx not in db.observer_ids:
            raise ValueError('member %d is not in the config'
                             % (idx,))
        db.propose_reconfig(
            db.voter_ids,
            tuple(i for i in db.observer_ids if i != idx))
        await s.stop()
        if isinstance(s.store, ReplicaStore):
            s.store.detach()
        self.observer_count -= 1

    async def reconfig_voters(self, new_voters,
                              observers=None) -> None:
        """Voter-set change with joint-majority handoff: the joint
        record installs C_old+C_new — from its append until the
        final record's, quorum commit and elections require
        majorities of BOTH sets, and a removed member can neither
        ack a quorum nor win a ballot (config-fenced).  A NEW voter
        index must already be a running member (``add_voter`` /
        ``replace_voter`` handle join-and-promote).  Leader
        self-removal is legal: the final record commits under the
        outgoing leader, which then hands off by election among
        C_new."""
        db = self.db
        new_voters = tuple(sorted(new_voters))
        obs = (tuple(observers) if observers is not None
               else tuple(i for i in db.observer_ids
                          if i not in new_voters))
        was_voters = db.voter_ids or ()
        gate = self.quorum
        # promote ack wiring FIRST: the joint record's own commit
        # needs C_new's majority to be audible
        for i in new_voters:
            if i == 0 or i in was_voters or i >= len(self.servers):
                continue
            s = self.servers[i]
            store = s.store
            if gate.enabled and isinstance(store, ReplicaStore) \
                    and store.on_applied is None:
                store.on_applied = (
                    lambda z, v='member:%d' % i:
                    gate.note_ack(v, z, self.db.epoch))
            s.role = 'follower'
        entry = db.propose_reconfig(new_voters, obs)
        if entry[2] == 'final':
            return
        if gate.enabled:
            await gate.wait(entry[6])
        final = db.commit_reconfig()
        if gate.enabled:
            await gate.wait(final[6])
        # demoted voters leave the ack wiring (the gate's config
        # fence already discards them) and serve on as observers
        for i in was_voters:
            if i in new_voters or i >= len(self.servers):
                continue
            s = self.servers[i]
            if isinstance(s.store, ReplicaStore):
                s.store.on_applied = None
            s.role = 'observer'
        if self.election is not None \
                and self.election.leader_idx not in new_voters:
            await self.election.elect('reconfig')

    async def add_voter(self) -> int:
        """Join-and-promote: start a fresh member (observer-style
        snapshot bootstrap), then widen the voter set through one
        joint window.  Returns the new member's index."""
        s = self._spawn_member()
        idx = len(self.servers) - 1
        await s.start()
        await self.reconfig_voters(self.db.voter_ids + (idx,))
        return idx

    async def remove_voter(self, idx: int) -> None:
        """Shrink the voter set through one joint window (leader
        self-removal included — see :meth:`reconfig_voters`)."""
        await self.reconfig_voters(
            tuple(i for i in self.db.voter_ids if i != idx))

    async def replace_voter(self, old_idx: int) -> int:
        """One joint window swaps a fresh member in for ``old_idx``
        — the add and the remove hand off atomically.  Returns the
        new member's index."""
        s = self._spawn_member()
        idx = len(self.servers) - 1
        await s.start()
        await self.reconfig_voters(
            tuple(i for i in self.db.voter_ids if i != old_idx)
            + (idx,))
        return idx
