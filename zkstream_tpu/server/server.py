"""The in-process asyncio ZooKeeper server.

Speaks the same wire protocol as the client through the symmetric
``PacketCodec(server=True)`` — the capability the reference's stream
codec advertises for building fake test servers
(reference: lib/zk-streams.js:28,70-71,84-85) but cannot actually
deliver (its reply encoder is missing).  This one is complete enough to
run the whole client test suite against: handshake with session
create/resume, the full request set, one-shot server-side watches with
correct locality, SET_WATCHES catch-up by relZxid, and session
migration between ensemble members.

``ZKEnsemble`` runs N servers over one shared ``ZKDatabase`` to simulate
a quorum on localhost (see store.py for why that is faithful enough for
the client-visible semantics).
"""

from __future__ import annotations

import asyncio
import logging

from ..protocol.consts import XID_NOTIFICATION, CreateFlag
from ..protocol.errors import ZKProtocolError
from ..protocol.framing import PacketCodec
from .store import ZKDatabase, ZKOpError, ZKServerSession

log = logging.getLogger('zkstream_tpu.server')


class ServerConnection:
    """One accepted client socket: handshake, request dispatch, and this
    connection's watch tables."""

    def __init__(self, server: 'ZKServer', reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.server = server
        self.db = server.db
        self.reader = reader
        self.writer = writer
        self.codec = PacketCodec(server=True)
        self.session: ZKServerSession | None = None
        #: One-shot watch tables, local to this connection (they die
        #: with the server, exactly like real ZK's).
        self.data_watches: dict[str, bool] = {}
        self.child_watches: dict[str, bool] = {}
        self.closed = False
        self._subscribed = False

    # -- wire helpers --

    def _write_bytes(self, data: bytes) -> None:
        if self.closed:
            return
        try:
            self.writer.write(data)
        except (ConnectionError, RuntimeError):
            pass

    def _send(self, pkt: dict) -> None:
        if self.closed:
            return
        self._write_bytes(self.codec.encode(pkt))

    def _reply(self, xid: int, opcode: str, err: str = 'OK',
               **body) -> None:
        if self.server.drop_replies:
            return
        if self.server.drop_pings and opcode == 'PING':
            return
        pkt = {'xid': xid, 'zxid': self.db.zxid, 'err': err,
               'opcode': opcode}
        pkt.update(body)
        self._send(pkt)

    def notify(self, ntype: str, path: str) -> None:
        """Send a watch notification; a fan-out (one db change, many
        subscribed connections) encodes the identical packet ONCE and
        shares the bytes — keyed by (type, path, zxid), which is unique
        per change since zxid strictly increases per mutation."""
        if self.closed:
            return
        key = (ntype, path, self.db.zxid)
        cache = self.server._notif_cache
        if cache is not None and cache[0] == key:
            data = cache[1]
        else:
            # Encode through the server-owned connection-independent
            # codec, not this connection's: the cached bytes are shared
            # with every subscribed connection, so they must not depend
            # on any per-connection encode state.
            data = self.server._notif_codec.encode(
                {'xid': XID_NOTIFICATION, 'zxid': self.db.zxid,
                 'err': 'OK', 'opcode': 'NOTIFICATION', 'type': ntype,
                 'state': 'SYNC_CONNECTED', 'path': path})
            self.server._notif_cache = (key, data)
        self._write_bytes(data)

    # -- watch dispatch (db change events -> this connection) --

    def _subscribe(self) -> None:
        if self._subscribed:
            return
        self._subscribed = True
        self.db.on('created', self._on_created)
        self.db.on('deleted', self._on_deleted)
        self.db.on('dataChanged', self._on_data_changed)
        self.db.on('childrenChanged', self._on_children_changed)
        self.db.on('sessionExpired', self._on_session_expired)

    def _unsubscribe(self) -> None:
        if not self._subscribed:
            return
        self._subscribed = False
        self.db.remove_listener('created', self._on_created)
        self.db.remove_listener('deleted', self._on_deleted)
        self.db.remove_listener('dataChanged', self._on_data_changed)
        self.db.remove_listener('childrenChanged',
                                self._on_children_changed)
        self.db.remove_listener('sessionExpired', self._on_session_expired)

    def _on_created(self, path: str, zxid: int) -> None:
        if self.data_watches.pop(path, None):
            self.notify('CREATED', path)

    def _on_deleted(self, path: str, zxid: int) -> None:
        if self.data_watches.pop(path, None):
            self.notify('DELETED', path)
        if self.child_watches.pop(path, None):
            self.notify('DELETED', path)

    def _on_data_changed(self, path: str, zxid: int) -> None:
        if self.data_watches.pop(path, None):
            self.notify('DATA_CHANGED', path)

    def _on_children_changed(self, path: str, zxid: int) -> None:
        if self.child_watches.pop(path, None):
            self.notify('CHILDREN_CHANGED', path)

    def _on_session_expired(self, session_id: int) -> None:
        if self.session is not None and self.session.id == session_id:
            self.close()

    # -- lifecycle --

    async def run(self) -> None:
        try:
            while not self.closed:
                data = await self.reader.read(65536)
                if not data:
                    break
                try:
                    pkts = self.codec.decode(data)
                except ZKProtocolError as e:
                    log.debug('server: undecodable input: %s', e)
                    break
                for pkt in pkts:
                    if self.codec.handshaking:
                        self._handle_connect(pkt)
                    else:
                        self._handle_request(pkt)
                    if self.closed:
                        break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            self.close()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self._unsubscribe()
        if self.session is not None and self.session.owner is self:
            self.session.owner = None
        self.server.conns.discard(self)
        try:
            self.writer.close()
        except (ConnectionError, RuntimeError):
            pass

    # -- handshake (session create / resume / migrate) --

    def _handle_connect(self, pkt: dict) -> None:
        timeout = pkt['timeOut']
        if pkt['sessionId'] == 0:
            sess = self.db.create_session(timeout)
        else:
            sess = self.db.resume_session(pkt['sessionId'], pkt['passwd'])
            if sess is None:
                # Unknown/expired session: zero id tells the client its
                # session is gone.
                self._send({'protocolVersion': 0, 'timeOut': timeout,
                            'sessionId': 0, 'passwd': b'\x00' * 16})
                self.codec.handshaking = False
                return
            # Session migration: drop the previous serving connection.
            if sess.owner is not None and sess.owner is not self:
                sess.owner.close()
        sess.owner = self
        self.session = sess
        self._send({'protocolVersion': 0, 'timeOut': sess.timeout,
                    'sessionId': sess.id, 'passwd': sess.passwd})
        self.codec.handshaking = False
        self._subscribe()

    # -- request dispatch --

    def _handle_request(self, pkt: dict) -> None:
        if self.session is None or self.session.expired:
            self._reply(pkt['xid'], pkt['opcode'], err='SESSION_EXPIRED')
            return
        self.db.touch_session(self.session)
        op = pkt['opcode']
        xid = pkt['xid']
        try:
            handler = getattr(self, '_op_' + op.lower(), None)
            if handler is None:
                self._reply(xid, op, err='UNIMPLEMENTED')
                return
            handler(pkt)
        except ZKOpError as e:
            # Failed reads with a watch flag still arm existence watches
            # where the protocol says so (handled inside the op); other
            # failures just carry the code.
            self._reply(xid, op, err=e.code)

    def _op_ping(self, pkt: dict) -> None:
        self._reply(pkt['xid'], 'PING')

    def _op_create(self, pkt: dict) -> None:
        path = self.db.create(pkt['path'], pkt['data'], pkt['acl'],
                              CreateFlag(pkt['flags']), self.session)
        self._reply(pkt['xid'], 'CREATE', path=path)

    def _op_delete(self, pkt: dict) -> None:
        self.db.delete(pkt['path'], pkt['version'])
        self._reply(pkt['xid'], 'DELETE')

    def _op_get_data(self, pkt: dict) -> None:
        try:
            data, stat = self.db.get_data(pkt['path'])
        except ZKOpError:
            raise
        if pkt.get('watch'):
            self.data_watches[pkt['path']] = True
        self._reply(pkt['xid'], 'GET_DATA', data=data, stat=stat)

    def _op_set_data(self, pkt: dict) -> None:
        stat = self.db.set_data(pkt['path'], pkt['data'], pkt['version'])
        self._reply(pkt['xid'], 'SET_DATA', stat=stat)

    def _op_exists(self, pkt: dict) -> None:
        try:
            stat = self.db.exists(pkt['path'])
        except ZKOpError:
            # EXISTS with watch on a missing node arms an existence
            # watch that fires CREATED later.
            if pkt.get('watch'):
                self.data_watches[pkt['path']] = True
            raise
        if pkt.get('watch'):
            self.data_watches[pkt['path']] = True
        self._reply(pkt['xid'], 'EXISTS', stat=stat)

    def _op_get_children(self, pkt: dict) -> None:
        children, stat = self.db.get_children(pkt['path'])
        if pkt.get('watch'):
            self.child_watches[pkt['path']] = True
        self._reply(pkt['xid'], 'GET_CHILDREN', children=children)

    def _op_get_children2(self, pkt: dict) -> None:
        children, stat = self.db.get_children(pkt['path'])
        if pkt.get('watch'):
            self.child_watches[pkt['path']] = True
        self._reply(pkt['xid'], 'GET_CHILDREN2', children=children,
                    stat=stat)

    def _op_get_acl(self, pkt: dict) -> None:
        acl, stat = self.db.get_acl(pkt['path'])
        self._reply(pkt['xid'], 'GET_ACL', acl=acl, stat=stat)

    def _op_sync(self, pkt: dict) -> None:
        # Single shared database: every server is trivially caught up.
        self._reply(pkt['xid'], 'SYNC')

    def _op_close_session(self, pkt: dict) -> None:
        self.db.close_session(self.session.id)
        self._reply(pkt['xid'], 'CLOSE_SESSION')
        self.close()

    def _op_set_watches(self, pkt: dict) -> None:
        """Re-arm watches after reconnect, sending catch-up
        notifications for anything that moved past relZxid."""
        rel = pkt['relZxid']
        events = pkt['events']
        for path in events.get('dataChanged', ()):
            node = self.db.nodes.get(path)
            if node is None:
                self.notify('DELETED', path)
            else:
                self.data_watches[path] = True
                if node.mzxid > rel:
                    self.data_watches.pop(path, None)
                    self.notify('DATA_CHANGED', path)
        for path in events.get('createdOrDestroyed', ()):
            node = self.db.nodes.get(path)
            if node is None:
                # Missing node: the watcher may have seen it alive, so
                # send DELETED (real ZK does the same for exist watches
                # — it cannot know the node never existed either).
                self.notify('DELETED', path)
            elif node.czxid > rel:
                self.notify('CREATED', path)
            else:
                self.data_watches[path] = True
        for path in events.get('childrenChanged', ()):
            node = self.db.nodes.get(path)
            if node is None:
                self.notify('DELETED', path)
            else:
                self.child_watches[path] = True
                if node.pzxid > rel:
                    self.child_watches.pop(path, None)
                    self.notify('CHILDREN_CHANGED', path)
        self._reply(pkt['xid'], 'SET_WATCHES')


class ZKServer:
    """One listening endpoint over a ZKDatabase."""

    def __init__(self, db: ZKDatabase | None = None,
                 host: str = '127.0.0.1', port: int = 0):
        self.db = db if db is not None else ZKDatabase()
        self.host = host
        self.port = port
        self._server: asyncio.base_events.Server | None = None
        self.conns: set[ServerConnection] = set()
        #: Fault-injection knobs for tests: swallow pings (forces the
        #: client's ping-timeout path) or swallow every reply (forces
        #: in-flight requests to hang until teardown).
        self.drop_pings = False
        self.drop_replies = False
        #: one-slot encode cache for notification fan-out
        #: ((type, path, zxid), wire bytes), filled via the dedicated
        #: connection-independent codec below (the bytes are shared
        #: across subscribers, so no per-connection codec may encode
        #: them)
        self._notif_cache: tuple[tuple, bytes] | None = None
        self._notif_codec = PacketCodec(server=True)
        self._notif_codec.handshaking = False

    async def start(self) -> 'ZKServer':
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info('ZK server listening on %s:%d', self.host, self.port)
        return self

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        conn = ServerConnection(self, reader, writer)
        self.conns.add(conn)
        await conn.run()

    async def stop(self) -> None:
        """Kill the server: stop listening and sever every connection.
        Sessions live in the database and keep their expiry clocks
        running — exactly what a crashed ensemble member looks like."""
        for conn in list(self.conns):
            conn.close()
        self.conns.clear()
        if self._server is not None:
            self._server.close()
            # In Python >= 3.12.1 wait_closed also waits for all client
            # handlers to return, so connections must be severed first.
            await self._server.wait_closed()
            self._server = None

    @property
    def address(self) -> tuple[str, int]:
        return (self.host, self.port)


class ZKEnsemble:
    """N servers over one shared database: localhost stand-in for a ZK
    quorum (reference analogue: test/multi-node.test.js's three real
    servers on distinct ports)."""

    def __init__(self, count: int = 3, host: str = '127.0.0.1'):
        self.db = ZKDatabase()
        self.servers = [ZKServer(self.db, host=host) for _ in range(count)]

    async def start(self) -> 'ZKEnsemble':
        for s in self.servers:
            await s.start()
        return self

    async def stop(self) -> None:
        for s in self.servers:
            await s.stop()

    async def kill(self, idx: int) -> None:
        await self.servers[idx].stop()

    async def restart(self, idx: int) -> None:
        """Bring a killed member back on its old port."""
        srv = self.servers[idx]
        assert srv._server is None, 'server still running'
        srv._server = await asyncio.start_server(
            srv._on_client, srv.host, srv.port)

    def addresses(self) -> list[tuple[str, int]]:
        return [s.address for s in self.servers]
